"""Durable session KV: park a finished turn's pages under its
``session_id`` so turn N+1 rebinds instead of re-prefilling
(docs/serving.md §Paged KV & prefix caching).

Warm sessions stay pinned in the device page pool (pure host
bookkeeping here — the pool holds the refcounts).  Cold sessions
(``session_ttl_seconds`` past their park time) and every warm session
at graceful drain are **spilled** to the host via the PR 2 atomic
protocol: stage the npz + meta under ``spill_dir/sess_<hash>/``, fsync,
write ``manifest.json`` last — so a crash mid-spill leaves either a
verifiable spill or recognisable garbage, never a half-trusted one.
``recover()`` re-registers every manifest-verified spill so a restarted
engine rebinds post-crash sessions exactly like warm ones.

bfloat16 leaves are stored as raw uint16 views (npz round-trips them
losslessly without depending on pickle support for ml_dtypes).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.resilience import atomic
from deepspeed_tpu.utils.logging import logger

_META_FILE = "meta.json"
_DATA_FILE = "kv.npz"


def session_dir_name(session_id: str) -> str:
    return "sess_" + hashlib.sha256(session_id.encode("utf-8")).hexdigest()[:16]


def pin_dir_name(tokens: "np.ndarray") -> str:
    """Entry dir for a pinned-prefix migration entry, keyed on the token
    run itself (pins have no session_id)."""
    digest = hashlib.sha256(np.asarray(tokens, np.int32).tobytes()).hexdigest()
    return "sess_pin_" + digest[:16]


def prefix_dir_name(tokens: "np.ndarray") -> str:
    """Entry dir for a tier-demoted (learned) prefix entry — distinct
    from pins so tier recovery can tell them apart by name alone."""
    digest = hashlib.sha256(np.asarray(tokens, np.int32).tobytes()).hexdigest()
    return "sess_pfx_" + digest[:16]


# -- migration wire format (docs/serving.md §Elastic fleet) ---------------
# One directory per entry, identical to the spill layout: kv.npz +
# meta.json staged first, manifest.json written LAST.  An export killed
# mid-write leaves a prefix of manifest-verified entries plus at most
# one unverifiable directory — the importer trusts exactly the verified
# subset, which is what makes kill -9 mid-migration lossless.

def write_entry(dest_dir: str, dir_name: str, meta: Dict,
                leaves: Dict[str, np.ndarray]) -> str:
    """Write one spill-format entry under ``dest_dir/dir_name`` (data +
    meta fsynced, manifest last).  Idempotent: a stale manifest from a
    prior attempt is invalidated before the data is rewritten, so a
    retried export can overwrite its own partial output safely."""
    target = os.path.join(dest_dir, dir_name)
    os.makedirs(target, exist_ok=True)
    stale = os.path.join(target, atomic.MANIFEST_FILE)
    if os.path.exists(stale):
        os.remove(stale)
    dtypes = _save_leaves(leaves, os.path.join(target, _DATA_FILE))
    meta = dict(meta)
    meta["leaf_dtypes"] = dtypes
    atomic.atomic_write_text(os.path.join(target, _META_FILE), json.dumps(meta))
    atomic.write_manifest(target)
    return target


def read_entry(target: str) -> Optional[Tuple[Dict, Dict[str, np.ndarray]]]:
    """One manifest-verified entry as ``(meta, leaves)``; None when the
    directory is unverifiable (export killed mid-write) — never trusted,
    never fatal."""
    ok, _ = atomic.verify_manifest(target)
    meta_path = os.path.join(target, _META_FILE)
    if not ok or not os.path.exists(meta_path):
        logger.warning(
            f"kvcache: ignoring unverifiable migration entry at {target}"
        )
        return None
    with open(meta_path) as f:
        meta = json.load(f)
    leaves = _load_leaves(os.path.join(target, _DATA_FILE), meta["leaf_dtypes"])
    return meta, leaves


def read_entries(src_dir: str) -> List[Tuple[Dict, Dict[str, np.ndarray]]]:
    """Every manifest-verified entry under ``src_dir`` as ``(meta,
    leaves)`` pairs, sorted by directory name."""
    out: List[Tuple[Dict, Dict[str, np.ndarray]]] = []
    if not os.path.isdir(src_dir):
        return out
    for name in sorted(os.listdir(src_dir)):
        target = os.path.join(src_dir, name)
        if not (name.startswith("sess_") and os.path.isdir(target)):
            continue
        loaded = read_entry(target)
        if loaded is not None:
            out.append(loaded)
    return out


@dataclasses.dataclass
class Session:
    """One warm parked session: the token history whose KV the pages
    hold, and the device pages themselves (refcounts held by the pool
    on this session's behalf)."""

    session_id: str
    tokens: np.ndarray  # (cached_len,) int32 — prompt + generated[:-1]
    pages: List[int]
    parked_at: float = 0.0

    @property
    def cached_len(self) -> int:
        return int(self.tokens.shape[0])


def _save_leaves(leaves: Dict[str, np.ndarray], path: str) -> Dict[str, str]:
    """npz-save ``leaves``; bfloat16 goes in as a uint16 view.  Returns
    the key -> original-dtype map for the meta file."""
    dtypes: Dict[str, str] = {}
    packed: Dict[str, np.ndarray] = {}
    for key, arr in leaves.items():
        arr = np.asarray(arr)
        dtypes[key] = str(arr.dtype)
        packed[key] = arr.view(np.uint16) if arr.dtype.name == "bfloat16" else arr
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **packed)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return dtypes


def _load_leaves(path: str, dtypes: Dict[str, str]) -> Dict[str, np.ndarray]:
    import ml_dtypes  # baked into the jax toolchain

    out: Dict[str, np.ndarray] = {}
    with np.load(path) as z:
        for key, dtype in dtypes.items():
            arr = z[key]
            if dtype == "bfloat16":
                arr = arr.view(ml_dtypes.bfloat16)
            out[key] = arr
    return out


# the tier manager stages its own T2 entries (it needs a fault site
# between the staged payload and the manifest), so the leaf codec and
# file names are part of this module's public surface
save_leaves = _save_leaves
load_leaves = _load_leaves
META_FILE = _META_FILE
DATA_FILE = _DATA_FILE


class SessionStore:
    """Warm (in-pool) + spilled (host) session registry.  The store
    never touches device memory itself: the pool passes host leaf dicts
    in for :meth:`spill` and gets them back from :meth:`load`."""

    def __init__(self, spill_dir: Optional[str] = None,
                 ttl_seconds: float = 0.0):
        self.spill_dir = spill_dir
        self.ttl_seconds = float(ttl_seconds)
        self._warm: Dict[str, Session] = {}
        self._spilled: Dict[str, str] = {}  # session_id -> verified dir
        self.parks = 0
        self.spills = 0
        self.restores = 0
        self.drops = 0

    # -- warm path --------------------------------------------------------
    def park(self, sess: Session) -> Optional[Session]:
        """Register a warm session; returns the *displaced* session for
        the same id (whose pages the pool must release), if any."""
        prev = self._warm.pop(sess.session_id, None)
        # a fresh park supersedes any stale spill of the same session
        self._spilled.pop(sess.session_id, None)
        self._warm[sess.session_id] = sess
        self.parks += 1
        return prev

    def peek(self, session_id: str) -> Optional[Session]:
        return self._warm.get(session_id)

    def is_spilled(self, session_id: str) -> bool:
        return session_id in self._spilled

    def pop_warm(self, session_id: str) -> Optional[Session]:
        return self._warm.pop(session_id, None)

    def warm(self) -> List[Session]:
        return list(self._warm.values())

    def expired(self, now: float) -> List[Session]:
        if self.ttl_seconds <= 0:
            return []
        return [
            s for s in self._warm.values()
            if now - s.parked_at > self.ttl_seconds
        ]

    def drop(self, session_id: str) -> Optional[Session]:
        self.drops += 1
        self._spilled.pop(session_id, None)
        return self._warm.pop(session_id, None)

    # -- spill / restore --------------------------------------------------
    def spill(self, sess: Session, leaves: Dict[str, np.ndarray]) -> str:
        """Atomically persist a session's host-gathered KV leaves.
        Stage data + meta, fsync, manifest LAST — only a directory whose
        manifest verifies is ever trusted by :meth:`recover`."""
        if self.spill_dir is None:
            raise ValueError("session spill requested without a spill_dir")
        target = os.path.join(self.spill_dir, session_dir_name(sess.session_id))
        os.makedirs(target, exist_ok=True)
        stale = os.path.join(target, atomic.MANIFEST_FILE)
        if os.path.exists(stale):
            os.remove(stale)  # re-spill: invalidate before rewriting data
        dtypes = _save_leaves(leaves, os.path.join(target, _DATA_FILE))
        atomic.atomic_write_text(
            os.path.join(target, _META_FILE),
            json.dumps({
                "session_id": sess.session_id,
                "tokens": [int(t) for t in sess.tokens],
                "parked_at": sess.parked_at,
                "leaf_dtypes": dtypes,
            }),
        )
        atomic.write_manifest(target)
        self._warm.pop(sess.session_id, None)
        self._spilled[sess.session_id] = target
        self.spills += 1
        return target

    def spilled_ids(self) -> List[str]:
        return sorted(self._spilled)

    def spilled_dir(self, session_id: str) -> Optional[str]:
        """The registered spill directory for ``session_id`` (export
        reads it directly — unlike :meth:`load`, nothing is consumed)."""
        return self._spilled.get(session_id)

    def adopt_spill(self, session_id: str, meta: Dict,
                    leaves: Dict[str, np.ndarray]) -> Optional[str]:
        """Persist an imported (migrated) session straight into this
        store's own ``spill_dir`` and register it — the landing path for
        migrated sessions when the survivor pool has no free pages.
        Returns None (entry dropped) without a spill_dir."""
        if self.spill_dir is None:
            return None
        meta = {k: v for k, v in meta.items() if k != "leaf_dtypes"}
        meta["session_id"] = session_id
        target = write_entry(self.spill_dir, session_dir_name(session_id),
                             meta, leaves)
        self._warm.pop(session_id, None)
        self._spilled[session_id] = target
        self.spills += 1
        return target

    def has(self, session_id: str) -> bool:
        return session_id in self._warm or session_id in self._spilled

    def load(self, session_id: str) -> Optional[Tuple[Session, Dict[str, np.ndarray]]]:
        """Read a spilled session back (host leaves; the pool re-pages
        them).  The spill entry is consumed — a later park re-persists."""
        target = self._spilled.get(session_id)
        if target is None:
            return None
        ok, notes = atomic.verify_manifest(target)
        if not ok:
            logger.warning(
                f"kvcache: spilled session {session_id!r} failed manifest "
                f"verification ({'; '.join(notes)}); dropping it"
            )
            self._spilled.pop(session_id, None)
            return None
        with open(os.path.join(target, _META_FILE)) as f:
            meta = json.load(f)
        leaves = _load_leaves(os.path.join(target, _DATA_FILE), meta["leaf_dtypes"])
        sess = Session(
            session_id=meta["session_id"],
            tokens=np.asarray(meta["tokens"], np.int32),
            pages=[],
            parked_at=float(meta.get("parked_at", 0.0)),
        )
        self._spilled.pop(session_id, None)
        self.restores += 1
        return sess, leaves

    # -- crash recovery ---------------------------------------------------
    def recover(self) -> List[str]:
        """Scan ``spill_dir`` and re-register every manifest-verified
        session spill.  Unverifiable directories (crash mid-spill before
        the manifest rename) are left on disk but never trusted."""
        if self.spill_dir is None or not os.path.isdir(self.spill_dir):
            return []
        found: List[str] = []
        for name in sorted(os.listdir(self.spill_dir)):
            target = os.path.join(self.spill_dir, name)
            if not (name.startswith("sess_") and os.path.isdir(target)):
                continue
            ok, _ = atomic.verify_manifest(target)
            meta_path = os.path.join(target, _META_FILE)
            if not ok or not os.path.exists(meta_path):
                logger.warning(
                    f"kvcache: ignoring unverifiable session spill at {target}"
                )
                continue
            with open(meta_path) as f:
                sid = json.load(f)["session_id"]
            self._spilled[sid] = target
            found.append(sid)
        return found

    def stats(self) -> Dict[str, int]:
        return {
            "warm": len(self._warm),
            "spilled": len(self._spilled),
            "parks": self.parks,
            "spills": self.spills,
            "restores": self.restores,
            "drops": self.drops,
        }
