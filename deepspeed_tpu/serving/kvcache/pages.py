"""Paged KV pool: a fixed-shape device page pool + host-side page
allocator, prefix index, and session store (docs/serving.md §Paged KV
& prefix caching).

Layout: ONE pair of ``(layers, num_pages, heads, page_len, head_dim)``
cache buffers (bf16/f32, or the int8 code+scale pair) whose **page axis
replaces the slot axis** of :class:`~deepspeed_tpu.serving.pool.SlotKVPool`.
Every logical slot is a row of ``pages_per_slot = max_len // page_len``
page ids (``self._tables``) the serving executables consume as a traced
int32 array — so admitting, retiring, sharing, or remapping pages never
changes an abstract signature and the exactly-two-executables contract
survives untouched.

**Page 0 is the reserved garbage page**: unused table entries point at
it, and the decode step's per-slot ``write_mask`` redirects the writes
of non-decoding slots there.  Reads of page 0 are always behind the
position mask; writes to it are by definition discardable.  This is the
paged analogue of the slot pool's overwrite-before-attend invariant.

Sharing is refcounted: the prefix index holds one reference per cached
prefix, each slot holds one per mapped page, and a parked session holds
one per kept page.  A page returns to the free list only at refcount
zero.  A slot may write a page only while it is the sole holder — a
partially-filled shared tail page is **copied-on-write** into a private
page (the copy rides the slot's first prefill chunk as a traced
``(src, dst)`` pair; ``src == dst == 0`` is the identity no-op).
"""
from __future__ import annotations

import functools
import math
import os
import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.serving.kvcache.prefix import PrefixEntry, PrefixIndex
from deepspeed_tpu.serving.kvcache.sessions import (
    Session,
    SessionStore,
    pin_dir_name,
    read_entries,
    read_entry,
    session_dir_name,
    write_entry,
)
from deepspeed_tpu.serving.pool import SlotPoolError
from deepspeed_tpu.utils.logging import logger

GARBAGE_PAGE = 0


def _pages_for(tokens: int, page_len: int) -> int:
    return -(-int(tokens) // int(page_len))


def _locked(fn):
    """Run the method under the pool's re-entrant lock.  The allocator
    state (refcounts, free lists, tables, prefix index, sessions) is one
    invariant-coupled unit: the serving engine, a background TTL sweep,
    and the upcoming elastic-fleet KV migration all mutate it, and a
    context switch between a decref and its free-list append double-
    frees pages.  RLock because the surface nests (``free`` ->
    ``retire``); uncontended re-entrant acquisition is tens of
    nanoseconds — invisible next to the numpy work per call."""
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)
    return wrapper


class PagedKVPool:
    """Fixed-shape device page pool + host-side allocator with
    shared-prefix dedup, copy-on-write, and durable sessions.

    Duck-compatible with :class:`SlotKVPool` where the scheduler and
    engine touch it (``free_slots`` / ``alloc`` / ``free`` / ``swap`` /
    ``cache_bytes`` / ``shape_math``); the paged extras
    (:meth:`alloc_request`, :meth:`retire`, :meth:`learn_prefix`,
    :meth:`consume_cow`, :meth:`table`) are discovered by ``getattr``
    so the slot pool keeps working unchanged.
    """

    def __init__(self, n_layer: int, num_slots: int, heads: int, max_len: int,
                 head_dim: int, kv_dtype: Any, page_len: int = 128,
                 num_pages: Optional[int] = None, sharding: Any = None,
                 prefill_chunk: int = 1,
                 pinned_prefixes: Sequence[Sequence[int]] = (),
                 session_ttl_seconds: float = 0.0,
                 spill_dir: Optional[str] = None):
        from deepspeed_tpu.ops.transformer.inference import init_kv_cache

        if num_slots < 1:
            raise SlotPoolError(f"num_slots must be >= 1, got {num_slots}")
        if page_len < 1:
            raise SlotPoolError(f"page_len must be >= 1, got {page_len}")
        if max_len < 1 or max_len % page_len != 0:
            raise SlotPoolError(
                f"max_len must be a positive multiple of page_len, got "
                f"max_len={max_len} page_len={page_len}"
            )
        self.n_layer = int(n_layer)
        self.num_slots = int(num_slots)
        self.heads = int(heads)
        self.max_len = int(max_len)
        self.head_dim = int(head_dim)
        self.kv_dtype = kv_dtype
        self.page_len = int(page_len)
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.pages_per_slot = self.max_len // self.page_len
        full = self.num_slots * self.pages_per_slot
        # default: every slot fully mappable plus an equal share of
        # pages for the prefix index and parked sessions, + garbage page
        self.num_pages = int(num_pages) if num_pages else 1 + 2 * full
        if self.num_pages < 1 + self.pages_per_slot:
            raise SlotPoolError(
                f"num_pages={self.num_pages} cannot map even one slot "
                f"({self.pages_per_slot} pages + the reserved garbage page)"
            )
        if self.num_pages < 1 + full:
            logger.warning(
                f"kvcache: num_pages={self.num_pages} < 1 + "
                f"{self.num_slots} slots x {self.pages_per_slot} pages — "
                f"a full pool of cache misses will wait on page churn"
            )
        self.k, self.v = init_kv_cache(
            n_layer, self.num_pages, heads, self.page_len, head_dim, kv_dtype
        )
        if sharding is not None:
            self.k, self.v = jax.device_put((self.k, self.v), sharding)
        # host-side allocator state (every public touch goes through
        # @_locked — see the decorator's docstring)
        self._lock = threading.RLock()
        self._free_pages: Deque[int] = deque(range(1, self.num_pages))
        self._ref = np.zeros((self.num_pages,), np.int64)
        self._ref[GARBAGE_PAGE] = 1  # permanently held
        self._free_slots: Deque[int] = deque(range(self.num_slots))
        self._owner: Dict[int, Any] = {}  # slot -> request id
        self._tables = np.zeros((self.num_slots, self.pages_per_slot), np.int32)
        self._slot_pages: Dict[int, List[int]] = {}
        self._pending_cow: Dict[int, Tuple[int, int]] = {}
        self.index = PrefixIndex()
        self.sessions = SessionStore(spill_dir=spill_dir,
                                     ttl_seconds=session_ttl_seconds)
        # optional hierarchical tiering (attach_tiers); when armed,
        # session spill/drop and cold prefix eviction route through the
        # PageTierManager instead of dying or hitting spill_dir directly
        self.tiers: Optional[Any] = None
        self._pinned_specs: List[np.ndarray] = [
            np.asarray(list(spec), np.int32) for spec in pinned_prefixes
            if len(list(spec)) >= 1
        ]
        # counters (kvcache/* telemetry reads these)
        self.lookups = 0
        self.hits = 0
        self.misses = 0
        self.tokens_saved = 0
        self.cow_copies = 0
        self.evictions = 0
        self.session_rebinds = 0
        self.alloc_waits = 0  # alloc_request returned None for lack of pages
        # per-tenant quota enforcement (docs/serving.md §Front-door):
        # armed via attach_tenants().  Charges follow the live slot —
        # fresh pages claimed for a tenant's request count against its
        # kv_pages_max until the slot retires; pinned-prefix inserts
        # count against pinned_prefixes_max (over-quota pins degrade to
        # unpinned entries, which pressure reclaim may evict).
        self.tenants: Optional[Any] = None
        self._tenant_pages: Dict[str, int] = {}
        self._slot_tenant: Dict[int, Tuple[str, int]] = {}
        self._tenant_pinned: Dict[str, int] = {}
        self.tenant_quota_defers = 0
        self.tenant_pin_rejects = 0

    # -- refcounting ------------------------------------------------------
    def _page_incref(self, pages: Sequence[int]) -> None:
        for p in pages:
            self._ref[p] += 1

    def _page_decref(self, pages: Sequence[int]) -> None:
        for p in pages:
            if p == GARBAGE_PAGE:
                raise SlotPoolError("refcount underflow on the garbage page")
            self._ref[p] -= 1
            if self._ref[p] < 0:
                raise SlotPoolError(f"page {p} refcount underflow")
            if self._ref[p] == 0:
                self._free_pages.append(p)

    def _take_pages(self, n: int, now: float = 0.0) -> Optional[List[int]]:
        """Claim ``n`` fresh pages at refcount 1, reclaiming cold state
        under pressure; None when the pool genuinely cannot satisfy."""
        if n == 0:
            return []
        if len(self._free_pages) < n:
            self._reclaim(n, now)
        if len(self._free_pages) < n:
            return None
        out = [self._free_pages.popleft() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        return out

    def _reclaim(self, need: int, now: float) -> None:
        """Free pages by retiring cold state, cheapest first: expired
        sessions (spill keeps them recoverable), then unpinned prefix
        entries coldest-first.  Pages still mapped by live slots are
        never touched — decref only returns sole-holder pages."""
        for sess in self.sessions.expired(now):
            self._spill_or_drop(sess)
            if len(self._free_pages) >= need:
                return
        for entry in self.index.evict_candidates():
            if len(self._free_pages) >= need:
                return
            self.index.remove(entry)
            self._page_decref(entry.pages)
            self.evictions += 1
        if len(self._free_pages) < need:
            for sess in sorted(self.sessions.warm(), key=lambda s: s.parked_at):
                if len(self._free_pages) >= need:
                    return
                self._spill_or_drop(sess)

    # -- SlotKVPool-compatible surface ------------------------------------
    @property
    def free_slots(self) -> int:
        return len(self._free_slots)

    @property
    def live_slots(self) -> int:
        return self.num_slots - len(self._free_slots)

    @property
    def pages_free(self) -> int:
        return len(self._free_pages)

    @property
    def pages_live(self) -> int:
        return self.num_pages - 1 - len(self._free_pages)

    def owner(self, slot: int) -> Optional[Any]:
        return self._owner.get(slot)

    def owners(self) -> Dict[int, Any]:
        return dict(self._owner)

    @_locked
    def alloc(self, request_id: Any) -> Optional[int]:
        """Plain slot claim (no request context): a fully-mapped slot
        with fresh private pages and no prefix/session reuse."""
        if request_id in self._owner.values():
            raise SlotPoolError(
                f"request {request_id!r} already owns a slot"
            )
        if not self._free_slots:
            return None
        pages = self._take_pages(self.pages_per_slot)
        if pages is None:
            self.alloc_waits += 1
            return None
        slot = self._free_slots.popleft()
        self._owner[slot] = request_id
        self._bind(slot, pages, cow=None)
        return slot

    @_locked
    def free(self, slot: int) -> None:
        self.retire(slot, None)

    def swap(self, k, v) -> None:
        self.k, self.v = k, v

    def cache_bytes(self) -> int:
        return int(
            sum(l.size * l.dtype.itemsize for l in jax.tree.leaves((self.k, self.v)))
        )

    def shape_math(self) -> str:
        kind = "int8+f32 scales" if isinstance(self.k, dict) else str(np.dtype(
            jax.tree.leaves(self.k)[0].dtype))
        return (
            f"2 x ({self.n_layer} layers x {self.num_pages} pages x "
            f"{self.heads} heads x {self.page_len} page_len x "
            f"{self.head_dim} head_dim) [{kind}] = "
            f"{self.cache_bytes() / 1e6:.1f} MB "
            f"({self.num_slots} slots x {self.pages_per_slot} pages/slot)"
        )

    # -- paged allocation -------------------------------------------------
    def _aligned_hit(self, cached: int, prompt_len: int) -> int:
        """Usable prefix hit: capped at ``prompt_len - 1`` (at least one
        chunk must run to produce the first-token logits) and rounded
        down to a prefill-chunk multiple (prefill restarts exactly on a
        chunk boundary, so the chunked numerics — and the admission
        TTFT math — stay identical to the cold path)."""
        hit = min(int(cached), int(prompt_len) - 1)
        hit -= hit % self.prefill_chunk
        return max(hit, 0)

    def _match_session(self, session_id: str, prompt: np.ndarray,
                       now: float) -> Optional[Session]:
        sess = self.sessions.peek(session_id)
        if sess is None and self.sessions.is_spilled(session_id):
            sess = self._restore_session(session_id, now)
        if sess is None and self.tiers is not None:
            sess = self.tiers.promote_session(session_id, now)
        if sess is None:
            return None
        cl = sess.cached_len
        if cl > prompt.shape[0] or not np.array_equal(sess.tokens, prompt[:cl]):
            return None  # divergent history: leave parked for the TTL sweep
        if self.tiers is not None and not self.tiers.promote_tail(sess, now):
            # the tier-held tail cannot be paged back in: give the
            # session up and re-prefill (rebind is only an optimisation)
            self.tiers.drop_session(sess)
            return None
        return sess

    @_locked
    def alloc_request(self, req: Any, now: float = 0.0) -> Optional[int]:
        """Hit-aware slot claim.  Resolves the request's longest cached
        prefix (session rebind first — it covers prior turns' generation
        — then the prefix index), maps reused pages read-only with a COW
        pair for a partial shared tail, claims fresh pages for the rest,
        and sets ``req.prefill_pos`` / ``req.prefix_hint`` so chunked
        prefill starts at the first uncached chunk boundary.  None when
        out of slots *or* pages (the request waits queued)."""
        if not self._free_slots:
            return None
        rid = req.request_id
        if rid in self._owner.values():
            raise SlotPoolError(f"request {rid!r} already owns a slot")
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        plen = int(prompt.shape[0])
        self.lookups += 1
        sid = getattr(req, "session_id", None)
        source, sess, entry, hit = None, None, None, 0
        if sid is not None:
            sess = self._match_session(sid, prompt, now)
            if sess is not None:
                hit = self._aligned_hit(sess.cached_len, plen)
                source = "session" if hit > 0 else None
        if source is None:
            entry = self.index.lookup(prompt, now=now)
            if self.tiers is not None:
                best = entry.length if entry is not None else 0
                if self.tiers.promote_prefix_for(prompt, now, min_len=best):
                    entry = self.index.lookup(prompt, now=now) or entry
            if entry is not None:
                hit = self._aligned_hit(entry.length, plen)
                source = "prefix" if hit > 0 else None
        if source is None:
            hit = 0
        src_pages = (sess.pages if source == "session"
                     else entry.pages if source == "prefix" else [])
        n_cover = _pages_for(hit, self.page_len) if hit else 0
        reuse = list(src_pages[:n_cover])
        tail_partial = hit % self.page_len != 0 and bool(reuse)
        # the slot may write the tail page only as its sole holder;
        # after the transfer below its refcount is current + 1 (slot)
        # - 1 (a consumed session's hold)
        need_cow = tail_partial and (
            int(self._ref[reuse[-1]]) + 1 - (1 if source == "session" else 0) > 1
        )
        total = min(plen + int(req.max_new_tokens), self.max_len)
        need = max(_pages_for(total, self.page_len), n_cover)
        # per-tenant page quota: fresh (privately-charged) pages for
        # this slot must fit under the tenant's cap — reused shared
        # pages are free (they are not attributable to one tenant).
        # Over quota the request WAITS (None), exactly like page
        # starvation: the tenant's own retirements free its budget, and
        # other tenants are unaffected — which is the point.
        tenant_name, n_fresh_planned = None, 0
        if self.tenants is not None:
            tenant_name = getattr(req, "tenant", None)
            cap = self.tenants.kv_pages_max(tenant_name)
            n_fresh_planned = need - n_cover + (1 if need_cow else 0)
            from deepspeed_tpu.serving.frontdoor.tenants import DEFAULT_TENANT

            key = tenant_name or DEFAULT_TENANT
            if cap > 0 and self._tenant_pages.get(key, 0) + n_fresh_planned > cap:
                self.tenant_quota_defers += 1
                self.tenants.note_quota_defer(tenant_name)
                return None
        # the slot takes its reference on every reused page BEFORE
        # claiming fresh ones: _take_pages may reclaim under pressure,
        # and reclaim is allowed to spill/demote the very session (or
        # evict the very prefix entry) this rebind is consuming — the
        # early incref keeps the reused pages (and their KV) live
        # through that
        self._page_incref(reuse)
        fresh = self._take_pages(need - n_cover + (1 if need_cow else 0), now)
        if fresh is None:
            self._page_decref(reuse)
            self.alloc_waits += 1
            return None
        if source == "session":
            # a consumed session releases all of its holds (tail pages
            # beyond the cover free here unless shared); when reclaim
            # spilled/demoted it mid-_take_pages its holds are already
            # released and the off-pool copy goes stale — harmless, a
            # later park for the sid supersedes it
            consumed = self.sessions.pop_warm(sid)
            if consumed is not None:
                self._page_decref(consumed.pages)
            self.session_rebinds += 1
        mapping = list(reuse)
        cow: Optional[Tuple[int, int]] = None
        if need_cow:
            cow = (mapping[-1], fresh[0])
            self._page_decref([mapping[-1]])  # slot abandons src for dst
            mapping[-1] = fresh[0]
            mapping.extend(fresh[1:])
            self.cow_copies += 1
        else:
            mapping.extend(fresh)
        slot = self._free_slots.popleft()
        self._owner[slot] = rid
        self._bind(slot, mapping, cow)
        if self.tenants is not None:
            from deepspeed_tpu.serving.frontdoor.tenants import DEFAULT_TENANT

            key = tenant_name or DEFAULT_TENANT
            n_charged = len(fresh)
            self._tenant_pages[key] = self._tenant_pages.get(key, 0) + n_charged
            self._slot_tenant[slot] = (key, n_charged)
        req.prefill_pos = hit
        req.prefix_hint = hit
        if hit > 0:
            self.hits += 1
            self.tokens_saved += hit
        else:
            self.misses += 1
        return slot

    def _bind(self, slot: int, pages: List[int],
              cow: Optional[Tuple[int, int]]) -> None:
        row = np.zeros((self.pages_per_slot,), np.int32)
        row[: len(pages)] = pages
        self._tables[slot] = row
        self._slot_pages[slot] = pages
        if cow is not None:
            self._pending_cow[slot] = cow

    @_locked
    def consume_cow(self, slot: int) -> Tuple[int, int]:
        """The slot's pending copy-on-write pair, consumed — staged into
        its FIRST prefill chunk.  ``(0, 0)`` (garbage page onto itself)
        is the traced identity when nothing is pending."""
        return self._pending_cow.pop(slot, (GARBAGE_PAGE, GARBAGE_PAGE))

    @_locked
    def table(self, slot: int) -> np.ndarray:
        return self._tables[slot].copy()

    @_locked
    def tables(self) -> np.ndarray:
        return self._tables.copy()

    # -- prefix learning --------------------------------------------------
    @_locked
    def learn_prefix(self, req: Any, now: float = 0.0) -> None:
        """Called once per request when its final prefill chunk has
        landed: the slot's pages now hold KV for the whole prompt, so
        the prompt becomes a cached prefix (and any configured pinned
        spec it extends is seeded, pinned).  The index takes its own
        reference on every covered page; the live owner keeps appending
        to the shared tail page — safe, because it only ever writes
        positions >= the entry length, and readers COW first."""
        pages = self._slot_pages.get(req.slot)
        if pages is None:
            return
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        # the run this prompt shares with previously-learned traffic
        # (computed BEFORE inserting the prompt itself): learned as its
        # own entry so a common system prompt becomes a reusable prefix
        # even though no single full prompt is a prefix of another
        split = self.index.common_prefix_len(prompt)
        for spec in self._pinned_specs:
            L = int(spec.shape[0])
            if L <= prompt.shape[0] and np.array_equal(prompt[:L], spec):
                self._insert_entry(spec.copy(), pages, pinned=True, now=now,
                                   tenant=getattr(req, "tenant", None))
        if self.prefill_chunk <= split < prompt.shape[0]:
            self._insert_entry(prompt[:split].copy(), pages, pinned=False, now=now)
        self._insert_entry(prompt.copy(), pages, pinned=False, now=now)

    def _insert_entry(self, tokens: np.ndarray, pages: List[int],
                      pinned: bool, now: float,
                      tenant: Optional[str] = None) -> None:
        if pinned and self.tenants is not None:
            # per-tenant pinned-prefix quota: an over-quota pin degrades
            # to a plain (evictable) entry instead of pinning — the
            # tenant keeps the cache benefit but cannot exempt unbounded
            # pages from pressure reclaim
            from deepspeed_tpu.serving.frontdoor.tenants import DEFAULT_TENANT

            cap = self.tenants.pinned_prefixes_max(tenant)
            key = tenant or DEFAULT_TENANT
            if cap > 0 and self._tenant_pinned.get(key, 0) >= cap:
                self.tenant_pin_rejects += 1
                pinned = False
        cover = pages[: _pages_for(tokens.shape[0], self.page_len)]
        entry = PrefixEntry(tokens=tokens, pages=list(cover), pinned=pinned,
                            last_used=now)
        inserted = self.index.insert(entry)
        newly_pinned = False
        if inserted is entry:
            self._page_incref(cover)
            newly_pinned = pinned
        elif pinned and not inserted.pinned:
            inserted.pinned = True  # a learned entry graduates to pinned
            newly_pinned = True
        if newly_pinned and self.tenants is not None:
            from deepspeed_tpu.serving.frontdoor.tenants import DEFAULT_TENANT

            key = tenant or DEFAULT_TENANT
            self._tenant_pinned[key] = self._tenant_pinned.get(key, 0) + 1

    @_locked
    def prefix_hint_tokens(self, prompt: np.ndarray,
                           session_id: Optional[str] = None) -> int:
        """Expected hit for a prompt *without* touching any state — the
        admission controller prices queued work with this so TTFT
        estimates use the post-hit budget."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = int(prompt.shape[0])
        if plen < 2:
            return 0
        if session_id is not None:
            sess = self.sessions.peek(session_id)
            if (sess is not None and sess.cached_len <= plen
                    and np.array_equal(sess.tokens, prompt[: sess.cached_len])):
                return self._aligned_hit(sess.cached_len, plen)
            if self.tiers is not None:
                cl, _tier = self.tiers.session_hint(prompt, session_id)
                if cl:
                    # a tiered session promotes on demand at alloc, so
                    # the expected hit is as real as a warm one
                    return self._aligned_hit(cl, plen)
        entry = self.index.lookup(prompt, stamp=False)
        best = self._aligned_hit(entry.length, plen) if entry is not None else 0
        if self.tiers is not None:
            tl, _tier = self.tiers.prefix_hint(prompt)
            if tl:
                best = max(best, self._aligned_hit(tl, plen))
        return best

    # residency-discount weights for fleet affinity pricing: reused
    # tokens are worth less when promoting them first costs a host
    # scatter (T1) or a disk read + scatter (T2)
    _TIER_WEIGHTS = {"": 1.0, "host": 0.75, "disk": 0.5}

    @_locked
    def affinity_tokens(self, prompt: np.ndarray,
                        session_id: Optional[str] = None) -> float:
        """Tier-aware :meth:`prefix_hint_tokens` for fleet routing:
        cached tokens discounted by residency (T0 full, T1 3/4, T2 1/2)
        so a session parked in host memory still beats a cold replica
        but loses to a replica holding it in HBM."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = int(prompt.shape[0])
        if plen < 2:
            return 0.0
        best = 0.0
        if session_id is not None:
            sess = self.sessions.peek(session_id)
            if (sess is not None and sess.cached_len <= plen
                    and np.array_equal(sess.tokens, prompt[: sess.cached_len])):
                best = float(self._aligned_hit(sess.cached_len, plen))
            elif self.tiers is not None:
                cl, tier = self.tiers.session_hint(prompt, session_id)
                if cl:
                    best = (self._aligned_hit(cl, plen)
                            * self._TIER_WEIGHTS.get(tier, 0.5))
        entry = self.index.lookup(prompt, stamp=False)
        if entry is not None:
            best = max(best, float(self._aligned_hit(entry.length, plen)))
        if self.tiers is not None:
            tl, tier = self.tiers.prefix_hint(prompt)
            if tl:
                best = max(best, self._aligned_hit(tl, plen)
                           * self._TIER_WEIGHTS.get(tier, 0.5))
        return best

    # -- retirement / sessions --------------------------------------------
    @_locked
    def retire(self, slot: int, req: Any = None, now: float = 0.0) -> None:
        """Return a slot.  A finished request with a ``session_id``
        parks the pages holding its turn (prompt + generated[:-1] — the
        last token was never fed, so it has no KV) under the session;
        everything else is dereferenced, freeing sole-holder pages."""
        if slot not in self._owner:
            raise SlotPoolError(f"slot {slot} is not allocated")
        del self._owner[slot]
        charged = self._slot_tenant.pop(slot, None)
        if charged is not None:
            key, n_charged = charged
            left = self._tenant_pages.get(key, 0) - n_charged
            if left > 0:
                self._tenant_pages[key] = left
            else:
                self._tenant_pages.pop(key, None)
        pages = self._slot_pages.pop(slot, [])
        self._pending_cow.pop(slot, None)
        self._tables[slot] = GARBAGE_PAGE
        self._free_slots.append(slot)
        sid = getattr(req, "session_id", None) if req is not None else None
        parked = False
        if sid is not None and getattr(req, "finish_reason", None) in ("eos", "length"):
            gen = list(getattr(req, "generated", []) or [])
            tokens = np.concatenate(
                [np.asarray(req.prompt, np.int32),
                 np.asarray(gen[:-1], np.int32)]
            )
            if tokens.shape[0] > 0:
                n_keep = _pages_for(tokens.shape[0], self.page_len)
                kept, dropped = pages[:n_keep], pages[n_keep:]
                prev = self.sessions.park(Session(
                    session_id=sid, tokens=tokens, pages=kept, parked_at=now,
                ))
                if prev is not None:
                    self._page_decref(prev.pages)
                if self.tiers is not None:
                    # a fresh park supersedes any tiered copy (mirror of
                    # park() clearing a stale spill)
                    self.tiers.discard_session(sid)
                self._page_decref(dropped)
                parked = True
        if not parked:
            self._page_decref(pages)

    def _gather_host(self, pages: Sequence[int]) -> Dict[str, np.ndarray]:
        ids = jnp.asarray(np.asarray(pages, np.int32))
        out: Dict[str, np.ndarray] = {}
        for prefix, tree in (("k", self.k), ("v", self.v)):
            leaves = tree if isinstance(tree, dict) else {None: tree}
            for name, buf in leaves.items():
                key = prefix if name is None else f"{prefix}.{name}"
                out[key] = jax.device_get(jnp.take(buf, ids, axis=1))
        return out

    def _scatter_device(self, pages: Sequence[int],
                        leaves: Dict[str, np.ndarray]) -> None:
        ids = jnp.asarray(np.asarray(pages, np.int32))

        def put(tree, prefix):
            if isinstance(tree, dict):
                return {
                    name: buf.at[:, ids].set(jnp.asarray(leaves[f"{prefix}.{name}"]))
                    for name, buf in tree.items()
                }
            return tree.at[:, ids].set(jnp.asarray(leaves[prefix]))

        # eager host->device writes, outside any compiled serving step
        # (and outside the ds_san transfer guards that wrap them)
        self.k = put(self.k, "k")
        self.v = put(self.v, "v")

    def _spill_or_drop(self, sess: Session) -> None:
        if self.tiers is not None:
            # tiering replaces direct spill/drop: the session parks in
            # host memory and cascades to disk under host-cap pressure
            self.tiers.demote_session(sess)
            return
        if self.sessions.spill_dir is not None:
            self.sessions.spill(sess, self._gather_host(sess.pages))
        else:
            self.sessions.drop(sess.session_id)
        self._page_decref(sess.pages)
        sess.pages = []

    def _restore_session(self, session_id: str, now: float) -> Optional[Session]:
        loaded = self.sessions.load(session_id)
        if loaded is None:
            return None
        sess, leaves = loaded
        pages = self._take_pages(_pages_for(sess.cached_len, self.page_len), now)
        if pages is None:
            logger.warning(
                f"kvcache: no pages to restore spilled session "
                f"{session_id!r}; dropping it"
            )
            self.sessions.drops += 1
            return None
        self._scatter_device(pages, leaves)
        sess.pages = pages
        sess.parked_at = now
        self.sessions.park(sess)
        return sess

    @_locked
    def sweep(self, now: float) -> int:
        """TTL sweep: spill (or drop) sessions cold past
        ``session_ttl_seconds``.  Cheap; the engine runs it per step."""
        expired = self.sessions.expired(now)
        for sess in expired:
            self._spill_or_drop(sess)
        return len(expired)

    @_locked
    def spill_sessions(self, now: float = 0.0) -> int:
        """Drain path: persist every warm session (no-op without a
        spill_dir — the pages die with the process, which only costs
        the restarted engine a re-prefill)."""
        if self.sessions.spill_dir is None:
            return 0
        warm = self.sessions.warm()
        for sess in warm:
            self._spill_or_drop(sess)
        return len(warm)

    @_locked
    def attach_tiers(self, mgr: Any) -> None:
        """Arm hierarchical tiering: ``mgr`` (a
        :class:`~deepspeed_tpu.serving.kvcache.tiers.PageTierManager`)
        takes over session spill/drop and cold prefix eviction."""
        self.tiers = mgr

    @_locked
    def attach_tenants(self, registry: Any) -> None:
        """Arm per-tenant quota enforcement: ``registry`` (a
        :class:`~deepspeed_tpu.serving.frontdoor.tenants.TenantRegistry`)
        supplies page and pinned-prefix caps; over-cap allocations defer
        (return ``None`` from :meth:`alloc_request`) and over-cap pins
        degrade to unpinned entries."""
        self.tenants = registry

    @_locked
    def recover(self) -> List[str]:
        """Post-crash: re-register manifest-verified session spills so
        rebinds keep working across the restart.  (Device pages and the
        learned prefix index died with the process — replay re-prefills
        and re-learns, so outputs stay bit-identical.)"""
        found = self.sessions.recover()
        if self.tiers is not None:
            found = found + self.tiers.recover()
        return found

    # -- live migration (docs/serving.md §Elastic fleet) ------------------
    @_locked
    def export_sessions(self, dest_dir: str, now: float = 0.0) -> List[str]:
        """Scale-down export: write every parked session (warm and
        spilled) plus every pinned prefix entry into ``dest_dir`` in the
        spill wire format, one manifest-last directory per entry.

        READ-ONLY on pool state — sessions stay parked, pins stay
        indexed, no refcount moves — so a failed or killed export is
        simply retried, and an abandoned one costs nothing.  A kill -9
        mid-export leaves a manifest-verified prefix of entries the
        importer trusts; the unverified tail is ignored."""
        os.makedirs(dest_dir, exist_ok=True)
        exported: List[str] = []
        for sess in self.sessions.warm():
            # a residency-window session keeps only head pages in T0;
            # the export must carry the tier-held tail too
            leaves = (self.tiers.merged_session_leaves(sess)
                      if self.tiers is not None
                      else self._gather_host(sess.pages))
            write_entry(
                dest_dir, session_dir_name(sess.session_id),
                {
                    "kind": "session",
                    "session_id": sess.session_id,
                    "tokens": [int(t) for t in sess.tokens],
                    "parked_at": sess.parked_at,
                },
                leaves,
            )
            exported.append(sess.session_id)
        for sid in self.sessions.spilled_ids():
            src = self.sessions.spilled_dir(sid)
            loaded = read_entry(src) if src else None
            if loaded is None:
                continue
            meta, leaves = loaded
            meta = {k: v for k, v in meta.items() if k != "leaf_dtypes"}
            meta.setdefault("kind", "session")
            write_entry(dest_dir, session_dir_name(sid), meta, leaves)
            exported.append(sid)
        for entry in self.index.entries():
            if not entry.pinned:
                continue  # learned entries re-learn from traffic
            write_entry(
                dest_dir, pin_dir_name(entry.tokens),
                {
                    "kind": "pinned_prefix",
                    "tokens": [int(t) for t in entry.tokens],
                },
                self._gather_host(entry.pages),
            )
            exported.append(f"pin:{len(entry.tokens)}")
        if self.tiers is not None:
            exported.extend(self.tiers.export_sessions(
                dest_dir, skip=set(exported)))
        return exported

    @_locked
    def import_sessions(self, src_dir: str, now: float = 0.0) -> Dict[str, int]:
        """Scale-up/survivor import: adopt every manifest-verified entry
        under ``src_dir``.  Sessions the pool already knows are skipped
        (the survivor's own copy wins — rebind is an optimisation, so a
        skip only re-prefills, it never changes outputs).  When the pool
        is out of pages a migrated session lands in this pool's own
        spill_dir instead (or is dropped without one)."""
        counts = {"sessions": 0, "pinned": 0, "respilled": 0, "skipped": 0}
        for meta, leaves in read_entries(src_dir):
            kind = meta.get("kind", "session")
            if kind == "pinned_prefix":
                tokens = np.asarray(meta["tokens"], np.int32)
                if tokens.shape[0] < 1:
                    counts["skipped"] += 1
                    continue
                existing = self.index.get(tokens)
                if existing is not None:
                    existing.pinned = True
                    counts["skipped"] += 1
                    continue
                pages = self._take_pages(
                    _pages_for(tokens.shape[0], self.page_len), now
                )
                if pages is None:
                    logger.warning(
                        "kvcache: no pages to import a pinned prefix "
                        f"({tokens.shape[0]} tokens); dropping it"
                    )
                    counts["skipped"] += 1
                    continue
                self._scatter_device(pages, leaves)
                # insert takes the index's own reference (ref -> 2);
                # releasing the import's claim leaves the index as the
                # sole holder, exactly like a learned pinned entry
                self._insert_entry(tokens, pages, pinned=True, now=now)
                self._page_decref(pages)
                counts["pinned"] += 1
                continue
            sid = meta["session_id"]
            if self.sessions.has(sid):
                counts["skipped"] += 1
                continue
            sess = Session(
                session_id=sid,
                tokens=np.asarray(meta["tokens"], np.int32),
                pages=[],
                parked_at=now,
            )
            pages = self._take_pages(
                _pages_for(sess.cached_len, self.page_len), now
            )
            if pages is None:
                if self.sessions.adopt_spill(sid, meta, leaves) is not None:
                    counts["respilled"] += 1
                else:
                    logger.warning(
                        f"kvcache: no pages and no spill_dir for migrated "
                        f"session {sid!r}; dropping it (next turn re-prefills)"
                    )
                    counts["skipped"] += 1
                continue
            self._scatter_device(pages, leaves)
            sess.pages = pages
            prev = self.sessions.park(sess)
            if prev is not None:  # pragma: no cover - has() guards this
                self._page_decref(prev.pages)
            counts["sessions"] += 1
        return counts

    # -- introspection ----------------------------------------------------
    @_locked
    def refcount(self, page: int) -> int:
        return int(self._ref[page])

    @_locked
    def stats(self) -> Dict[str, Any]:
        sess = self.sessions.stats()
        out = {
            "page_len": self.page_len,
            "num_pages": self.num_pages,
            "pages_per_slot": self.pages_per_slot,
            "pages_live": self.pages_live,
            "pages_free": self.pages_free,
            "lookups": self.lookups,
            "prefix_hits": self.hits,
            "prefix_misses": self.misses,
            "hit_rate": (self.hits / self.lookups) if self.lookups else 0.0,
            "tokens_saved": self.tokens_saved,
            "cow_copies": self.cow_copies,
            "evictions": self.evictions,
            "alloc_waits": self.alloc_waits,
            "prefix_entries": len(self.index),
            "session_rebinds": self.session_rebinds,
            "sessions_warm": sess["warm"],
            "sessions_spilled": sess["spilled"],
            "session_parks": sess["parks"],
            "session_spills": sess["spills"],
            "session_restores": sess["restores"],
            "session_drops": sess["drops"],
        }
        if self.tiers is not None:
            out["tiers"] = self.tiers.stats()
        if self.tenants is not None:
            out["tenant_pages"] = dict(self._tenant_pages)
            out["tenant_pinned"] = dict(self._tenant_pinned)
            out["tenant_quota_defers"] = self.tenant_quota_defers
            out["tenant_pin_rejects"] = self.tenant_pin_rejects
        return out
