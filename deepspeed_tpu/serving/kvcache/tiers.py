"""Hierarchical KV page tiering: HBM (T0) → pinned host memory (T1) →
disk (T2), with overlap-hidden swaps (docs/serving.md §KV tiering).

The device :class:`~deepspeed_tpu.serving.kvcache.pages.PagedKVPool` is
tier 0.  Cold state — unreferenced prefix entries past the LRU
watermark, parked-session pages, and the tail pages of contexts beyond
the residency window — demotes T0→T1→T2 so KV capacity becomes a
function of host+disk, not HBM.  Promotion is demand-driven (a rebind
or prefix hit pages the entry back in before the slot binds) plus
scheduler-hinted (queued admits prefetch their pages back to T0 before
their prefill chunk runs).

Threading contract (ds_race relies on this):

* The **engine thread** owns every device touch.  T0↔T1 moves
  (``device_get`` gather / ``device_put`` scatter) run batched at step
  boundaries under ``pool._lock`` → ``self._lock`` (always that order),
  so page tables are only ever rewritten between steps and the
  exactly-two-executables contract survives — tables stay traced
  values, tiering never changes an abstract signature.
* The **migration worker** (one :class:`BoundedWorker` thread) owns the
  slow tier boundary only: T1→T2 npz writes and T2→T1 reads.  It takes
  ``self._lock`` alone and never touches the pool or device buffers, so
  there is no lock-order cycle and no background thread ever races a
  donated device buffer.

T2 durability reuses the PR 15 stage→manifest protocol: kv.npz +
meta.json staged and fsynced first, ``manifest.json`` written LAST
(fault site ``tier.demote`` sits between the two, so an injected kill
leaves exactly the torn, never-trusted stage the chaos test wants).
``recover()`` trusts only manifest-verified directories.

Swap-hiding is measured, not assumed: the engine stamps each step's
wall window into a ring; every worker job computes how much of its own
duration overlapped a step window.  ``swap_hidden_ratio`` in
:meth:`stats` is the headline the ``kvtiers`` bench gates on, and each
job emits a Perfetto span (cat ``serving.tier``) for trace-level
audits.
"""
from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.resilience import atomic, faults
from deepspeed_tpu.runtime.overlap.worker import BoundedWorker
from deepspeed_tpu.serving.kvcache.prefix import PrefixEntry, PrefixIndex
from deepspeed_tpu.serving.kvcache.sessions import (
    DATA_FILE,
    META_FILE,
    Session,
    load_leaves,
    prefix_dir_name,
    save_leaves,
    session_dir_name,
    write_entry,
)
from deepspeed_tpu.utils.logging import logger

__all__ = ["PageTierManager", "TierEntry"]

_HOST = "host"
_DISK = "disk"


def _pages_for(tokens: int, page_len: int) -> int:
    return -(-int(tokens) // int(page_len))


def _leaf_bytes(leaves: Optional[Dict[str, np.ndarray]]) -> int:
    if not leaves:
        return 0
    return int(sum(a.size * a.dtype.itemsize for a in leaves.values()))


@dataclasses.dataclass
class TierEntry:
    """One off-device KV entry.  ``kind`` is ``session`` (a whole parked
    session), ``tail`` (the beyond-residency-window tail pages of a
    still-warm session; T1-only by construction), or ``prefix`` (a
    demoted learned prefix)."""

    key: str
    kind: str
    tokens: np.ndarray
    n_pages: int
    tier: str  # _HOST | _DISK
    leaves: Optional[Dict[str, np.ndarray]] = None
    dir_name: str = ""
    last_used: float = 0.0
    pinned: bool = False
    session_id: str = ""
    parked_at: float = 0.0
    writing: bool = False  # T1->T2 write in flight on the worker
    reading: bool = False  # T2->T1 read in flight on the worker

    @property
    def host_bytes(self) -> int:
        return _leaf_bytes(self.leaves)


class PageTierManager:
    """Three-tier page residency manager over a :class:`PagedKVPool`.

    Engine-thread entry points (``tick`` and every ``promote_*`` /
    ``demote_*``) must hold ``pool._lock`` before this manager's lock;
    :meth:`tick` acquires it itself.  Worker jobs take only
    ``self._lock``.
    """

    def __init__(self, pool: Any, host_pages: int = 0,
                 disk_dir: Optional[str] = None,
                 residency_window: int = 0,
                 demote_watermark: float = 0.75,
                 prefetch_ahead: int = 4,
                 demote_batch: int = 4,
                 worker_depth: int = 32):
        self.pool = pool
        self.host_pages = max(0, int(host_pages))  # 0 = unbounded T1
        self.disk_dir = disk_dir or None
        self.residency_window = max(0, int(residency_window))
        self.demote_watermark = float(demote_watermark)
        self.prefetch_ahead = max(0, int(prefetch_ahead))
        self.demote_batch = max(1, int(demote_batch))
        if not (0.0 < self.demote_watermark <= 1.0):
            raise ValueError(
                f"demote_watermark must be in (0, 1], got {demote_watermark}")
        if self.disk_dir:
            os.makedirs(self.disk_dir, exist_ok=True)
        # instrumentable via ds_race's instrument(mgr, "_lock", site)
        self._lock = threading.RLock()
        self._entries: Dict[str, TierEntry] = {}
        self._pfx = PrefixIndex()  # shadow index over tier-resident prefixes
        self._promoting: set = set()  # session ids mid-promotion: not demotable
        self._dirgen = 0  # T2 dir generation: a re-demoted session never
        # reuses its previous on-disk dir (whose rmtree may be in flight)
        self._worker = BoundedWorker(name="ds-kv-tiers", depth=worker_depth)
        # engine step windows for the swap-hide overlap accounting
        self._steps: Deque[Tuple[float, float]] = deque(maxlen=256)
        self.telemetry: Any = None  # engine injects its TelemetryManager
        # counters (kvcache/tier/* gauges read these through stats())
        self.demote_t0_t1 = 0
        self.demote_t1_t2 = 0
        self.promote_t1_t0 = 0
        self.promote_t2_t1 = 0
        self.promote_t2_t0 = 0  # demand-driven synchronous disk reads
        self.tail_demotions = 0
        self.tail_promotions = 0
        self.hits_t1 = 0
        self.hits_t2 = 0
        self.misses = 0
        self.drops = 0
        self.prefetch_jobs = 0
        self.swap_seconds_total = 0.0
        self.swap_seconds_hidden = 0.0

    # -- keys ---------------------------------------------------------
    @staticmethod
    def _skey(session_id: str) -> str:
        return "sess:" + session_id

    @staticmethod
    def _tkey(session_id: str) -> str:
        return "tail:" + session_id

    @staticmethod
    def _pkey(tokens: np.ndarray) -> str:
        return "pfx:" + np.asarray(tokens, np.int32).tobytes().hex()[:32]

    # -- swap-hide accounting -----------------------------------------
    def note_step(self, start: float, end: float) -> None:
        """Record one engine step's wall window (monotonic stamps)."""
        with self._lock:
            self._steps.append((float(start), float(end)))

    def _hidden_overlap(self, start: float, end: float) -> float:
        with self._lock:
            windows = list(self._steps)
        hidden = 0.0
        for ws, we in windows:
            hidden += max(0.0, min(end, we) - max(start, ws))
        return min(hidden, end - start)

    def _account_swap(self, op: str, start: float, end: float,
                      n_pages: int) -> None:
        dur = max(0.0, end - start)
        hidden = self._hidden_overlap(start, end)
        with self._lock:
            self.swap_seconds_total += dur
            self.swap_seconds_hidden += hidden
        tracer = getattr(self.telemetry, "tracer", None)
        if tracer is not None and getattr(tracer, "enabled", False):
            t1 = tracer.now()
            tracer.add_span(
                f"tier.{op}", "serving.tier", t1 - dur, t1,
                tid=3, tid_name="kv tiers",
                args={"pages": int(n_pages),
                      "hidden_s": round(hidden, 6)},
            )

    # -- T2 staging (worker thread) -----------------------------------
    def _write_t2(self, entry_dir: str, meta: Dict,
                  leaves: Dict[str, np.ndarray]) -> str:
        """Stage one tier entry to disk, manifest LAST.  The
        ``tier.demote`` fault site sits between the staged payload and
        the manifest: an injected kill leaves a torn stage that
        :meth:`recover` never trusts."""
        target = os.path.join(self.disk_dir, entry_dir)
        os.makedirs(target, exist_ok=True)
        stale = os.path.join(target, atomic.MANIFEST_FILE)
        if os.path.exists(stale):
            os.remove(stale)
        dtypes = save_leaves(leaves, os.path.join(target, DATA_FILE))
        meta = dict(meta)
        meta["leaf_dtypes"] = dtypes
        atomic.atomic_write_text(os.path.join(target, META_FILE),
                                 json.dumps(meta))
        faults.check("tier.demote")
        atomic.write_manifest(target)
        return target

    def _read_t2(self, entry_dir: str,
                 quiet: bool = False) -> Optional[Dict[str, np.ndarray]]:
        target = os.path.join(self.disk_dir, entry_dir)
        ok, _ = atomic.verify_manifest(target)
        meta_path = os.path.join(target, META_FILE)
        if not ok or not os.path.exists(meta_path):
            if not quiet:
                logger.warning(
                    f"kvcache: tier entry at {target} failed manifest "
                    f"verification; ignoring it")
            return None
        with open(meta_path) as f:
            meta = json.load(f)
        return load_leaves(os.path.join(target, DATA_FILE),
                           meta["leaf_dtypes"])

    def _entry_meta(self, e: TierEntry) -> Dict:
        if e.kind == "session":
            return {"kind": "session", "session_id": e.session_id,
                    "tokens": [int(t) for t in e.tokens],
                    "parked_at": e.parked_at}
        return {"kind": "prefix", "tokens": [int(t) for t in e.tokens],
                "pinned": bool(e.pinned)}

    def _remove_dir(self, dir_name: str) -> None:
        if not (self.disk_dir and dir_name):
            return
        shutil.rmtree(os.path.join(self.disk_dir, dir_name),
                      ignore_errors=True)

    # -- worker jobs ---------------------------------------------------
    def _submit_write(self, e: TierEntry) -> bool:
        """T1→T2: queue ``e``'s leaves for a background disk write.
        Caller holds ``self._lock``; ``e.writing`` guards re-submit."""
        if not self.disk_dir or e.writing or e.kind == "tail":
            return False
        leaves = e.leaves
        if leaves is None:
            return False
        e.writing = True
        self._dirgen += 1
        base = (session_dir_name(e.session_id) if e.kind == "session"
                else prefix_dir_name(e.tokens))
        e.dir_name = f"{base}-g{self._dirgen}"
        meta = self._entry_meta(e)

        def job(entry=e, leaves=leaves, meta=meta):
            t0 = time.monotonic()
            try:
                self._write_t2(entry.dir_name, meta, leaves)
            except FileNotFoundError:
                # the entry was consumed and _drop_entry rmtree'd the
                # staging dir out from under the write — nothing to keep
                with self._lock:
                    entry.writing = False
                    self._remove_dir(entry.dir_name)
                return
            t1 = time.monotonic()
            with self._lock:
                entry.writing = False
                if self._entries.get(entry.key) is entry:
                    entry.leaves = None
                    entry.tier = _DISK
                    self.demote_t1_t2 += 1
                else:
                    # promoted (consumed) while the write was in flight:
                    # the staged copy is stale — drop it
                    self._remove_dir(entry.dir_name)
            self._account_swap("demote", t0, t1, entry.n_pages)

        if not self._worker.submit(job, label=f"demote:{e.key}"):
            e.writing = False
            return False
        return True

    def _submit_read(self, e: TierEntry) -> bool:
        """T2→T1 prefetch: queue a background disk read so a hinted
        promotion finds the leaves already host-resident.  Caller holds
        ``self._lock``."""
        if e.tier != _DISK or e.reading or e.writing:
            return False
        e.reading = True

        def job(entry=e):
            t0 = time.monotonic()
            faults.check("tier.promote")
            # quiet: a demand promotion may consume the entry (and
            # remove its dir) while this prefetch is in flight — that
            # is a benign race, not a torn stage
            leaves = self._read_t2(entry.dir_name, quiet=True)
            t1 = time.monotonic()
            with self._lock:
                entry.reading = False
                if self._entries.get(entry.key) is not entry:
                    return  # consumed or discarded while reading
                if leaves is None:
                    logger.warning(
                        f"kvcache: tier entry {entry.key} unreadable at "
                        f"{entry.dir_name}; dropping it")
                    self._drop_entry(entry)  # torn on disk: unrecoverable
                    return
                if entry.tier == _DISK:
                    entry.leaves = leaves
                    entry.tier = _HOST
                    self.promote_t2_t1 += 1
            self._account_swap("promote", t0, t1, entry.n_pages)

        if not self._worker.submit(job, label=f"prefetch:{e.key}"):
            e.reading = False
            return False
        self.prefetch_jobs += 1
        return True

    def _pump_errors(self) -> None:
        for label, exc in self._worker.errors():
            if isinstance(exc, (faults.InjectedKill, faults.InjectedFault)):
                raise exc  # fault-injection tests want these surfaced
            logger.warning(f"kvcache: tier migration job {label} failed: {exc}")

    # -- registration helpers (self._lock held) ------------------------
    def _register(self, e: TierEntry) -> None:
        self._entries[e.key] = e
        if e.kind == "prefix":
            shadow = PrefixEntry(tokens=e.tokens, pages=[], pinned=e.pinned,
                                 last_used=e.last_used, tier_key=e.key)
            self._pfx.insert(shadow)

    def _drop_entry(self, e: TierEntry) -> None:
        self._entries.pop(e.key, None)
        if e.kind == "prefix":
            shadow = self._pfx.get(e.tokens)
            if shadow is not None and shadow.tier_key == e.key:
                self._pfx.remove(shadow)
        if e.tier == _DISK or e.writing:
            self._remove_dir(e.dir_name)

    def _materialize(self, e: TierEntry) -> Optional[Dict[str, np.ndarray]]:
        """Entry leaves, reading T2 synchronously when a demand
        promotion outruns its prefetch.  Returns None (and drops the
        entry) when the disk copy is unverifiable."""
        if e.leaves is not None:
            self.hits_t1 += 1
            return e.leaves
        leaves = self._read_t2(e.dir_name)
        if leaves is None:
            self._drop_entry(e)
            return None
        self.hits_t2 += 1
        self.promote_t2_t0 += 1
        return leaves

    # -- demotion (engine thread, pool lock held) -----------------------
    def demote_session(self, sess: Session, now: float = 0.0) -> bool:
        """Park a whole warm session in T1 (merging any tier-held tail),
        releasing its T0 pages.  The pool's ``_spill_or_drop`` routes
        here when tiering is armed."""
        sid = sess.session_id
        with self._lock:
            if sid in self._promoting:
                return False  # mid-promotion: not a demotion candidate
            tail = self._entries.get(self._tkey(sid))
        head = self.pool._gather_host(sess.pages) if sess.pages else {}
        with self._lock:
            if tail is not None:
                if head:
                    leaves = {k: np.concatenate([head[k], tail.leaves[k]],
                                                axis=1)
                              for k in tail.leaves}
                else:
                    leaves = tail.leaves
                self._entries.pop(tail.key, None)
            else:
                leaves = head
            n_pages = len(sess.pages) + (tail.n_pages if tail else 0)
            e = TierEntry(
                key=self._skey(sid), kind="session", tokens=sess.tokens,
                n_pages=n_pages, tier=_HOST, leaves=leaves,
                last_used=now, session_id=sid, parked_at=sess.parked_at,
            )
            self._register(e)
            self.demote_t0_t1 += 1
        self.pool.sessions.pop_warm(sid)
        self.pool._page_decref(sess.pages)
        sess.pages = []
        return True

    def demote_tail(self, sess: Session, now: float = 0.0) -> int:
        """Demote a warm session's pages beyond the residency window to
        T1 (the session stays warm and rebinds promote the tail back
        first).  Returns the number of pages demoted."""
        if self.residency_window <= 0:
            return 0
        sid = sess.session_id
        keep = max(1, _pages_for(self.residency_window, self.pool.page_len))
        with self._lock:
            if sid in self._promoting or self._tkey(sid) in self._entries:
                return 0
        if len(sess.pages) <= keep:
            return 0
        tail_pages = sess.pages[keep:]
        leaves = self.pool._gather_host(tail_pages)
        with self._lock:
            e = TierEntry(
                key=self._tkey(sid), kind="tail", tokens=sess.tokens,
                n_pages=len(tail_pages), tier=_HOST, leaves=leaves,
                last_used=now, session_id=sid,
            )
            self._register(e)
            self.tail_demotions += 1
        self.pool._page_decref(tail_pages)
        sess.pages = sess.pages[:keep]
        return len(tail_pages)

    def demote_prefix(self, entry: PrefixEntry, now: float = 0.0) -> bool:
        """Move a learned prefix entry out of the device index into T1.
        Pages shared with live slots stay alive through their other
        holders; this only releases the index's reference."""
        leaves = self.pool._gather_host(entry.pages)
        with self._lock:
            key = self._pkey(entry.tokens)
            if key in self._entries:  # already tiered under this key
                leaves = None
            else:
                e = TierEntry(
                    key=key, kind="prefix", tokens=entry.tokens,
                    n_pages=len(entry.pages), tier=_HOST, leaves=leaves,
                    last_used=max(now, entry.last_used), pinned=entry.pinned,
                )
                self._register(e)
                self.demote_t0_t1 += 1
        self.pool.index.remove(entry)
        self.pool._page_decref(entry.pages)
        return True

    def discard_session(self, session_id: str) -> None:
        """A fresh park supersedes any tiered copy of the session (the
        mirror of ``SessionStore.park`` clearing a stale spill)."""
        with self._lock:
            for key in (self._skey(session_id), self._tkey(session_id)):
                e = self._entries.get(key)
                if e is not None:
                    self._drop_entry(e)

    def drop_session(self, sess: Session) -> None:
        """Give up on a warm session whose tail cannot be paged back in:
        release everything; the next turn re-prefills (bit-identical —
        rebind is only ever an optimisation)."""
        self.pool.sessions.drop(sess.session_id)
        self.pool._page_decref(sess.pages)
        sess.pages = []
        self.discard_session(sess.session_id)
        with self._lock:
            self.drops += 1

    # -- promotion (engine thread, pool lock held) ----------------------
    def has_session(self, session_id: str) -> bool:
        with self._lock:
            return self._skey(session_id) in self._entries

    def has_tail(self, session_id: str) -> bool:
        with self._lock:
            return self._tkey(session_id) in self._entries

    def promote_session(self, session_id: str, now: float) -> Optional[Session]:
        """Page a tiered session back into T0 and park it warm.  On
        page starvation the entry stays tiered and the caller
        re-prefills."""
        with self._lock:
            e = self._entries.get(self._skey(session_id))
            if e is None:
                self.misses += 1
                return None
            self._promoting.add(session_id)
        try:
            with self._lock:
                leaves = self._materialize(e)
                if leaves is None:
                    return None
            pages = self.pool._take_pages(e.n_pages, now)
            if pages is None:
                # routine under oversubscription: the request falls back
                # to a full prefill and the entry stays parked
                logger.debug(
                    f"kvcache: no pages to promote tiered session "
                    f"{session_id!r}; leaving it parked in "
                    f"{'T1' if e.tier == _HOST else 'T2'}")
                return None
            self.pool._scatter_device(pages, leaves)
            sess = Session(session_id=session_id, tokens=e.tokens,
                           pages=pages, parked_at=now)
            self.pool.sessions.park(sess)
            with self._lock:
                self._drop_entry(e)
                self.promote_t1_t0 += 1
            return sess
        finally:
            with self._lock:
                self._promoting.discard(session_id)

    def promote_tail(self, sess: Session, now: float) -> bool:
        """Page a warm session's tiered tail back in ahead of a rebind.
        False when T0 cannot hold it (caller drops + re-prefills)."""
        sid = sess.session_id
        with self._lock:
            e = self._entries.get(self._tkey(sid))
            if e is None:
                return True
            self._promoting.add(sid)
        try:
            pages = self.pool._take_pages(e.n_pages, now)
            if pages is None:
                return False
            self.pool._scatter_device(pages, e.leaves)
            sess.pages = sess.pages + pages
            with self._lock:
                self._drop_entry(e)
                self.tail_promotions += 1
                self.hits_t1 += 1
            return True
        finally:
            with self._lock:
                self._promoting.discard(sid)

    def lookup_prefix(self, prompt: np.ndarray,
                      stamp: bool = False) -> Optional[TierEntry]:
        """Deepest tier-resident prefix of ``prompt`` (shadow-index
        walk; no device work)."""
        with self._lock:
            shadow = self._pfx.lookup(prompt, stamp=stamp)
            if shadow is None:
                return None
            return self._entries.get(shadow.tier_key)

    def promote_prefix_for(self, prompt: np.ndarray, now: float,
                           min_len: int = 0) -> bool:
        """Demand promotion: if a tier-resident prefix of ``prompt``
        beats the device index's best hit (``min_len``), page it back
        into T0 and re-insert it into the index.  True when the caller
        should re-run its index lookup."""
        with self._lock:
            e = self.lookup_prefix(prompt, stamp=True)
            if e is None or int(e.tokens.shape[0]) <= int(min_len):
                if e is None:
                    self.misses += 1
                return False
            leaves = self._materialize(e)
            if leaves is None:
                return False
        pages = self.pool._take_pages(e.n_pages, now)
        if pages is None:
            return False
        self.pool._scatter_device(pages, leaves)
        # _insert_entry takes the index's own reference; releasing the
        # promotion's claim leaves the index as the sole holder
        self.pool._insert_entry(e.tokens, pages, pinned=e.pinned, now=now)
        self.pool._page_decref(pages)
        with self._lock:
            self._drop_entry(e)
            self.promote_t1_t0 += 1
        return True

    def merged_session_leaves(self, sess: Session) -> Dict[str, np.ndarray]:
        """Full host leaves for a warm session whose tail may be
        tier-held (migration export needs complete KV coverage)."""
        head = self.pool._gather_host(sess.pages) if sess.pages else {}
        with self._lock:
            tail = self._entries.get(self._tkey(sess.session_id))
            if tail is None or tail.leaves is None:
                return head
            if not head:
                return dict(tail.leaves)
            return {k: np.concatenate([head[k], tail.leaves[k]], axis=1)
                    for k in tail.leaves}

    # -- affinity pricing ----------------------------------------------
    def session_hint(self, prompt: np.ndarray,
                     session_id: str) -> Tuple[int, str]:
        """(cached tokens, tier) for a tiered session matching
        ``prompt`` — the fleet router prices T1/T2 residency with this
        so a parked session still beats a cold replica."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            e = self._entries.get(self._skey(session_id))
            if e is None:
                return 0, ""
            cl = int(e.tokens.shape[0])
            if cl > prompt.shape[0] or not np.array_equal(
                    e.tokens, prompt[:cl]):
                return 0, ""
            return cl, e.tier

    def prefix_hint(self, prompt: np.ndarray) -> Tuple[int, str]:
        with self._lock:
            e = self.lookup_prefix(prompt, stamp=False)
            if e is None:
                return 0, ""
            return int(e.tokens.shape[0]), e.tier

    # -- the per-step tick (engine thread) ------------------------------
    def tick(self, now: float,
             hints: Sequence[Tuple[Any, Optional[str]]] = ()) -> None:
        """One migration-queue turn, run at step boundaries (and from
        ``stats()``/``drain()`` so an idle engine still drains pending
        demotions).  Order matters: hinted prefetch first (so imminent
        admits win the free pages), then watermark demotion, then T1
        cap enforcement."""
        self._pump_errors()
        with self.pool._lock:
            hinted = self._prefetch(now, hints)
            self._demote_pass(now, hinted)
            with self._lock:
                self._enforce_host_cap()

    def _prefetch(self, now: float,
                  hints: Sequence[Tuple[Any, Optional[str]]]) -> set:
        hinted: set = set()
        for prompt, sid in list(hints)[: self.prefetch_ahead]:
            if sid is not None:
                hinted.add(sid)
                warm = self.pool.sessions.peek(sid)
                if warm is not None:
                    with self._lock:
                        tail = self._entries.get(self._tkey(sid))
                    if (tail is not None
                            and self.pool.pages_free > tail.n_pages):
                        self.promote_tail(warm, now)
                    continue
                with self._lock:
                    e = self._entries.get(self._skey(sid))
                    if e is not None and e.tier == _DISK:
                        self._submit_read(e)
                        continue
                if (e is not None
                        and self.pool.pages_free > e.n_pages):
                    self.promote_session(sid, now)
                continue
            if prompt is None:
                continue
            with self._lock:
                e = self.lookup_prefix(np.asarray(prompt, np.int32))
                if e is not None and e.tier == _DISK:
                    self._submit_read(e)
                    continue
            if (e is not None and self.pool.pages_free > e.n_pages
                    and self.pool.index.get(e.tokens) is None):
                self.promote_prefix_for(np.asarray(prompt, np.int32), now)
        return hinted

    def _over_watermark(self) -> bool:
        capacity = self.pool.num_pages - 1
        return self.pool.pages_live > self.demote_watermark * capacity

    def _demote_pass(self, now: float, hinted: set) -> None:
        budget = self.demote_batch
        # residency window first: it trims warm sessions without
        # evicting anything, so it is the cheapest pressure valve
        if self.residency_window > 0:
            for sess in sorted(self.pool.sessions.warm(),
                               key=lambda s: s.parked_at):
                if budget <= 0:
                    break
                if sess.session_id in hinted:
                    continue
                if self.demote_tail(sess, now) > 0:
                    budget -= 1
        if not self._over_watermark():
            return
        for entry in self.pool.index.evict_candidates():
            if budget <= 0 or not self._over_watermark():
                return
            self.demote_prefix(entry, now)
            budget -= 1
        for sess in sorted(self.pool.sessions.warm(),
                           key=lambda s: s.parked_at):
            if budget <= 0 or not self._over_watermark():
                return
            if sess.session_id in hinted:
                continue
            if self.demote_session(sess, now):
                budget -= 1

    def _enforce_host_cap(self) -> None:
        """Push LRU T1 entries to T2 (or drop them without a disk tier)
        until the host store fits ``host_pages``.  Caller holds
        ``self._lock``."""
        if self.host_pages <= 0:
            return
        while True:
            resident = [e for e in self._entries.values()
                        if e.tier == _HOST and not e.writing
                        and e.kind != "tail"]
            used = sum(e.n_pages for e in self._entries.values()
                       if e.tier == _HOST)
            if used <= self.host_pages or not resident:
                return
            victim = min(resident, key=lambda e: e.last_used)
            if self.disk_dir:
                if not self._submit_write(victim):
                    return  # worker queue full: retry next tick
            else:
                logger.warning(
                    f"kvcache: host tier over cap with no disk tier; "
                    f"dropping {victim.key}")
                self._drop_entry(victim)
                self.drops += 1

    def export_sessions(self, dest_dir: str,
                        skip: Optional[set] = None) -> List[str]:
        """Scale-down export: write every tier-resident session into
        ``dest_dir`` in the migration wire format.  READ-ONLY on tier
        state (mirrors the pool's export contract — a killed export is
        simply retried)."""
        skip = skip or set()
        exported: List[str] = []
        with self._lock:
            entries = [e for e in self._entries.values()
                       if e.kind == "session" and e.session_id not in skip]
        for e in entries:
            with self._lock:
                leaves = e.leaves if e.leaves is not None else (
                    self._read_t2(e.dir_name) if e.dir_name else None)
            if leaves is None:
                continue
            write_entry(dest_dir, session_dir_name(e.session_id),
                        self._entry_meta(e), leaves)
            exported.append(e.session_id)
        return exported

    # -- lifecycle ------------------------------------------------------
    def flush(self, now: float = 0.0, timeout: float = 30.0) -> int:
        """Drain path: demote every warm session and push every
        disk-eligible T1 entry to T2, then wait for the worker — after
        this, tiered state survives the process."""
        moved = 0
        with self.pool._lock:
            for sess in list(self.pool.sessions.warm()):
                if self.demote_session(sess, now):
                    moved += 1
            with self._lock:
                if self.disk_dir:
                    for e in list(self._entries.values()):
                        if e.tier == _HOST and not e.writing:
                            self._submit_write(e)
        self._worker.drain(timeout)
        self._pump_errors()
        return moved

    @staticmethod
    def _dir_gen(name: str) -> int:
        """Generation number from a ``<base>-g<N>`` T2 dir name (0 for
        pre-generation names, e.g. dirs written by older builds)."""
        _, sep, tail = name.rpartition("-g")
        return int(tail) if sep and tail.isdigit() else 0

    def recover(self) -> List[str]:
        """Post-crash: re-register every manifest-verified T2 entry.
        Torn stages (kill mid-demotion, before the manifest) are left
        on disk but never trusted; when several committed generations
        of the same entry survive, the newest wins and the superseded
        dirs are removed."""
        found: List[str] = []
        if not self.disk_dir or not os.path.isdir(self.disk_dir):
            return found
        best: Dict[str, Tuple[float, int, TierEntry]] = {}
        for name in sorted(os.listdir(self.disk_dir)):
            target = os.path.join(self.disk_dir, name)
            if not (name.startswith("sess_") and os.path.isdir(target)):
                continue
            # verify_manifest() accepts a manifest-less dir as a legacy
            # tag; for tier stages no manifest means torn mid-demotion,
            # so require the commit marker explicitly
            if not os.path.exists(os.path.join(target, atomic.MANIFEST_FILE)):
                logger.warning(
                    f"kvcache: ignoring torn tier stage at {target}")
                continue
            ok, _ = atomic.verify_manifest(target)
            meta_path = os.path.join(target, META_FILE)
            if not ok or not os.path.exists(meta_path):
                logger.warning(
                    f"kvcache: ignoring unverifiable tier entry at {target}")
                continue
            with open(meta_path) as f:
                meta = json.load(f)
            tokens = np.asarray(meta.get("tokens", []), np.int32)
            if tokens.shape[0] < 1:
                continue
            n_pages = _pages_for(tokens.shape[0], self.pool.page_len)
            if meta.get("kind", "session") == "prefix":
                e = TierEntry(
                    key=self._pkey(tokens), kind="prefix", tokens=tokens,
                    n_pages=n_pages, tier=_DISK, dir_name=name,
                    pinned=bool(meta.get("pinned", False)),
                )
            else:
                sid = meta["session_id"]
                e = TierEntry(
                    key=self._skey(sid), kind="session", tokens=tokens,
                    n_pages=n_pages, tier=_DISK, dir_name=name,
                    session_id=sid,
                    parked_at=float(meta.get("parked_at", 0.0)),
                )
            rank = (e.parked_at, self._dir_gen(name))
            prev = best.get(e.key)
            if prev is not None and (prev[0], prev[1]) >= rank:
                self._remove_dir(name)  # committed but superseded
                continue
            if prev is not None:
                self._remove_dir(prev[2].dir_name)
            best[e.key] = (rank[0], rank[1], e)
        with self._lock:
            for _, gen, e in best.values():
                self._dirgen = max(self._dirgen, gen)
                if e.key not in self._entries:
                    self._register(e)
                    found.append(e.key)
        return sorted(found)

    def close(self) -> None:
        self._worker.close()

    # -- introspection ---------------------------------------------------
    def inflight(self) -> int:
        return self._worker.pending()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            host = [e for e in self._entries.values() if e.tier == _HOST]
            disk = [e for e in self._entries.values() if e.tier == _DISK]
            total = self.swap_seconds_total
            hidden = self.swap_seconds_hidden
            return {
                "host_entries": len(host),
                "host_pages": sum(e.n_pages for e in host),
                "host_bytes": sum(e.host_bytes for e in host),
                "disk_entries": len(disk),
                "disk_pages": sum(e.n_pages for e in disk),
                "demote_t0_t1": self.demote_t0_t1,
                "demote_t1_t2": self.demote_t1_t2,
                "promote_t1_t0": self.promote_t1_t0,
                "promote_t2_t1": self.promote_t2_t1,
                "promote_t2_t0": self.promote_t2_t0,
                "tail_demotions": self.tail_demotions,
                "tail_promotions": self.tail_promotions,
                "hits_t1": self.hits_t1,
                "hits_t2": self.hits_t2,
                "tier_misses": self.misses,
                "tier_drops": self.drops,
                "prefetch_jobs": self.prefetch_jobs,
                "inflight": self._worker.pending(),
                "swap_seconds_total": total,
                "swap_seconds_hidden": hidden,
                "swap_hidden_ratio": (hidden / total) if total > 0 else 1.0,
            }
