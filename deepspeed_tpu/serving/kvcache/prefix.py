"""Host-side prefix index: a radix tree over token ids mapping a new
prompt's longest cached prefix to refcounted read-only page lists
(docs/serving.md §Paged KV & prefix caching).

The tree is edge-compressed (each edge carries a run of token ids);
entries terminate exactly at nodes, and :meth:`insert` splits edges so
that invariant holds.  Lookup walks the prompt and returns the deepest
entry whose key is a prefix of it — O(prompt_len) regardless of how
many prefixes are cached.  The index is pure host bookkeeping: page
refcounts live in :class:`~deepspeed_tpu.serving.kvcache.pages.PagedKVPool`,
which holds one reference per entry so a cached prefix's pages survive
slot churn until the entry is evicted.

Entries learned from traffic are evictable LRU-style under pool
pressure; entries seeded from ``serving.kvcache.pinned_prefixes`` are
``pinned`` and never evicted.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class PrefixEntry:
    """One cached prefix: ``tokens`` (the key) and the device pages
    holding its KV.  ``pages`` covers ``ceil(len(tokens) / page_len)``
    pages; the last page may be partially filled — readers copy-on-write
    it before writing (the COW invariant)."""

    tokens: np.ndarray  # (n,) int32
    pages: List[int]
    pinned: bool = False
    hits: int = 0
    last_used: float = 0.0
    # set only on the tier manager's shadow-index entries: the tier
    # store key holding this prefix's off-device KV (pages is [] there)
    tier_key: str = ""

    @property
    def length(self) -> int:
        return int(self.tokens.shape[0])

    def key(self) -> bytes:
        return self.tokens.tobytes()


class _Node:
    __slots__ = ("edge", "children", "entry")

    def __init__(self, edge: Tuple[int, ...] = ()):
        self.edge = edge  # token run from the parent to this node
        self.children: Dict[int, "_Node"] = {}  # first token -> child
        self.entry: Optional[PrefixEntry] = None


def _common_len(a: Tuple[int, ...], b: np.ndarray, off: int) -> int:
    n = min(len(a), b.shape[0] - off)
    i = 0
    while i < n and a[i] == int(b[off + i]):
        i += 1
    return i


class PrefixIndex:
    """Radix tree over int32 token ids with an entry table for O(1)
    exact lookup / removal and LRU eviction scans."""

    def __init__(self):
        self._root = _Node()
        self._entries: Dict[bytes, PrefixEntry] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def entries(self) -> Iterable[PrefixEntry]:
        return self._entries.values()

    def get(self, tokens: np.ndarray) -> Optional[PrefixEntry]:
        return self._entries.get(np.asarray(tokens, np.int32).tobytes())

    # -- insert -----------------------------------------------------------
    def insert(self, entry: PrefixEntry) -> PrefixEntry:
        """Insert ``entry`` keyed on its tokens.  If the key is already
        present the existing entry is returned unchanged (first writer
        wins — its pages are already refcounted) and the caller must
        release the duplicate's pages."""
        tokens = np.asarray(entry.tokens, np.int32).reshape(-1)
        if tokens.shape[0] < 1:
            raise ValueError("prefix entry must contain at least one token")
        entry.tokens = tokens
        existing = self._entries.get(entry.key())
        if existing is not None:
            return existing
        node, off = self._root, 0
        while off < tokens.shape[0]:
            first = int(tokens[off])
            child = node.children.get(first)
            if child is None:
                leaf = _Node(tuple(int(t) for t in tokens[off:]))
                node.children[first] = leaf
                node = leaf
                off = tokens.shape[0]
                break
            n = _common_len(child.edge, tokens, off)
            if n == len(child.edge):
                node, off = child, off + n
                continue
            # split child's edge at n: node -> mid -> child
            mid = _Node(child.edge[:n])
            child.edge = child.edge[n:]
            mid.children[child.edge[0]] = child
            node.children[first] = mid
            node, off = mid, off + n
        if off < tokens.shape[0]:  # pragma: no cover - loop always lands
            raise AssertionError("radix insert did not consume the key")
        if node.entry is not None:
            return node.entry
        node.entry = entry
        self._entries[entry.key()] = entry
        return entry

    # -- lookup -----------------------------------------------------------
    def lookup(self, prompt: np.ndarray, now: float = 0.0,
               stamp: bool = True) -> Optional[PrefixEntry]:
        """Deepest entry whose key is a prefix of ``prompt``; stamps
        ``hits``/``last_used`` on the winner unless ``stamp=False``
        (the admission controller's side-effect-free hint path)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        best: Optional[PrefixEntry] = None
        node, off = self._root, 0
        while off < prompt.shape[0]:
            child = node.children.get(int(prompt[off]))
            if child is None:
                break
            n = _common_len(child.edge, prompt, off)
            if n < len(child.edge):
                break  # partial edge match: no entry can end mid-edge
            node, off = child, off + n
            if node.entry is not None:
                best = node.entry
        if best is not None and stamp:
            best.hits += 1
            best.last_used = now
        return best

    def common_prefix_len(self, prompt: np.ndarray) -> int:
        """Longest common prefix between ``prompt`` and ANY stored key —
        deeper than :meth:`lookup`, which only sees runs that terminate
        at an entry.  This is the split point a new prompt shares with
        cached traffic (mid-edge included); the pool learns that run as
        its own entry, which is how a common system prompt becomes
        reusable across requests without being pinned."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        node, off = self._root, 0
        while off < prompt.shape[0]:
            child = node.children.get(int(prompt[off]))
            if child is None:
                break
            n = _common_len(child.edge, prompt, off)
            off += n
            if n < len(child.edge):
                break
            node = child
        return off

    # -- eviction ---------------------------------------------------------
    def remove(self, entry: PrefixEntry) -> bool:
        """Drop an entry (its node stays; edges are not re-merged — the
        tree only ever holds as many nodes as tokens inserted)."""
        found = self._entries.pop(entry.key(), None)
        if found is None:
            return False
        node, off = self._root, 0
        tokens = entry.tokens
        while off < tokens.shape[0]:
            child = node.children.get(int(tokens[off]))
            if child is None:
                return True
            n = _common_len(child.edge, tokens, off)
            if n < len(child.edge):
                return True
            node, off = child, off + n
        node.entry = None
        return True

    def evict_candidates(self) -> List[PrefixEntry]:
        """Unpinned entries, coldest first (LRU by ``last_used``, ties
        broken by fewer hits then shorter keys)."""
        return sorted(
            (e for e in self._entries.values() if not e.pinned),
            key=lambda e: (e.last_used, e.hits, e.length),
        )
