"""ServingWatchdog: SIGTERM → stop admission → drain → exit 43.

PR 2's :class:`~deepspeed_tpu.resilience.watchdog.PreemptionWatchdog`
contract, wired into the serving plane (docs/serving.md §Resilience).
The training engine answers preemption with an emergency checkpoint;
the serving engine's equivalent durable state is the request journal —
so the drain sequence is:

1. the signal handler only flags (async-signal-safe; a *repeated*
   signal escalates through the inner watchdog's restore-and-redeliver
   escape hatch, exactly like training);
2. ``submit()`` starts rejecting with :class:`ServingDraining` the
   moment the flag is up — admission stops before the next step;
3. the next ``step()`` enters the drain loop: in-flight requests keep
   decoding (no new admissions) until the live set empties or
   ``drain_deadline_seconds`` runs out;
4. undone work — still-queued requests plus in-flight requests the
   deadline cut off — is already durable in the journal (submit records
   commit at acknowledgement); a final ``drain`` record is appended and
   the journal commits;
5. **exit 43 certifies the commit**: with a journal, 43 is raised only
   after ``commit()`` returns (a failed commit quarantines and exits
   1); without a journal, 43 requires a complete drain (undone work
   with nowhere durable to live is exit 1, the crash contract — resume
   has nothing to replay from).

The engine drives :meth:`ServingEngine.install_watchdog`; tests drive
the same path by delivering a real ``SIGTERM`` to the process.
"""
from __future__ import annotations

import signal
from typing import Optional, Tuple

from deepspeed_tpu.resilience.watchdog import EXIT_PREEMPTED_SAVED, PreemptionWatchdog

_DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class ServingWatchdog:
    """Thin composition over :class:`PreemptionWatchdog`: same signal
    plumbing, flag-only handler, grace window and escalation — the
    serving engine polls :attr:`draining` and runs the drain itself at
    its step boundary (``engine._drain_and_exit``)."""

    def __init__(
        self,
        drain_deadline_seconds: float = 30.0,
        exit_code: int = EXIT_PREEMPTED_SAVED,
        signals: Tuple[signal.Signals, ...] = _DEFAULT_SIGNALS,
    ):
        self._inner = PreemptionWatchdog(
            grace_seconds=drain_deadline_seconds,
            exit_code=exit_code,
            signals=signals,
        )

    # -- lifecycle --------------------------------------------------------
    def install(self) -> "ServingWatchdog":
        self._inner.install()
        return self

    def uninstall(self) -> None:
        self._inner.uninstall()

    __enter__ = install

    def __exit__(self, *exc_info) -> None:
        self.uninstall()

    # -- state ------------------------------------------------------------
    @property
    def draining(self) -> bool:
        """A drain signal has been received (admission must reject)."""
        return self._inner.preemption_requested

    @property
    def exit_code(self) -> int:
        return self._inner.exit_code

    @property
    def drain_deadline_seconds(self) -> float:
        return self._inner.grace_seconds

    @property
    def signal_name(self) -> str:
        return self._inner.signal_name

    @property
    def requested_at(self) -> Optional[float]:
        return self._inner.requested_at

    def remaining(self) -> float:
        """Seconds of drain budget left (+inf when no drain pending)."""
        return self._inner.remaining()

    def reset(self) -> None:
        self._inner.reset()


__all__ = ["ServingWatchdog"]
