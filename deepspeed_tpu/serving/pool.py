"""Slot-pool KV cache: a fixed-shape device cache + a host-side slot
allocator.

The pool is ONE pair of ``(layers, num_slots, heads, max_len, head_dim)``
cache buffers (bf16/f32, or the int8 code+scale pair reusing the
``init_kv_cache`` int8 machinery) whose **slot axis is the batch axis**
of the fused inference blocks: every compiled serving step sees the same
shapes no matter which subset of slots is live, so admitting or retiring
a sequence never changes an abstract signature — the no-recompile
property the whole continuous-batching design rests on (docs/serving.md).

The allocator is pure host bookkeeping: ``alloc()`` hands out the
longest-free slot (FIFO over frees, so reuse is fair and stale-cache
paths get exercised), ``free()`` returns it.  Freeing does NOT touch
device memory — a freed slot's stale keys/values are unreachable by
construction (the next occupant's writes start at position 0 and the
position mask only ever exposes positions the occupant itself wrote;
see the overwrite-before-attend invariant in docs/serving.md).
"""
from __future__ import annotations

from collections import deque
from typing import Any, Deque, Dict, Optional

import jax
import numpy as np


class SlotPoolError(RuntimeError):
    pass


class SlotKVPool:
    """Fixed-shape KV slot pool + host-side allocator.

    ``kv_dtype`` follows ``init_kv_cache``: a jnp dtype for the plain
    cache or ``"int8"`` for the quantized code+scale pair.  The device
    buffers live in ``self.k`` / ``self.v``; the serving engine donates
    them through its compiled steps and rebinds the outputs via
    :meth:`swap`.
    """

    def __init__(self, n_layer: int, num_slots: int, heads: int, max_len: int,
                 head_dim: int, kv_dtype: Any, sharding: Any = None):
        from deepspeed_tpu.ops.transformer.inference import init_kv_cache

        if num_slots < 1:
            raise SlotPoolError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 1:
            raise SlotPoolError(f"max_len must be >= 1, got {max_len}")
        self.n_layer = int(n_layer)
        self.num_slots = int(num_slots)
        self.heads = int(heads)
        self.max_len = int(max_len)
        self.head_dim = int(head_dim)
        self.kv_dtype = kv_dtype
        self.k, self.v = init_kv_cache(n_layer, num_slots, heads, max_len, head_dim, kv_dtype)
        if sharding is not None:
            # place on the serving mesh up front — otherwise the first
            # compiled step reshards the pool implicitly (a transfer the
            # ds_san guard rightly flags)
            self.k, self.v = jax.device_put((self.k, self.v), sharding)
        self._free: Deque[int] = deque(range(num_slots))
        self._owner: Dict[int, Any] = {}  # slot -> request id

    # -- allocator --------------------------------------------------------
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def live_slots(self) -> int:
        return self.num_slots - len(self._free)

    def owner(self, slot: int) -> Optional[Any]:
        return self._owner.get(slot)

    def owners(self) -> Dict[int, Any]:
        """Snapshot of slot -> request id (the serving drain logs the
        in-flight set a deadline cut off; a copy, safe to iterate while
        the scheduler retires)."""
        return dict(self._owner)

    def alloc(self, request_id: Any) -> Optional[int]:
        """Claim a slot for ``request_id``; None when the pool is full.
        A request id may own at most one slot — a second alloc under the
        same id would orphan the first slot's bookkeeping (its free()
        could land on either slot), so it raises instead."""
        if request_id in self._owner.values():
            raise SlotPoolError(
                f"request {request_id!r} already owns a slot; "
                f"free it before re-allocating"
            )
        if not self._free:
            return None
        slot = self._free.popleft()
        self._owner[slot] = request_id
        return slot

    def free(self, slot: int) -> None:
        if slot not in self._owner:
            raise SlotPoolError(f"slot {slot} is not allocated")
        del self._owner[slot]
        self._free.append(slot)

    # -- device buffers ---------------------------------------------------
    def swap(self, k, v) -> None:
        """Rebind the cache buffers after a donated compiled step (the
        old arrays were consumed by the donation)."""
        self.k, self.v = k, v

    def cache_bytes(self) -> int:
        """HBM bytes held by the pool (both caches, all leaves)."""
        return int(
            sum(l.size * l.dtype.itemsize for l in jax.tree.leaves((self.k, self.v)))
        )

    def shape_math(self) -> str:
        """Human-readable pool sizing (ds_report serving rows)."""
        kind = "int8+f32 scales" if isinstance(self.k, dict) else str(np.dtype(
            jax.tree.leaves(self.k)[0].dtype))
        return (
            f"2 x ({self.n_layer} layers x {self.num_slots} slots x "
            f"{self.heads} heads x {self.max_len} positions x "
            f"{self.head_dim} head_dim) [{kind}] = "
            f"{self.cache_bytes() / 1e6:.1f} MB"
        )
