"""ServingEngine: continuous-batching decode against one compiled
executable.

Sits on top of an :class:`~deepspeed_tpu.inference.engine.InferenceEngine`
(whose params/mesh/dtype it reuses) and replaces the closed
``generate()`` loop with a request stream:

* ``submit()`` — admission-controlled (queue bound, per-request
  queue-wait deadlines, capacity validation with the derived numbers);
* ``step()`` — one scheduler tick: expire/admit, up to
  ``prefill_chunks_per_step`` prompt chunks, then ONE decode step over
  the whole slot pool;
* ``drain()`` — run until every request finishes, return the results.

Exactly **two** executables serve any churning live set: a prefill-chunk
step (fixed ``(1, prefill_chunk)`` tokens, traced slot + position
scalars) and a decode step (fixed ``(num_slots, 1)`` tokens, traced
per-slot position vector).  Admitting, retiring, or chunk-advancing
sequences only changes *values*, never abstract signatures — proven
under an armed ds_san run (tests/test_serving.py) rather than asserted.
Both executables donate the cache pool, so the slot cache is updated
in place.  Decoding is greedy by default (``generate(do_sample=False)``
bit-parity); per-request sampling (``submit(do_sample=True,
temperature=..., top_k=..., seed=...)``) rides the same fixed signature
as per-slot vectors — temperature/top-k/seed per slot, keys derived
from (seed, position) so outputs are reproducible regardless of slot
assignment or pool churn.
"""
from __future__ import annotations

import dataclasses
import time
from contextlib import nullcontext
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu import telemetry as _telemetry
from deepspeed_tpu.analysis.shard import hooks as shard_hooks
from deepspeed_tpu.config.config import ServingConfig
from deepspeed_tpu.resilience import faults
from deepspeed_tpu.serving.journal import JournalError, RequestJournal
from deepspeed_tpu.serving.kvcache import PagedKVPool
from deepspeed_tpu.serving.pool import SlotKVPool
from deepspeed_tpu.serving.scheduler import (
    PRIORITY_NORMAL,
    ContinuousScheduler,
    PrefillJob,
    Request,
    ServingDraining,
    ServingOverloaded,
    ServingQueueFull,
    advance_request_ids,
)
from deepspeed_tpu.serving.watchdog import ServingWatchdog
from deepspeed_tpu.utils.logging import log_dist, logger


class ServingEngine:
    def __init__(self, engine, config: Any = None, **overrides):
        """``engine``: a built InferenceEngine (GPT family).  ``config``:
        a :class:`ServingConfig`, a raw ``serving`` config dict, or None;
        ``overrides`` replace individual fields (``num_slots=2, ...``)."""
        if config is None:
            config = ServingConfig()
        elif isinstance(config, dict):
            config = ServingConfig.from_dict(config)
        if overrides:
            config = dataclasses.replace(config, **overrides)
        # re-validate unconditionally: a directly-constructed
        # ServingConfig (or replace()d fields) never went through
        # from_dict's chunk-multiple / dtype checks
        config = ServingConfig.from_dict(dataclasses.asdict(config))
        if not engine._is_gpt:
            raise ValueError("ServingEngine requires a causal-LM (GPT-family) InferenceEngine")
        self.engine = engine
        self.config = config
        mcfg = engine.model_config

        capacity = engine.generation_capacity
        if config.max_len:
            if config.max_len > capacity:
                raise ValueError(
                    f"serving.max_len={config.max_len} exceeds the engine's "
                    f"generation capacity min(max_out_tokens={engine.max_out_tokens}, "
                    f"n_positions={mcfg.n_positions}) = {capacity}"
                )
            max_len = config.max_len
            from deepspeed_tpu.ops import kernels as _kernels_mod

            if _kernels_mod.flash_decode_armed() and max_len % 128:
                logger.warning(
                    f"serving.max_len={max_len} is not a multiple of 128, so "
                    "the fused flash-decode kernel cannot serve this pool "
                    "(decode falls back to the lax dequant path; "
                    "docs/kernels.md) — align max_len to 128 to arm it"
                )
        else:
            # derive: the engine capacity floored to a chunk multiple
            # (chunk-multiple capacity guarantees the last prefill
            # chunk's write never clamps — docs/serving.md)
            max_len = (capacity // config.prefill_chunk) * config.prefill_chunk
            if max_len < 1:
                raise ValueError(
                    f"serving.prefill_chunk={config.prefill_chunk} exceeds the "
                    f"engine's generation capacity {capacity}; lower the chunk "
                    f"or raise max_out_tokens"
                )
            from deepspeed_tpu.ops import kernels as _kernels_mod

            if _kernels_mod.flash_decode_armed() and max_len % 128:
                # flash-decode kernel grid wants S % 128 == 0: floor the
                # derived capacity to a (chunk, 128) common multiple so
                # the decode hot path actually takes the kernel; keep
                # the chunk floor when the capacity is too small for one
                import math

                step = math.lcm(config.prefill_chunk, 128)
                aligned = (capacity // step) * step
                if aligned >= config.prefill_chunk:
                    log_dist(
                        f"serving: derived max_len {max_len} -> {aligned} "
                        "(floored to the flash-decode kernel's "
                        f"lcm(chunk={config.prefill_chunk}, 128)={step} grid; "
                        "set serving.max_len explicitly to keep the larger "
                        "capacity on the lax path — docs/kernels.md)"
                    )
                    max_len = aligned
                else:
                    logger.warning(
                        f"serving: derived max_len={max_len} cannot align to "
                        "the flash-decode kernel's 128-row grid within the "
                        f"engine capacity {capacity}; decode falls back to "
                        "the lax path (docs/kernels.md)"
                    )
        kv_dtype = "int8" if config.kv_cache_dtype == "int8" else engine._kv_dtype
        from deepspeed_tpu.sharding.layout import replicated_sharding

        self._replicated = replicated_sharding(engine.mesh)
        kvc = config.kvcache
        self._paged = bool(kvc.enabled)
        if self._paged:
            import math

            if config.max_len:
                if max_len % kvc.page_len:
                    raise ValueError(
                        f"serving.max_len={max_len} must be a multiple of "
                        f"serving.kvcache.page_len={kvc.page_len} — the paged "
                        "pool maps slots as whole pages (docs/serving.md "
                        "§Paged KV & prefix caching)"
                    )
            else:
                # re-floor the derived capacity to a (chunk, page_len)
                # common multiple: chunk-multiple keeps the last prefill
                # write from clamping, page-multiple keeps slots whole
                step = math.lcm(config.prefill_chunk, kvc.page_len)
                aligned = (capacity // step) * step
                if aligned < config.prefill_chunk:
                    raise ValueError(
                        f"serving.kvcache.page_len={kvc.page_len} cannot align "
                        f"to the engine capacity {capacity} within "
                        f"lcm(prefill_chunk={config.prefill_chunk}, page_len)="
                        f"{step}; lower page_len or raise max_out_tokens"
                    )
                if aligned != max_len:
                    log_dist(
                        f"serving: derived max_len {max_len} -> {aligned} "
                        f"(floored to lcm(chunk={config.prefill_chunk}, "
                        f"page_len={kvc.page_len})={step} for the paged pool)"
                    )
                    max_len = aligned
            self.pool = PagedKVPool(
                mcfg.n_layer, config.num_slots, mcfg.n_head, max_len,
                mcfg.head_dim, kv_dtype, page_len=kvc.page_len,
                num_pages=(kvc.num_pages or None), sharding=self._replicated,
                prefill_chunk=config.prefill_chunk,
                pinned_prefixes=kvc.pinned_prefixes,
                session_ttl_seconds=kvc.session_ttl_seconds,
                spill_dir=(kvc.spill_dir or None),
            )
        else:
            self.pool = SlotKVPool(
                mcfg.n_layer, config.num_slots, mcfg.n_head, max_len, mcfg.head_dim,
                kv_dtype, sharding=self._replicated,
            )
        self.scheduler = ContinuousScheduler(
            self.pool,
            prefill_chunk=config.prefill_chunk,
            prefill_chunks_per_step=config.prefill_chunks_per_step,
            max_queue=config.max_queue,
            deadline_seconds=config.deadline_seconds,
            capacity=min(max_len, capacity),
            slo_ttft_ms=config.slo_ttft_ms,
            degrade_queue_watermark=config.degrade_queue_watermark,
            degrade_engage_steps=config.degrade_engage_steps,
            degrade_disengage_steps=config.degrade_disengage_steps,
            degrade_max_new_tokens=config.degrade_max_new_tokens,
        )
        # the admission controller's measured-service-rate feed: the
        # telemetry registry's recent window when the plane is armed,
        # the engine's local EWMA otherwise (scheduler stays jax-free)
        self.scheduler.step_seconds_fn = self._measured_step_seconds
        self._step_wall_ewma: Optional[float] = None

        # client_key -> request id (the fleet router's at-most-once
        # admission map; seeded from the journal when one is armed)
        self._client_keys: Dict[str, int] = {}

        # write-ahead request journal (docs/serving.md §Resilience):
        # "" = off.  A construction failure disables journaling rather
        # than the engine — availability over durability, loudly.
        self._journal: Optional[RequestJournal] = None
        if config.journal_dir:
            try:
                self._journal = RequestJournal(
                    config.journal_dir,
                    segment_records=config.journal_segment_records,
                    keep_segments=config.journal_keep_segments,
                )
                # id-reuse guard: a restarted process submitting BEFORE
                # recover() must not hand out a journaled incomplete id
                # (its retire record would drop the old acknowledged
                # request from the replay set)
                advance_request_ids(self._journal.last_request_id)
                # at-most-once admission: journaled client keys survive
                # a restart, so a duplicate resubmit dedups here too
                self._client_keys.update(self._journal.client_keys)
            except OSError as e:
                logger.error(
                    f"serving: request journal at {config.journal_dir!r} failed "
                    f"to open ({e!r}); journaling DISABLED — a crash loses "
                    "in-flight and queued requests"
                )
        self._watchdog: Optional[ServingWatchdog] = None
        self._journal_quarantined: Optional[str] = None

        from deepspeed_tpu.runtime.overlap.timeline import StepTimeline

        self.timeline = StepTimeline(enabled=True, phases=("sched", "prefill", "decode"))

        # telemetry (docs/telemetry.md): attach to whatever plane the
        # process armed (the train engine's configure(), or an explicit
        # telemetry.configure() from bench_serving / the smoke tool) —
        # a no-config process gets no-op publishes.  The scheduler's
        # lifecycle events become per-request spans + TTFT/TPOT
        # histograms; step phases ride the timeline attachment.
        # NB arm the plane BEFORE constructing engines: the timeline
        # attachment and the manager's SLO config are captured here —
        # a later configure() reaches the registry/tracer flags but not
        # these construction-time decisions.
        self.telemetry = _telemetry.manager_for("serving")
        self._tel_ttft = self.telemetry.histogram("serving/ttft_ms")
        self._tel_tpot = self.telemetry.histogram("serving/tpot_ms")
        self._tel_queue_wait = self.telemetry.histogram("serving/queue_wait_ms")
        if self.telemetry.collect or self.telemetry.tracer.enabled:
            self.timeline.attach_telemetry(self.telemetry, prefix="serving")
        self.scheduler.on_event = self._on_request_event

        from deepspeed_tpu.analysis.sanitizer import maybe_from_config

        self._sanitizer = maybe_from_config(None)
        self._prefill_fn = None
        self._prefill_jit = None  # unwrapped jit handle (ds_shard audit)
        self._decode_fn = None
        self._decode_jit = None  # unwrapped jit handle (attribute_decode)
        self.prefill_compiles = 0
        self.decode_compiles = 0
        self._step_count = 0
        # kvcache event watermarks: deltas become Perfetto instants
        self._kv_evt_seen = {"evictions": 0, "session_spills": 0}
        # hierarchical KV tiering (docs/serving.md §KV tiering): the
        # tier manager's migration worker moves T1<->T2 in the
        # background; the engine thread drives T0<->T1 through tick()
        # at step boundaries (and from stats()/drain(), so an idle
        # engine still drains pending demotions)
        self._tiers = None
        if self._paged and kvc.tiers.enabled:
            from deepspeed_tpu.serving.kvcache.tiers import PageTierManager

            self._tiers = PageTierManager(
                self.pool,
                host_pages=kvc.tiers.host_pages,
                disk_dir=(kvc.tiers.disk_dir or None),
                residency_window=kvc.tiers.residency_window,
                demote_watermark=kvc.tiers.demote_watermark,
                prefetch_ahead=kvc.tiers.prefetch_ahead,
                demote_batch=kvc.tiers.demote_batch,
            )
            self._tiers.telemetry = self.telemetry
            self.pool.attach_tiers(self._tiers)
        # multi-tenant dimension (docs/serving.md §Front-door): rate
        # limits + weighted-fair queueing + SLO classes + KV quotas +
        # billing-grade accounting, all keyed by submit(tenant=...)
        self.tenants = None
        tcfg = getattr(config, "tenants", None)
        if tcfg is not None and tcfg.enabled:
            from deepspeed_tpu.serving.frontdoor.tenants import TenantRegistry

            self.tenants = TenantRegistry(tcfg)
            self.scheduler.tenants = self.tenants
            attach = getattr(self.pool, "attach_tenants", None)
            if attach is not None:
                attach(self.tenants)
        log_dist(
            f"serving engine: {config.num_slots} slots x {max_len} positions "
            f"(kv={'int8' if kv_dtype == 'int8' else jnp.dtype(kv_dtype).name}, "
            f"chunk={config.prefill_chunk}, pool {self.pool.cache_bytes() / 1e6:.1f} MB)"
        )

    # ------------------------------------------------------------------
    # compiled steps (built once; churn only changes traced values)
    # ------------------------------------------------------------------
    def _wrap(self, fn, site: str):
        """Sanitizer recompile proof: when armed, every call's abstract
        signature is checked — a second signature at either site is a
        recorded recompile (the compile-stability tests gate on this).
        Owner-scoped so several serving engines in one armed process
        (the bench sweeps builds 8) each keep their first-compile grace."""
        san = self._sanitizer
        if san is not None:
            return san.recompile.wrap(fn, site=site, owner=id(self))
        return fn

    def _get_prefill(self):
        if self._prefill_fn is None:
            from deepspeed_tpu.inference.engine import sample_logits_pooled
            from deepspeed_tpu.ops.transformer.inference import forward_with_cache

            icfg = self.engine.inference_config(self.pool.max_len)
            n_pos = self.engine.model_config.n_positions
            chunk = self.config.prefill_chunk
            max_top_k = self.config.max_top_k

            def _take_slot(c, slot):
                return jax.tree.map(
                    lambda a: jax.lax.dynamic_slice(
                        a, (0, slot, 0, 0, 0), (a.shape[0], 1) + a.shape[2:]
                    ),
                    c,
                )

            def _put_slot(c, cs, slot):
                return jax.tree.map(
                    lambda a, b: jax.lax.dynamic_update_slice(a, b, (0, slot, 0, 0, 0)),
                    c, cs,
                )

            if self._paged:
                def fn(params, toks, table, pos, take_idx, cow_src, cow_dst,
                       flag, temp, topk, seed, k_pool, v_pool):
                    # the slot's pending copy-on-write lands BEFORE this
                    # chunk's writes: a traced (src, dst) page pair rides
                    # the request's first chunk ((0, 0) — garbage page
                    # onto itself — is the identity when nothing pends)
                    cow = lambda b: b.at[:, cow_dst].set(b[:, cow_src])  # noqa: E731
                    k_pool = jax.tree.map(cow, k_pool)
                    v_pool = jax.tree.map(cow, v_pool)
                    position_ids = jnp.clip(
                        pos + jnp.arange(chunk, dtype=jnp.int32), 0, n_pos - 1
                    )[None, :]
                    logits, k_pool, v_pool = forward_with_cache(
                        params, toks, k_pool, v_pool, pos[None], icfg,
                        position_ids=position_ids, page_table=table[None, :],
                    )
                    key = jax.random.fold_in(
                        jax.random.PRNGKey(seed), pos + take_idx
                    )
                    first = sample_logits_pooled(
                        logits[0, take_idx].astype(jnp.float32)[None, :],
                        key[None], flag[None], temp[None], topk[None],
                        max_top_k,
                    )[0]
                    return first, k_pool, v_pool

                donate = (11, 12)
            else:
                def fn(params, toks, slot, pos, take_idx, flag, temp, topk, seed, k_pool, v_pool):
                    ks, vs = _take_slot(k_pool, slot), _take_slot(v_pool, slot)
                    # explicit clipped position ids: the zero-padded chunk
                    # tail must not clamp the wpe slice and shift real rows
                    position_ids = jnp.clip(
                        pos + jnp.arange(chunk, dtype=jnp.int32), 0, n_pos - 1
                    )[None, :]
                    logits, ks, vs = forward_with_cache(
                        params, toks, ks, vs, pos, icfg, position_ids=position_ids
                    )
                    # the first generated token samples with the request's
                    # params (the same key schedule as decode: key = seed
                    # folded with the fed token's cache position)
                    key = jax.random.fold_in(jax.random.PRNGKey(seed), pos + take_idx)
                    first = sample_logits_pooled(
                        logits[0, take_idx].astype(jnp.float32)[None, :],
                        key[None],
                        flag[None],
                        temp[None],
                        topk[None],
                        max_top_k,
                    )[0]
                    return first, _put_slot(k_pool, ks, slot), _put_slot(v_pool, vs, slot)

                donate = (9, 10)

            self._prefill_jit = jax.jit(self.engine._scoped(fn), donate_argnums=donate)
            self._prefill_fn = self._wrap(self._prefill_jit, "serving.prefill")
            self.prefill_compiles += 1
            # ds_shard Pass 2 feed (no-op unless the audit armed it)
            shard_hooks.note_serving(
                self, "serving.prefill", self._prefill_jit,
                self._prefill_abstract_args(),
            )
        return self._prefill_fn

    def _get_decode(self):
        if self._decode_fn is None:
            from deepspeed_tpu.inference.engine import sample_logits_pooled
            from deepspeed_tpu.ops.transformer.inference import forward_with_cache

            icfg = self.engine.inference_config(self.pool.max_len)
            max_top_k = self.config.max_top_k

            if self._paged:
                def fn(params, toks, pos, flags, temps, topks, seeds,
                       page_table, write_mask, k_pool, v_pool):
                    # per-slot page tables are traced values of the one
                    # fixed signature; write_mask redirects non-decoding
                    # slots' writes to the garbage page (pages.py)
                    logits, k_pool, v_pool = forward_with_cache(
                        params, toks[:, None], k_pool, v_pool, pos, icfg,
                        page_table=page_table, write_mask=write_mask,
                    )
                    keys = jax.vmap(
                        lambda s, p: jax.random.fold_in(jax.random.PRNGKey(s), p)
                    )(seeds, pos)
                    nxt = sample_logits_pooled(
                        logits[:, -1].astype(jnp.float32), keys, flags, temps,
                        topks, max_top_k,
                    )
                    return nxt, k_pool, v_pool

                donate = (9, 10)
            else:
                def fn(params, toks, pos, flags, temps, topks, seeds, k_pool, v_pool):
                    # per-slot pos: slot-indexed cache write + position mask
                    # (ops/transformer/inference.py), auto-clipped position ids
                    logits, k_pool, v_pool = forward_with_cache(
                        params, toks[:, None], k_pool, v_pool, pos, icfg
                    )
                    # per-(request seed, position) keys: reproducible per
                    # request regardless of slot assignment or pool churn
                    keys = jax.vmap(
                        lambda s, p: jax.random.fold_in(jax.random.PRNGKey(s), p)
                    )(seeds, pos)
                    nxt = sample_logits_pooled(
                        logits[:, -1].astype(jnp.float32), keys, flags, temps, topks,
                        max_top_k,
                    )
                    return nxt, k_pool, v_pool

                donate = (7, 8)

            self._decode_jit = jax.jit(self.engine._scoped(fn), donate_argnums=donate)
            self._decode_fn = self._wrap(self._decode_jit, "serving.decode")
            self.decode_compiles += 1
            # ds_shard Pass 2 feed (no-op unless the audit armed it)
            shard_hooks.note_serving(
                self, "serving.decode", self._decode_jit,
                self._decode_abstract_args(),
            )
        return self._decode_fn

    def _decode_abstract_args(self):
        """The decode executable's argument signature as
        ShapeDtypeStructs (pool-derived, nothing executes) — shared by
        ``attribute_decode`` and the ds_shard collective audit."""
        S = self.pool.num_slots
        abstract = lambda tree: jax.tree.map(  # noqa: E731
            lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), tree
        )
        args = [
            abstract(self.engine.params),
            jax.ShapeDtypeStruct((S,), jnp.int32),   # toks
            jax.ShapeDtypeStruct((S,), jnp.int32),   # pos
            jax.ShapeDtypeStruct((S,), jnp.bool_),   # flags
            jax.ShapeDtypeStruct((S,), jnp.float32),  # temps
            jax.ShapeDtypeStruct((S,), jnp.int32),   # topks
            jax.ShapeDtypeStruct((S,), jnp.uint32),  # seeds
        ]
        if self._paged:
            args += [
                jax.ShapeDtypeStruct((S, self.pool.pages_per_slot), jnp.int32),
                jax.ShapeDtypeStruct((S,), jnp.bool_),  # write_mask
            ]
        args += [abstract(self.pool.k), abstract(self.pool.v)]
        return tuple(args)

    def _prefill_abstract_args(self):
        """The prefill executable's argument signature (one chunk, one
        slot) as ShapeDtypeStructs — the ds_shard audit's AOT feed."""
        abstract = lambda tree: jax.tree.map(  # noqa: E731
            lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), tree
        )
        chunk = self.config.prefill_chunk
        i32 = lambda: jax.ShapeDtypeStruct((), jnp.int32)  # noqa: E731
        args = [abstract(self.engine.params),
                jax.ShapeDtypeStruct((1, chunk), jnp.int32)]
        if self._paged:
            args += [
                jax.ShapeDtypeStruct((self.pool.pages_per_slot,), jnp.int32),
                i32(), i32(), i32(), i32(),  # pos, take_idx, cow_src, cow_dst
            ]
        else:
            args += [i32(), i32(), i32()]   # slot, pos, take_idx
        args += [
            jax.ShapeDtypeStruct((), jnp.bool_),    # do_sample
            jax.ShapeDtypeStruct((), jnp.float32),  # temperature
            jax.ShapeDtypeStruct((), jnp.int32),    # top_k
            jax.ShapeDtypeStruct((), jnp.uint32),   # seed
            abstract(self.pool.k), abstract(self.pool.v),
        ]
        return tuple(args)

    def attribute_decode(self):
        """Per-kernel cost attribution of the decode executable
        (docs/telemetry.md §Attribution): AOT-lower the decode function
        against the pool's own shapes — abstract args only, so nothing
        executes, no slot state is touched, and the sanitizer's
        one-executable recompile proof is unaffected.  Returns an
        :class:`~deepspeed_tpu.telemetry.attribution.Attribution` or
        None when the backend exposes no HLO text."""
        from deepspeed_tpu.telemetry.attribution import attribute_executable

        self._get_decode()  # ensure the jit handle exists
        compiled = self._decode_jit.lower(*self._decode_abstract_args()).compile()
        return attribute_executable(compiled, label="serving_decode")

    # ------------------------------------------------------------------
    # measured service rate (the admission controller's feed)
    # ------------------------------------------------------------------
    def _measured_step_seconds(self) -> Optional[float]:
        """Recent mean serving-step wall in seconds.  THIS engine's EWMA
        (compile steps excluded) wins once it exists; before the first
        measured step, the telemetry registry's process-wide
        ``serving/step_wall_ms`` window (the gauge the timeline
        attachment publishes) seeds a fresh engine in an armed,
        already-serving process.  None on a cold engine — which admits:
        shedding needs evidence."""
        if self._step_wall_ewma:
            return self._step_wall_ewma
        if self.telemetry.collect:
            wm = self.telemetry.gauge("serving/step_wall_ms").window_mean()
            if wm:
                return wm / 1e3
        return None

    # ------------------------------------------------------------------
    # journal plumbing (quarantine-on-failure; docs/serving.md)
    # ------------------------------------------------------------------
    def _journal_record(self, method: str, *args) -> None:
        """Append one record; a failed append quarantines (the journal
        can no longer certify anything) and serving continues."""
        j = self._journal
        if j is None:
            return
        try:
            getattr(j, method)(*args)
        except JournalError as e:
            self._quarantine_journal(e)

    def _journal_commit(self) -> bool:
        """Commit appended records; False (after quarantine) when the
        journal could not certify durability."""
        j = self._journal
        if j is None or not j.dirty:
            return j is not None
        try:
            j.commit()
            return True
        except JournalError as e:
            self._quarantine_journal(e)
            return False

    def _quarantine_journal(self, err: Exception) -> None:
        j, self._journal = self._journal, None
        logger.error(
            f"serving: journal commit failed ({err}); quarantining — serving "
            "continues WITHOUT crash recovery for new work"
        )
        j.quarantine()
        self._journal_quarantined = j.quarantined
        if self.telemetry.collect:
            self.telemetry.counter("serving/journal_quarantined").inc()

    # ------------------------------------------------------------------
    # request API
    # ------------------------------------------------------------------
    def submit(
        self,
        prompt,
        max_new_tokens: Optional[int] = None,
        eos_token_id: Optional[int] = None,
        deadline_seconds: Optional[float] = None,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        seed: int = 0,
        priority: Optional[int] = None,
        client_key: Optional[str] = None,
        session_id: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> int:
        """Enqueue one request; returns its id.  Raises
        :class:`ServingQueueFull` when the queue is at its bound,
        :class:`ServingOverloaded` (with ``retry_after``) when the
        estimated TTFT exceeds ``serving.slo_ttft_ms`` or the
        degradation ladder sheds the tier, :class:`ServingDraining`
        after SIGTERM, and ``ValueError`` when the request cannot ever
        fit the pool.  With a journal armed, the id is returned only
        after the submit record committed — an acknowledged request
        survives a crash.

        ``priority``: 0 high (never TTFT-shed) / 1 normal (default) /
        2 low (first shed under overload).

        Sampling is per-request (``do_sample``/``temperature``/``top_k``/
        ``seed`` become per-slot vectors of the fixed decode signature):
        tokens are reproducible for a given (seed, position) regardless
        of slot assignment or what else shares the pool; greedy requests
        (the default) bit-match solo ``generate(do_sample=False)``.

        ``client_key`` is an idempotency key (docs/serving.md §Fleet):
        a resubmit carrying a key this engine has already acknowledged
        — in memory or in the journal, i.e. across a crash/restart —
        returns the ORIGINAL id without a second admission.

        ``session_id`` (paged pool only; docs/serving.md §Paged KV &
        prefix caching): a finished turn's KV pages park under this id,
        and the next turn whose prompt extends the parked history
        rebinds them — prefill restarts at the first uncached chunk.
        Ignored (beyond journaling) on the slot-contiguous pool.

        ``tenant`` (docs/serving.md §Front-door): the multi-tenant
        dimension.  With ``serving.tenants`` armed, the submit is
        charged against the tenant's token bucket (raises
        :class:`TenantThrottled` with ``retry_after`` past the limit),
        queued under weighted-fair queueing ahead of the priority
        tiers, and — when ``priority`` is not given explicitly — tiered
        by the tenant's SLO class.  The label journals (``tn``), so
        per-tenant accounting reconciles exactly across a crash."""
        if client_key is not None:
            known = self._client_keys.get(client_key)
            if known is not None:
                if self.scheduler.request(known) is not None:
                    return known
                # the original admission was delivered and popped — the
                # dedup window is the request's tracked lifetime, so a
                # retry after discharge is a NEW request (returning the
                # dead id would strand the caller waiting forever)
                del self._client_keys[client_key]
        if do_sample and top_k > self.config.max_top_k:
            raise ValueError(
                f"top_k={top_k} exceeds serving.max_top_k={self.config.max_top_k} "
                "(the static top-k head width of the one compiled decode step); "
                "raise serving.max_top_k or lower the request's top_k"
            )
        if self._watchdog is not None and self._watchdog.draining:
            if self.telemetry.collect:
                self.telemetry.counter("serving/rejected").inc()
            raise ServingDraining(
                f"serving engine is draining ({self._watchdog.signal_name} "
                f"received, {max(self._watchdog.remaining(), 0.0):.1f}s of drain "
                "budget left); retry against the restarted engine",
                retry_after=max(self._watchdog.remaining(), 0.0),
            )
        faults.check("serving.submit")
        effective_max_new = (
            max_new_tokens if max_new_tokens is not None else self.config.max_new_tokens
        )
        if self.tenants is not None:
            # SLO class → priority tier (an explicit priority wins),
            # then the token-bucket charge: reserved capacity
            # (prompt + budget), realized usage billed at retire.
            # Raises TenantThrottled (429 semantics) with retry_after.
            priority = self.tenants.priority_for(tenant, priority)
            cost = float(np.asarray(prompt).reshape(-1).shape[0]
                         + int(effective_max_new))
            try:
                self.tenants.admit(tenant, cost, now=time.monotonic())
            except ServingQueueFull:
                if self.telemetry.collect:
                    self.telemetry.counter("serving/rejected").inc()
                    self._tenant_counter(tenant, "throttled").inc()
                raise
        elif priority is None:
            priority = PRIORITY_NORMAL
        try:
            req = self.scheduler.submit(
                prompt,
                max_new_tokens=effective_max_new,
                eos_token_id=eos_token_id,
                deadline_seconds=deadline_seconds,
                do_sample=do_sample,
                temperature=temperature,
                top_k=top_k,
                seed=seed,
                priority=priority,
                client_key=client_key,
                session_id=session_id,
                tenant=tenant,
                now=time.monotonic(),
                step=self._step_count,
            )
        except ServingOverloaded as e:
            if self.telemetry.collect:
                self.telemetry.counter("serving/rejected").inc()
                self.telemetry.counter("serving/shed").inc()
                self.telemetry.histogram("serving/retry_after_s").observe(
                    e.retry_after or 0.0
                )
            if self.tenants is not None:
                self.tenants.note("rejected", tenant)
            raise
        except ServingQueueFull:
            if self.telemetry.collect:
                self.telemetry.counter("serving/rejected").inc()
            if self.tenants is not None:
                self.tenants.note("rejected", tenant)
            raise
        # WAL contract: the submit record is durable BEFORE the id is
        # acknowledged (a commit failure quarantines; the request still
        # serves — availability over durability, loudly)
        self._journal_record("record_submit", req)
        self._journal_commit()
        if client_key is not None:
            self._client_keys[client_key] = req.request_id
        if self.tenants is not None:
            self.tenants.note("admitted", tenant)
            if self.telemetry.collect:
                self._tenant_counter(tenant, "admitted").inc()
        if self.telemetry.collect:
            self.telemetry.counter("serving/submitted").inc()
        return req.request_id

    def _tenant_counter(self, tenant: Optional[str], kind: str):
        from deepspeed_tpu.serving.frontdoor.tenants import DEFAULT_TENANT

        return self.telemetry.counter(
            f"serving/tenant/{tenant or DEFAULT_TENANT}/{kind}")

    def client_request_id(self, client_key: str) -> Optional[int]:
        """The id this engine acknowledged for ``client_key`` (in memory
        or journaled), or None — the fleet router's at-most-once dedup
        probe (docs/serving.md §Fleet)."""
        return self._client_keys.get(client_key)

    def recover(self) -> list:
        """Replay the journal's incomplete requests into this engine
        under their **original ids** (idempotent: a second ``recover()``
        on the same engine re-reads the on-disk set, which now shows
        them incomplete-but-resubmitted — they are deduped by id at the
        scheduler).  Greedy and seeded-sampling replays bit-match the
        uninterrupted run (docs/serving.md §Resilience).  Returns the
        replayed ids, oldest first."""
        if self._paged:
            # re-register manifest-verified session spills FIRST, so a
            # replayed turn-N+1 rebinds its session exactly like the
            # uninterrupted run would have
            try:
                sids = self.pool.recover()
                if sids:
                    log_dist(
                        f"serving: kvcache re-registered {len(sids)} spilled "
                        f"session(s) from {self.pool.sessions.spill_dir!r}"
                    )
            except OSError as e:
                logger.warning(f"serving: kvcache session recovery failed: {e!r}")
        if self._journal is None:
            return []
        try:
            entries = self._journal.incomplete()
        except JournalError as e:
            self._quarantine_journal(e)
            return []
        replayed = []
        for e in entries:
            rid = int(e["id"])
            if self.scheduler.request(rid) is not None:
                continue  # already live here (double recover)
            req = self.scheduler.submit(
                np.asarray(e["prompt"], np.int32),
                max_new_tokens=int(e["max_new"]),
                eos_token_id=e.get("eos"),
                # 0 = NO deadline (None falls back to the scheduler
                # default): the queue wait already happened once, an
                # acknowledged replay must not expire a second time
                deadline_seconds=0.0,
                do_sample=bool(e.get("do_sample", False)),
                temperature=float(e.get("temperature", 1.0)),
                top_k=int(e.get("top_k", 0)),
                seed=int(e.get("seed", 0)),
                priority=int(e.get("priority", PRIORITY_NORMAL)),
                request_id=rid,
                bypass_admission=True,  # accepted before the crash
                client_key=e.get("ck"),
                session_id=e.get("sid"),
                # the journaled tenant label rides the replay — the
                # bucket is NOT re-charged (admission happened before
                # the crash; a replay must never double-bill)
                tenant=e.get("tn"),
                now=time.monotonic(),
                step=self._step_count,
            )
            if e.get("ck"):
                self._client_keys[str(e["ck"])] = rid
            if self.tenants is not None:
                self.tenants.note("replayed", e.get("tn"))
            advance_request_ids(rid)
            # re-journal into the live segment: recovery is self-contained
            # even after the old segments compact away
            self._journal_record("record_submit", req)
            replayed.append(rid)
        self._journal_commit()
        if replayed:
            log_dist(
                f"serving: replayed {len(replayed)} incomplete request(s) "
                f"from the journal (ids {replayed[0]}..{replayed[-1]})"
            )
            if self.telemetry.collect:
                self.telemetry.counter("serving/replayed").inc(len(replayed))
        return replayed

    def step(self) -> bool:
        """One serving step: tick the scheduler, land this step's prefill
        chunks, then one decode step over the pool.  Returns whether any
        work remains.  If a drain signal is pending (SIGTERM through the
        installed :class:`ServingWatchdog`), runs the graceful drain and
        exits with the watchdog's contract instead."""
        if self._watchdog is not None and self._watchdog.draining:
            self._drain_and_exit()
        return self._step_once(admit=True)

    def _step_once(self, admit: bool) -> bool:
        tl = self.timeline
        self._step_count += 1
        compiles0 = self.prefill_compiles + self.decode_compiles
        t0 = time.monotonic()
        if self._paged:
            # TTL sweep BEFORE admission: pages a cold session releases
            # this tick are available to the requests admitted in it
            self.pool.sweep(t0)
        if self._tiers is not None:
            # migration tick BEFORE admission: hinted prefetch pages
            # upcoming admits/rebinds back to T0 so their prefill chunk
            # runs against warm pages; watermark demotion batches the
            # device_get traffic at the step boundary
            self._tiers.tick(
                t0, hints=self.scheduler.upcoming_hints(
                    self._tiers.prefetch_ahead))
        with tl.phase("sched"):
            plan = self.scheduler.tick(t0, self._step_count, admit=admit)
        with tl.phase("prefill"):
            for job in plan.prefill_jobs:
                self._run_prefill(job)
        with tl.phase("decode"):
            toks, pos, decoding = self.scheduler.decode_inputs()
            if decoding:
                self._run_decode(toks, pos, decoding)
        tl.set_gauge("queue_depth", self.scheduler.queue_depth)
        tl.set_gauge("live_slots", self.pool.live_slots)
        tl.end_step()
        # measured service rate for the admission controller (EWMA over
        # non-empty, non-compile steps — a jit trace in the wall would
        # poison the TTFT estimate into shedding everything for minutes;
        # the registry window supersedes the EWMA when armed)
        wall = time.monotonic() - t0
        if (plan.prefill_jobs or decoding) and (
            self.prefill_compiles + self.decode_compiles == compiles0
        ):
            self._step_wall_ewma = (
                wall if self._step_wall_ewma is None
                else 0.2 * wall + 0.8 * self._step_wall_ewma
            )
        # retirements this step become durable at the boundary
        self._journal_commit()
        if self._tiers is not None:
            # the step's wall window feeds the swap-hide overlap ratio
            self._tiers.note_step(t0, time.monotonic())
        self._publish_kvcache()
        return self.scheduler.has_work()

    def drain(self, max_steps: Optional[int] = None) -> Dict[int, Request]:
        """Step until every submitted request finishes (or ``max_steps``
        elapses); returns and clears the finished-request map.  Also
        sweeps queued-deadline expiry first, so an idle engine's
        over-deadline waiters expire even when no step runs."""
        self.scheduler.sweep_expired(time.monotonic(), self._step_count)
        if self._tiers is not None:
            # idle-engine demotion: a drain() with no work must still
            # turn the migration queue (mirror of the idle TTL sweep)
            self._tiers.tick(time.monotonic())
        steps = 0
        while self.scheduler.has_work():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        self._journal_commit()
        return self.scheduler.pop_finished()

    # ------------------------------------------------------------------
    # graceful drain (docs/serving.md §Resilience)
    # ------------------------------------------------------------------
    def install_watchdog(
        self,
        drain_deadline_seconds: Optional[float] = None,
        exit_code: Optional[int] = None,
    ) -> ServingWatchdog:
        """Arm SIGTERM/SIGINT graceful drain: admission stops, in-flight
        requests drain within the deadline, undone work persists in the
        journal, and the process exits 43 only after the journal
        commits (1 otherwise)."""
        if self._watchdog is None:
            kw = {}
            if exit_code is not None:
                kw["exit_code"] = exit_code
            self._watchdog = ServingWatchdog(
                drain_deadline_seconds=(
                    drain_deadline_seconds
                    if drain_deadline_seconds is not None
                    else self.config.drain_deadline_seconds
                ),
                **kw,
            ).install()
        return self._watchdog

    def _drain_and_exit(self) -> None:
        """The SIGTERM sequence.  Exit 43 certifies durable undone work
        (journal committed) — or a complete drain when no journal is
        armed; anything less is exit 1, the crash contract."""
        wd = self._watchdog
        log_dist(
            f"serving: drain signal ({wd.signal_name}) received; admission "
            f"stopped, draining {self.pool.live_slots} in-flight request(s) "
            f"within {max(wd.remaining(), 0.0):.1f}s "
            f"({self.scheduler.queue_depth} queued will replay from the journal)"
        )
        if self.telemetry.collect:
            self.telemetry.counter("serving/drains").inc()
        drained_all = True
        try:
            while self.scheduler.live and wd.remaining() > 0:
                self._step_once(admit=False)
        except BaseException as e:  # a dying drain must still certify honestly
            logger.error(f"serving: drain loop failed: {e!r}")
            drained_all = False
        if self.scheduler.live:
            drained_all = False
            undone_live = sorted(self.pool.owners().values())
            logger.warning(
                f"serving: drain deadline ({wd.drain_deadline_seconds:g}s) cut "
                f"off {len(undone_live)} in-flight request(s) {undone_live}; "
                "they replay from the journal"
            )
        if self._paged:
            # persist every warm session before the process dies: the
            # restarted engine's recover() re-registers the spills and
            # turn N+1 rebinds across the restart (no-op w/o spill_dir)
            try:
                if self._tiers is not None:
                    # tiering path: demote every warm session and push
                    # T1 to disk, so tiered state survives the process
                    n_spilled = self._tiers.flush(time.monotonic())
                else:
                    n_spilled = self.pool.spill_sessions(time.monotonic())
                if n_spilled:
                    log_dist(
                        f"serving: kvcache spilled {n_spilled} warm "
                        f"session(s) at drain"
                    )
            except OSError as e:
                logger.error(
                    f"serving: kvcache session spill at drain failed: {e!r}"
                )
        undone = self.scheduler.pending_ids()
        if self._journal is not None:
            self._journal_record("record_drain", undone)
            committed = self._journal_commit()
            if committed:
                log_dist(
                    f"serving: journal committed ({len(undone)} undone request(s) "
                    f"durable); exiting with code {wd.exit_code}"
                )
                raise SystemExit(wd.exit_code)
            logger.error("serving: journal could not commit at drain; exiting 1")
            raise SystemExit(1)
        if drained_all and not undone:
            log_dist(
                "serving: drained completely (no journal armed, nothing undone); "
                f"exiting with code {wd.exit_code}"
            )
            raise SystemExit(wd.exit_code)
        logger.error(
            f"serving: {len(undone)} undone request(s) with no journal to "
            "persist them; exiting 1 (crash contract)"
        )
        raise SystemExit(1)

    def cancel(self, request_id: int) -> bool:
        """Retire a queued or in-flight request without finishing it
        (the hedge loser's path; docs/serving.md §Fleet).  The retire
        record journals and commits immediately — a cancelled request
        must not replay after a crash.  False when the id is unknown or
        already retired."""
        ok = self.scheduler.cancel(
            request_id, now=time.monotonic(), step=self._step_count
        )
        if ok:
            self._journal_commit()
        return ok

    def result(self, request_id: int) -> Optional[Request]:
        return self.scheduler.request(request_id)

    def pop_results(self) -> Dict[int, Request]:
        return self.scheduler.pop_finished()

    # ------------------------------------------------------------------
    # telemetry: per-request lifecycle (docs/telemetry.md span schema)
    # ------------------------------------------------------------------
    def _on_request_event(self, kind: str, r, now: float, step: int) -> None:
        """Scheduler lifecycle hook → spans on the request's own trace
        lane (tid = request id): queue → prefill → decode → retire, plus
        the TTFT / per-output-token histograms the SLO bench reads.
        Host dict ops only; spans cost nothing when tracing is off."""
        tm = self.telemetry
        tracer = tm.tracer if tm.tracer.enabled else None
        rid = r.request_id
        # journal lifecycle records (committed at the step boundary;
        # docs/serving.md §Resilience journal format)
        if kind == "admitted":
            self._journal_record("record_admit", r)
        elif kind == "first_token":
            self._journal_record("record_first_token", r)
        elif kind in ("finished", "cancelled"):
            self._journal_record("record_retire", r)
            if self.tenants is not None:
                # realized-usage billing, mirrored by the retire
                # record's ``n`` — the two ledgers reconcile exactly
                # after a crash + recover() (at most one retire per id)
                if kind == "finished":
                    self.tenants.bill(r.tenant, len(r.generated))
                    if tm.collect:
                        self._tenant_counter(r.tenant, "billed_tokens").inc(
                            len(r.generated))
                else:
                    self.tenants.note("cancelled", r.tenant)
        elif kind in ("expired", "shed"):
            # reject record, committed NOW rather than at the step
            # boundary: a crash in between must not resurrect a request
            # the client was already told to retry elsewhere
            self._journal_record("record_reject", r)
            self._journal_commit()
            if self.tenants is not None:
                self.tenants.note(kind, r.tenant)
        if kind == "admitted":
            self._tel_queue_wait.observe((now - r.submit_time) * 1e3)
            if tracer is not None:
                tracer.add_span(
                    "queue", "serving.request", r.submit_time, now,
                    pid=_telemetry.PID_REQUESTS, tid=rid,
                    args={"request": rid, "slot": r.slot, "prompt_len": r.prompt_len},
                    tid_name=f"request {rid}",
                )
        elif kind == "first_token":
            ttft_ms = (now - r.submit_time) * 1e3
            self._tel_ttft.observe(ttft_ms)
            if tracer is not None:
                tracer.add_span(
                    "prefill", "serving.request",
                    r.admit_time if r.admit_time is not None else r.submit_time, now,
                    pid=_telemetry.PID_REQUESTS, tid=rid,
                    args={"request": rid, "ttft_ms": round(ttft_ms, 3),
                          "chunks": -(-r.prompt_len // self.config.prefill_chunk)},
                )
            tm.check_slo(ttft_ms)
        elif kind == "finished":
            if tm.collect:
                tm.counter("serving/finished", reason=r.finish_reason or "?").inc()
                if len(r.generated) > 1 and r.first_token_time is not None:
                    self._tel_tpot.observe(
                        (now - r.first_token_time) * 1e3 / (len(r.generated) - 1)
                    )
            if tracer is not None:
                if r.first_token_time is not None:
                    tracer.add_span(
                        "decode", "serving.request", r.first_token_time, now,
                        pid=_telemetry.PID_REQUESTS, tid=rid,
                        args={"request": rid, "tokens": len(r.generated)},
                    )
                tracer.add_instant(
                    "retire", "serving.request", ts=now,
                    pid=_telemetry.PID_REQUESTS, tid=rid,
                    args={"request": rid, "finish_reason": r.finish_reason,
                          "tokens": len(r.generated)},
                )
        elif kind == "cancelled":
            if tm.collect:
                tm.counter("serving/cancelled").inc()
            if tracer is not None:
                tracer.add_instant(
                    "cancelled", "serving.request", ts=now,
                    pid=_telemetry.PID_REQUESTS, tid=rid,
                    args={"request": rid, "tokens": len(r.generated)},
                )
        elif kind == "expired":
            if tm.collect:
                tm.counter("serving/expired").inc()
            if tracer is not None:
                tracer.add_instant(
                    "expired", "serving.request", ts=now,
                    pid=_telemetry.PID_REQUESTS, tid=rid,
                    args={"request": rid,
                          "queue_wait_ms": round((now - r.submit_time) * 1e3, 3)},
                )
        elif kind == "shed":
            if tm.collect:
                tm.counter("serving/shed").inc()
                if r.retry_after is not None:
                    tm.histogram("serving/retry_after_s").observe(r.retry_after)
            if tracer is not None:
                tracer.add_instant(
                    "shed", "serving.request", ts=now,
                    pid=_telemetry.PID_REQUESTS, tid=rid,
                    args={"request": rid, "priority": r.priority,
                          "ladder_rung": self.scheduler.ladder.level,
                          "retry_after_s": r.retry_after},
                )

    def _publish_kvcache(self) -> None:
        """Paged-pool counters → ``kvcache/*`` registry gauges, plus
        Perfetto instants for eviction/spill deltas since the last
        publish (step-boundary granularity; host dict reads only)."""
        if not self._paged:
            return
        st = self.pool.stats()
        tm = self.telemetry
        if tm.collect:
            for key in ("pages_live", "pages_free", "hit_rate", "tokens_saved",
                        "cow_copies", "evictions", "session_rebinds",
                        "session_spills", "session_restores", "prefix_entries",
                        "sessions_warm", "sessions_spilled"):
                tm.gauge(f"kvcache/{key}").set(float(st[key]))
        if tm.collect and self._tiers is not None and "tiers" in st:
            for key, val in st["tiers"].items():
                if isinstance(val, (int, float)):
                    tm.gauge(f"kvcache/tier/{key}").set(float(val))
        tracer = tm.tracer if tm.tracer.enabled else None
        for key, name in (("evictions", "kvcache_evict"),
                          ("session_spills", "kvcache_spill")):
            delta = int(st[key]) - self._kv_evt_seen[key]
            if delta > 0 and tracer is not None:
                tracer.add_instant(
                    name, "serving.kvcache",
                    args={"count": delta, "pages_free": st["pages_free"],
                          "pages_live": st["pages_live"]},
                )
            self._kv_evt_seen[key] = int(st[key])

    def telemetry_summary(self) -> Dict[str, Any]:
        """Compact roll-up for bench records — MODEL-derived, unlike the
        train engine's compiled-cost gauges (the serving executables are
        plain jit; docs/telemetry.md): ``mfu`` from 2·N FLOPs per
        generated token over the live slots at the measured step wall
        (per-chip share), and ``hbm_bytes_per_step`` as the decode
        roofline traffic model — params read once per token step plus
        the KV pool touched — an upper bound, not a measured access
        count; plus the registry digest."""
        from deepspeed_tpu.profiling.flops_profiler import peak_flops

        mcfg = self.engine.model_config
        n_params = mcfg.num_params() if hasattr(mcfg, "num_params") else 0
        s = self.timeline.summary()
        wall_s = s.get("wall_ms", 0.0) / 1e3
        live = s.get("live_slots", 0.0)
        # per-chip share of the model work (bench.py's tokens/s/chip
        # convention): a sharded model splits the 2N across devices
        flops_step = 2.0 * n_params * max(live, 0.0) / jax.device_count()
        mfu = (
            flops_step / wall_s / peak_flops() if wall_s > 0 and flops_step else None
        )
        param_bytes = sum(
            int(np.prod(np.shape(p)) * np.dtype(p.dtype).itemsize)
            for p in jax.tree.leaves(self.engine.params)
        )
        return {
            "mfu": None if mfu is None else round(mfu, 6),
            "hbm_bytes_per_step": param_bytes + self.pool.cache_bytes(),
            "telemetry": self.telemetry.digest(),
        }

    # ------------------------------------------------------------------
    def _run_prefill(self, job: PrefillJob) -> None:
        faults.check("serving.prefill")
        faults.check_latency("serving.prefill")
        san = self._sanitizer
        fn = self._get_prefill()
        r = job.req
        # explicit staging of the host-side chunk + scalars onto the
        # serving mesh (transfer-guard clean: device_put is sanctioned,
        # and pre-placing on the mesh means the jit has nothing to move)
        if self._paged:
            cow_src, cow_dst = self.pool.consume_cow(r.slot)
            staged = jax.device_put(
                (job.tokens[None, :], self.pool.table(r.slot),
                 np.int32(job.start), np.int32(job.take_idx),
                 np.int32(cow_src), np.int32(cow_dst),
                 np.bool_(r.do_sample), np.float32(r.temperature),
                 np.int32(r.top_k), np.uint32(r.seed & 0xFFFFFFFF)),
                self._replicated,
            )
        else:
            staged = jax.device_put(
                (job.tokens[None, :], np.int32(r.slot), np.int32(job.start),
                 np.int32(job.take_idx), np.bool_(r.do_sample),
                 np.float32(r.temperature), np.int32(r.top_k),
                 np.uint32(r.seed & 0xFFFFFFFF)),
                self._replicated,
            )
        tracer = self.telemetry.tracer if self.telemetry.tracer.enabled else None
        t0 = tracer.now() if tracer is not None else 0.0
        guard = san.transfer.guard("serving.prefill") if san is not None else nullcontext()
        with guard:
            first, k, v = fn(
                self.engine.params, *staged, self.pool.k, self.pool.v,
            )
        self.pool.swap(k, v)
        # explicit d2h read doubles as the fence that keeps prefill_ms
        # honest; the value is the first generated token on final chunks
        tok = int(jax.device_get(first))
        now = time.monotonic()
        if self._paged and job.final:
            # the whole prompt's KV is paged in: learn it as a shared
            # prefix (before note_prefill — a 1-token budget can retire
            # the request, releasing the slot, inside that call)
            self.pool.learn_prefix(r, now=now)
        if tracer is not None:
            # chunk-level detail on the request's own lane, between its
            # queue and prefill spans (the fenced read above makes the
            # span a real device-work window, not dispatch overhead)
            tracer.add_span(
                "prefill_chunk", "serving.request", t0, now,
                pid=_telemetry.PID_REQUESTS, tid=r.request_id,
                args={"request": r.request_id, "start": job.start,
                      "len": job.length, "final": job.final},
                tid_name=f"request {r.request_id}",
            )
        self.scheduler.note_prefill(job, tok, now=now, step=self._step_count)

    def _run_decode(self, toks: np.ndarray, pos: np.ndarray, decoding) -> None:
        faults.check("serving.decode")
        faults.check_latency("serving.decode")
        san = self._sanitizer
        fn = self._get_decode()
        flags, temps, topks, seeds = self.scheduler.sampling_inputs()
        if self._paged:
            # non-decoding slots write to the garbage page; their reads
            # were already safe behind the position mask
            wmask = np.zeros((self.pool.num_slots,), np.bool_)
            for r in decoding:
                wmask[r.slot] = True
            staged = jax.device_put(
                (toks, pos, flags, temps, topks, seeds,
                 self.pool.tables(), wmask),
                self._replicated,
            )
        else:
            staged = jax.device_put(
                (toks, pos, flags, temps, topks, seeds), self._replicated
            )
        guard = san.transfer.guard("serving.decode") if san is not None else nullcontext()
        with guard:
            nxt, k, v = fn(
                self.engine.params, *staged, self.pool.k, self.pool.v,
            )
        self.pool.swap(k, v)
        out = np.asarray(jax.device_get(nxt))
        now = time.monotonic()
        self.scheduler.note_decode(
            {r.slot: int(out[r.slot]) for r in decoding}, now, self._step_count
        )

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Counters + per-step phase attribution (prefill_ms/decode_ms/
        sched_ms, mean queue_depth/live_slots) for logs and bench
        records.  Host-side deadline sweep included: an idle engine's
        over-deadline waiters expire the moment anyone looks, not only
        when a ``step()`` happens to run."""
        s = self.scheduler
        if s.sweep_expired(time.monotonic(), self._step_count):
            self._journal_commit()
        if self._paged:
            # same idle-sweep shape for parked-session TTLs: the pool's
            # per-step sweep never runs on a replica that receives no
            # traffic, so a drained-but-alive replica would pin its
            # pages forever without this (docs/serving.md §Elastic fleet)
            self.pool.sweep(time.monotonic())
        if self._tiers is not None:
            # idle-engine demotion: a quiescent engine must still drain
            # pending demotions instead of holding T0 pages forever
            self._tiers.tick(time.monotonic())
        if self.telemetry.collect:
            self.telemetry.gauge("serving/queue_depth_now").set(s.queue_depth)
            self.telemetry.gauge("serving/live_slots_now").set(self.pool.live_slots)
        j = self._journal
        out = {
            "submitted": s.submitted,
            "finished": s.finished_count,
            "rejected": s.rejected,
            "expired": s.expired,
            # resilience (docs/serving.md §Resilience)
            "shed": s.shed_count + s.admission.shed,
            "cancelled": s.cancelled_count,
            "degrade_level": s.ladder.level,
            "degrade_rung": s.ladder.rung,
            "degrade_engagements": s.ladder.engagements,
            "draining": bool(self._watchdog is not None and self._watchdog.draining),
            "journal": (
                "off" if j is None and not getattr(self, "_journal_quarantined", None)
                else ("quarantined" if j is None else "on")
            ),
            "journal_records": 0 if j is None else j.records,
            "journal_commits": 0 if j is None else j.commits,
            # instantaneous levels; the window MEANS arrive from the
            # timeline summary below as queue_depth / live_slots
            "queue_depth_now": s.queue_depth,
            "live_slots_now": self.pool.live_slots,
            "serving_steps": self._step_count,
            "prefill_compiles": self.prefill_compiles,
            "decode_compiles": self.decode_compiles,
            "pool_bytes": self.pool.cache_bytes(),
            "kv_dtype": "int8" if isinstance(self.pool.k, dict) else str(
                np.dtype(jax.tree.leaves(self.pool.k)[0].dtype)
            ),
        }
        if self._paged:
            out["kvcache"] = self.pool.stats()
            self._publish_kvcache()
        if self.tenants is not None:
            out["tenants"] = self.tenants.snapshot()
        out.update(self.timeline.summary())
        return out


__all__ = [
    "ServingEngine", "ServingQueueFull", "ServingOverloaded", "ServingDraining",
    "Request",
]
