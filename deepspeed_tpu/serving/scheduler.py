"""Continuous-batching scheduler: admission, chunked prefill, and
token-granularity retirement — pure host bookkeeping, no jax.

Requests flow ``QUEUED -> PREFILL -> DECODE -> DONE`` (or ``EXPIRED``
when the queue-wait deadline passes before a slot frees; ``submit``
itself rejects with :class:`ServingQueueFull` past the queue bound).
Every :meth:`tick` produces a :class:`StepPlan` the serving engine
executes against its two fixed-shape executables:

* up to ``prefill_chunks_per_step`` prompt chunks (FIFO across the
  slots mid-prefill) — long prompts are *split*, so an in-flight decode
  is never stalled behind a 384-token prefill;
* one decode step over the whole slot pool whenever any slot is
  decoding.

The scheduler also owns the **safe-position invariant** the fixed-shape
decode step relies on: :meth:`decode_inputs` gives every non-decoding
slot a write position whose contents are overwritten before they are
ever attendable (a mid-prefill slot's next chunk start; position 0 for
free slots, which the next occupant's first chunk overwrites).
"""
from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.serving.pool import SlotKVPool
from deepspeed_tpu.utils.logging import logger

QUEUED = "queued"
PREFILL = "prefill"
DECODE = "decode"
DONE = "done"
EXPIRED = "expired"


class ServingQueueFull(RuntimeError):
    """Graceful admission rejection: the waiting queue is at its bound.
    Callers back off / shed load; nothing in flight is affected."""


# Process-global request ids: several engines in one process (bench
# sweeps build one per (kv, load) point) must not reuse ids — the
# telemetry trace keys per-request span lanes on them.
_REQUEST_IDS = itertools.count()


@dataclasses.dataclass
class Request:
    """One sequence through the pool.  ``prompt`` is a 1-D int32 array;
    timings are host wall-clock stamps the SLO bench aggregates."""

    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    deadline_seconds: Optional[float] = None  # queue-wait bound; None = scheduler default
    # per-request sampling params (ride the fixed decode signature as
    # per-slot vectors; greedy when do_sample is False)
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    seed: int = 0

    status: str = QUEUED
    slot: Optional[int] = None
    prefill_pos: int = 0  # prompt tokens written to the cache so far
    generated: List[int] = dataclasses.field(default_factory=list)
    finish_reason: Optional[str] = None  # eos | length | expired
    submit_time: float = 0.0
    admit_time: Optional[float] = None  # queue -> slot (prefill starts)
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    submit_step: int = 0
    admit_step: Optional[int] = None
    first_token_step: Optional[int] = None
    finish_step: Optional[int] = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    def tokens(self) -> np.ndarray:
        """prompt + generated (the solo-``generate()``-comparable view)."""
        return np.concatenate([self.prompt, np.asarray(self.generated, np.int32)])


@dataclasses.dataclass
class PrefillJob:
    """One prompt chunk: write ``tokens`` (padded to the chunk size) at
    cache position ``start`` of the request's slot.  ``take_idx`` is the
    within-chunk index of the last real token — where the first
    generated token is sampled when ``final``."""

    req: Request
    start: int
    tokens: np.ndarray  # (prefill_chunk,) int32, zero-padded past `length`
    length: int
    final: bool
    take_idx: int


@dataclasses.dataclass
class StepPlan:
    """This tick's prefill chunks.  The decode set is NOT planned here:
    the engine derives it from :meth:`ContinuousScheduler.decode_inputs`
    *after* the chunks land, so a request whose final chunk completed
    this very step decodes this step too."""

    prefill_jobs: List[PrefillJob]


class ContinuousScheduler:
    def __init__(
        self,
        pool: SlotKVPool,
        prefill_chunk: int,
        prefill_chunks_per_step: int = 1,
        max_queue: int = 64,
        deadline_seconds: float = 0.0,
        capacity: Optional[int] = None,
    ):
        self.pool = pool
        self.prefill_chunk = int(prefill_chunk)
        self.prefill_chunks_per_step = max(1, int(prefill_chunks_per_step))
        self.max_queue = int(max_queue)
        self.deadline_seconds = float(deadline_seconds)
        # admission bound on prompt+generated length (pool capacity
        # clamped by the engine's generation capacity)
        self.capacity = int(capacity) if capacity is not None else pool.max_len
        self._queue: Deque[Request] = deque()
        self._active: Dict[int, Request] = {}  # slot -> request
        self._finished: Dict[int, Request] = {}  # request_id -> request
        self._ids = _REQUEST_IDS
        self.submitted = 0
        self.rejected = 0
        self.expired = 0
        self.finished_count = 0
        # lifecycle observer (the serving engine's telemetry hook):
        # called as on_event(kind, request, now, step) at "admitted",
        # "first_token", "finished", "expired" transitions.  Pure host
        # callback — the scheduler itself stays jax- and telemetry-free.
        self.on_event: Optional[Any] = None

    def _emit(self, kind: str, r: Request, now: float, step: int) -> None:
        if self.on_event is not None:
            self.on_event(kind, r, now, step)

    # -- introspection ----------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def live(self) -> int:
        return len(self._active)

    def has_work(self) -> bool:
        return bool(self._queue or self._active)

    def request(self, request_id: int) -> Optional[Request]:
        if request_id in self._finished:
            return self._finished[request_id]
        for r in self._active.values():
            if r.request_id == request_id:
                return r
        for r in self._queue:
            if r.request_id == request_id:
                return r
        return None

    def pop_finished(self) -> Dict[int, Request]:
        out, self._finished = self._finished, {}
        return out

    # -- admission --------------------------------------------------------
    def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        eos_token_id: Optional[int] = None,
        deadline_seconds: Optional[float] = None,
        now: float = 0.0,
        step: int = 0,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        seed: int = 0,
    ) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if do_sample and temperature <= 0.0:
            raise ValueError(f"temperature must be > 0 when sampling, got {temperature}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        total = prompt.shape[0] + int(max_new_tokens)
        if total > self.capacity:
            raise ValueError(
                f"prompt_len + max_new_tokens = {prompt.shape[0]}+{max_new_tokens} "
                f"= {total} exceeds the serving capacity {self.capacity} "
                f"(pool max_len={self.pool.max_len})"
            )
        if len(self._queue) >= self.max_queue:
            self.rejected += 1
            raise ServingQueueFull(
                f"serving queue is full ({len(self._queue)} waiting >= "
                f"max_queue={self.max_queue}); retry later or raise serving.max_queue"
            )
        req = Request(
            request_id=next(self._ids),
            prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            eos_token_id=eos_token_id,
            deadline_seconds=deadline_seconds,
            do_sample=bool(do_sample),
            temperature=float(temperature),
            top_k=int(top_k),
            seed=int(seed),
            submit_time=now,
            submit_step=step,
        )
        self._queue.append(req)
        self.submitted += 1
        return req

    # -- per-step policy --------------------------------------------------
    def tick(self, now: float, step: int) -> StepPlan:
        """Expire over-deadline waiters, admit queued requests into free
        slots, and pick this step's prefill chunks."""
        # 1) queue-wait deadlines
        if self._queue:
            kept: Deque[Request] = deque()
            for r in self._queue:
                deadline = (
                    r.deadline_seconds
                    if r.deadline_seconds is not None
                    else self.deadline_seconds
                )
                if deadline and (now - r.submit_time) > deadline:
                    r.status = EXPIRED
                    r.finish_reason = "expired"
                    r.finish_time = now
                    r.finish_step = step
                    self._finished[r.request_id] = r
                    self.expired += 1
                    logger.warning(
                        f"serving: request {r.request_id} expired after "
                        f"{now - r.submit_time:.3f}s in queue (deadline {deadline:g}s)"
                    )
                    self._emit("expired", r, now, step)
                else:
                    kept.append(r)
            self._queue = kept
        # 2) admission: queued -> free slots (FIFO)
        while self._queue and self.pool.free_slots:
            r = self._queue.popleft()
            r.slot = self.pool.alloc(r.request_id)
            r.status = PREFILL
            r.prefill_pos = 0
            r.admit_time = now
            r.admit_step = step
            self._active[r.slot] = r
            self._emit("admitted", r, now, step)
        # 3) prefill chunk budget, FIFO over mid-prefill slots
        jobs: List[PrefillJob] = []
        budget = self.prefill_chunks_per_step
        prefilling = sorted(
            (r for r in self._active.values() if r.status == PREFILL),
            key=lambda r: r.request_id,
        )
        for r in prefilling:
            pos = r.prefill_pos
            while budget > 0 and pos < r.prompt_len:
                length = min(self.prefill_chunk, r.prompt_len - pos)
                chunk = np.zeros((self.prefill_chunk,), np.int32)
                chunk[:length] = r.prompt[pos : pos + length]
                jobs.append(
                    PrefillJob(
                        req=r,
                        start=pos,
                        tokens=chunk,
                        length=length,
                        final=pos + length >= r.prompt_len,
                        take_idx=length - 1,
                    )
                )
                pos += length
                budget -= 1
            if budget == 0:
                break
        return StepPlan(prefill_jobs=jobs)

    def note_prefill(self, job: PrefillJob, first_token: int, now: float, step: int) -> None:
        """A chunk landed; on the final chunk the sampled first token
        arrives (the TTFT moment) and the request joins the decode set —
        or retires immediately when its budget is a single token / the
        first token is EOS."""
        r = job.req
        r.prefill_pos = job.start + job.length
        if not job.final:
            return
        r.status = DECODE
        r.generated = [int(first_token)]
        r.first_token_time = now
        r.first_token_step = step
        self._emit("first_token", r, now, step)
        if len(r.generated) >= r.max_new_tokens or (
            r.eos_token_id is not None and first_token == r.eos_token_id
        ):
            self._finish(r, now, step)

    def decode_inputs(self) -> Tuple[np.ndarray, np.ndarray, List[Request]]:
        """Fixed-shape decode-step inputs over the whole pool.

        Decoding slots feed their latest token at its true position;
        every other slot gets a *safe* garbage position — one whose
        write is overwritten before it can ever be attended (the next
        chunk start for mid-prefill slots, 0 for free slots)."""
        toks = np.zeros((self.pool.num_slots,), np.int32)
        pos = np.zeros((self.pool.num_slots,), np.int32)
        decoding: List[Request] = []
        for slot, r in self._active.items():
            if r.status == DECODE:
                toks[slot] = r.generated[-1]
                pos[slot] = r.prompt_len + len(r.generated) - 1
                decoding.append(r)
            else:  # mid-prefill: next chunk overwrites this position
                pos[slot] = r.prefill_pos
        return toks, pos, decoding

    def sampling_inputs(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Fixed-shape per-slot sampling vectors (ride the same decode
        signature every step): do_sample flags, temperatures, top-k
        bounds, and seeds.  Non-active / non-sampling slots keep the
        greedy defaults — their computed token is either discarded
        (non-decoding) or the bare argmax (the solo-``generate()``
        bit-match path)."""
        S = self.pool.num_slots
        flags = np.zeros((S,), bool)
        temps = np.ones((S,), np.float32)
        topks = np.zeros((S,), np.int32)
        seeds = np.zeros((S,), np.uint32)
        for slot, r in self._active.items():
            flags[slot] = r.do_sample
            temps[slot] = np.float32(r.temperature)
            topks[slot] = np.int32(r.top_k)
            seeds[slot] = np.uint32(r.seed & 0xFFFFFFFF)
        return flags, temps, topks, seeds

    def note_decode(self, tokens_by_slot: Dict[int, int], now: float, step: int) -> None:
        """Append this step's token per decoding slot; retire at EOS or
        budget — the slot frees *this* token, not at batch end."""
        for slot, tok in tokens_by_slot.items():
            r = self._active[slot]
            r.generated.append(int(tok))
            if (r.eos_token_id is not None and tok == r.eos_token_id) or len(
                r.generated
            ) >= r.max_new_tokens:
                self._finish(r, now, step)

    def _finish(self, r: Request, now: float, step: int) -> None:
        r.status = DONE
        r.finish_reason = (
            "eos"
            if (r.eos_token_id is not None and r.generated and r.generated[-1] == r.eos_token_id)
            else "length"
        )
        r.finish_time = now
        r.finish_step = step
        del self._active[r.slot]
        self.pool.free(r.slot)
        self._finished[r.request_id] = r
        self.finished_count += 1
        self._emit("finished", r, now, step)
