"""Continuous-batching scheduler: admission, chunked prefill, and
token-granularity retirement — pure host bookkeeping, no jax.

Requests flow ``QUEUED -> PREFILL -> DECODE -> DONE`` (or ``EXPIRED``
when the queue-wait deadline passes before a slot frees; ``submit``
itself rejects with :class:`ServingQueueFull` past the queue bound).
Every :meth:`tick` produces a :class:`StepPlan` the serving engine
executes against its two fixed-shape executables:

* up to ``prefill_chunks_per_step`` prompt chunks (FIFO across the
  slots mid-prefill) — long prompts are *split*, so an in-flight decode
  is never stalled behind a 384-token prefill;
* one decode step over the whole slot pool whenever any slot is
  decoding.

The scheduler also owns the **safe-position invariant** the fixed-shape
decode step relies on: :meth:`decode_inputs` gives every non-decoding
slot a write position whose contents are overwritten before they are
ever attendable (a mid-prefill slot's next chunk start; position 0 for
free slots, which the next occupant's first chunk overwrites).

Overload management (docs/serving.md §Resilience): ``submit`` carries a
**priority tier** (0 high / 1 normal / 2 low); admission into free
slots is priority-then-FIFO.  An :class:`AdmissionController` sheds
normal/low submits whose *estimated TTFT* — queue backlog over the
measured step rate the engine feeds in — exceeds ``slo_ttft_ms``,
raising :class:`ServingOverloaded` with a ``retry_after`` hint.  A
:class:`DegradationLadder` engages on sustained queue pressure with
hysteresis: clamp new admits' ``max_new_tokens`` → shrink the prefill
chunk budget to 1 → shed queued low-priority requests.
"""
from __future__ import annotations

import dataclasses
import math
import threading
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.config import constants as C
from deepspeed_tpu.serving.pool import SlotKVPool
from deepspeed_tpu.utils.logging import logger

QUEUED = "queued"
PREFILL = "prefill"
DECODE = "decode"
DONE = "done"
EXPIRED = "expired"
SHED = "shed"
CANCELLED = "cancelled"

PRIORITY_HIGH = C.SERVING_PRIORITY_HIGH
PRIORITY_NORMAL = C.SERVING_PRIORITY_NORMAL
PRIORITY_LOW = C.SERVING_PRIORITY_LOW


class ServingQueueFull(RuntimeError):
    """Graceful admission rejection: the waiting queue is at its bound.
    Callers back off / shed load; nothing in flight is affected.
    ``retry_after`` (seconds, may be None) is the backoff hint derived
    from the estimated backlog drain time."""

    def __init__(self, msg: str, retry_after: Optional[float] = None):
        super().__init__(msg)
        self.retry_after = retry_after


class ServingOverloaded(ServingQueueFull):
    """Load-shed rejection: the request's *estimated TTFT* (backlog over
    the measured step rate) exceeds the configured SLO.  Subclasses
    :class:`ServingQueueFull` so existing back-off handlers keep
    working; ``retry_after`` estimates when the backlog will have
    drained below the SLO."""


class ServingDraining(ServingQueueFull):
    """Admission stopped: the engine received SIGTERM and is draining
    (docs/serving.md §Resilience).  Retry against the restarted engine
    — journaled undone work replays there."""


class _IdSource:
    """Process-global request ids: several engines in one process (bench
    sweeps build one per (kv, load) point) must not reuse ids — the
    telemetry trace keys per-request span lanes on them.  Journal
    replay preserves original ids, so :meth:`advance_past` bumps the
    counter beyond any replayed id before fresh submits resume."""

    def __init__(self):
        self._n = -1
        self._lock = threading.Lock()

    def __next__(self) -> int:
        with self._lock:
            self._n += 1
            return self._n

    def advance_past(self, request_id: int) -> None:
        with self._lock:
            self._n = max(self._n, int(request_id))


_REQUEST_IDS = _IdSource()


def advance_request_ids(request_id: int) -> None:
    """Module-level hook for journal replay (see :class:`_IdSource`)."""
    _REQUEST_IDS.advance_past(request_id)


@dataclasses.dataclass
class Request:
    """One sequence through the pool.  ``prompt`` is a 1-D int32 array;
    timings are host wall-clock stamps the SLO bench aggregates."""

    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    deadline_seconds: Optional[float] = None  # queue-wait bound; None = scheduler default
    # per-request sampling params (ride the fixed decode signature as
    # per-slot vectors; greedy when do_sample is False)
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    seed: int = 0
    # overload management (docs/serving.md §Resilience)
    priority: int = PRIORITY_NORMAL  # 0 high / 1 normal / 2 low
    retry_after: Optional[float] = None  # backoff hint on shed/expired results
    degraded: bool = False  # admitted under an engaged degradation ladder
    # caller-chosen idempotency key (the fleet router's at-most-once
    # admission contract; journaled in the submit record)
    client_key: Optional[str] = None
    # durable session KV (serving/kvcache): requests sharing a
    # session_id rebind the previous turn's parked pages instead of
    # re-prefilling; journaled so replay reuses the same session
    session_id: Optional[str] = None
    # multi-tenant dimension (serving/frontdoor/tenants.py): journaled
    # (``tn``) so per-tenant accounting reconciles across a crash;
    # ``wfq_tag`` is the start-time-fair-queueing virtual start time —
    # the pop order when a TenantRegistry is attached to the scheduler
    tenant: Optional[str] = None
    wfq_tag: float = 0.0
    # tokens already cached at admission (prefix/session hit) — prefill
    # starts here; 0 on the slot pool and on kvcache misses
    prefix_hint: int = 0

    status: str = QUEUED
    slot: Optional[int] = None
    prefill_pos: int = 0  # prompt tokens written to the cache so far
    generated: List[int] = dataclasses.field(default_factory=list)
    finish_reason: Optional[str] = None  # eos | length | expired
    submit_time: float = 0.0
    admit_time: Optional[float] = None  # queue -> slot (prefill starts)
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    submit_step: int = 0
    admit_step: Optional[int] = None
    first_token_step: Optional[int] = None
    finish_step: Optional[int] = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    def tokens(self) -> np.ndarray:
        """prompt + generated (the solo-``generate()``-comparable view)."""
        return np.concatenate([self.prompt, np.asarray(self.generated, np.int32)])


@dataclasses.dataclass
class PrefillJob:
    """One prompt chunk: write ``tokens`` (padded to the chunk size) at
    cache position ``start`` of the request's slot.  ``take_idx`` is the
    within-chunk index of the last real token — where the first
    generated token is sampled when ``final``."""

    req: Request
    start: int
    tokens: np.ndarray  # (prefill_chunk,) int32, zero-padded past `length`
    length: int
    final: bool
    take_idx: int


@dataclasses.dataclass
class StepPlan:
    """This tick's prefill chunks.  The decode set is NOT planned here:
    the engine derives it from :meth:`ContinuousScheduler.decode_inputs`
    *after* the chunks land, so a request whose final chunk completed
    this very step decodes this step too."""

    prefill_jobs: List[PrefillJob]


class DegradationLadder:
    """Graduated load response with hysteresis (docs/serving.md
    §Resilience).  ``update`` is called once per scheduler tick with the
    queue depth; ``engage_steps`` consecutive pressured ticks climb one
    rung, ``disengage_steps`` consecutive calm ticks step one down —
    engaging fast and disengaging slow so the ladder does not flap at
    the watermark.

    Rungs: 0 normal · 1 clamp new admits' ``max_new_tokens`` · 2 shrink
    the prefill chunk budget to one chunk/step · 3 shed queued
    low-priority requests.
    """

    RUNGS = ("normal", "clamp_new_tokens", "shrink_prefill", "shed_low_priority")
    MAX_LEVEL = 3

    def __init__(self, max_queue: int, watermark: float = 0.75,
                 engage_steps: int = 8, disengage_steps: int = 16):
        self.max_queue = int(max_queue)
        self.watermark = float(watermark)
        self.engage_steps = max(1, int(engage_steps))
        self.disengage_steps = max(1, int(disengage_steps))
        self.level = 0
        self.engagements = 0  # rung climbs over the scheduler's life
        self._pressured_ticks = 0
        self._calm_ticks = 0

    @property
    def rung(self) -> str:
        return self.RUNGS[self.level]

    def pressured(self, queue_depth: int) -> bool:
        return self.max_queue > 0 and queue_depth >= self.watermark * self.max_queue

    def update(self, queue_depth: int) -> int:
        """One tick; returns the (possibly changed) level."""
        if self.pressured(queue_depth):
            self._calm_ticks = 0
            self._pressured_ticks += 1
            if self._pressured_ticks >= self.engage_steps and self.level < self.MAX_LEVEL:
                self.level += 1
                self.engagements += 1
                self._pressured_ticks = 0
                logger.warning(
                    f"serving: degradation ladder engaged rung {self.level} "
                    f"({self.rung}) at queue depth {queue_depth}/{self.max_queue}"
                )
        else:
            self._pressured_ticks = 0
            self._calm_ticks += 1
            if self._calm_ticks >= self.disengage_steps and self.level > 0:
                self.level -= 1
                self._calm_ticks = 0
                logger.info(
                    f"serving: degradation ladder stepped down to rung "
                    f"{self.level} ({self.rung})"
                )
        return self.level


class AdmissionController:
    """Estimated-TTFT load shedding.  The estimate is a queueing model
    over *measured* time — ``step_seconds_fn`` returns the engine's
    recent mean serving-step wall (the telemetry registry's window when
    the plane is armed, a local EWMA otherwise); the backlog is counted
    in steps:

    * prefill work ahead: every queued prompt's chunks (plus the
      candidate's own) over the effective chunks-per-step budget;
    * slot wait: with no free slot, the mean remaining decode budget of
      the live set, times how many queue "generations" precede the
      candidate (``ceil(queue_position / num_slots)``).

    It is an *estimate* feeding an SLO threshold, not a guarantee — the
    point is that shed decisions track the actually-measured service
    rate, so a slow chip sheds sooner at the same queue depth.  High
    priority bypasses the test (only the hard ``max_queue`` bound
    applies); without a measurement yet (cold engine) everything
    admits."""

    def __init__(self, scheduler: "ContinuousScheduler", slo_ttft_ms: float,
                 retry_after_min: float = C.SERVING_RETRY_AFTER_MIN_SECONDS_DEFAULT):
        self.scheduler = scheduler
        self.slo_ttft_ms = float(slo_ttft_ms)
        self.retry_after_min = float(retry_after_min)
        self.shed = 0  # TTFT-shed submit rejections

    def estimate_ttft_seconds(self, prompt_len: int,
                              in_queue: bool = False,
                              prompt=None,
                              session_id: Optional[str] = None) -> Optional[float]:
        """``in_queue=True`` when the candidate already sits in the
        queue (the rung-3 shed path pricing a waiter's retry_after):
        its chunks are then inside the queue sum and its queue slot
        inside ``len(_queue)`` — adding them again would double-count.

        With a paged kvcache pool, prefill work is priced at the
        **post-hit budget**: the pool's side-effect-free
        ``prefix_hint_tokens`` probe subtracts the expected prefix /
        session hit from every queued prompt (and from the candidate,
        when its tokens are given), so shed decisions track the work
        the engine will actually do."""
        s = self.scheduler
        step_s = s.step_seconds_fn() if s.step_seconds_fn is not None else None
        if not step_s or step_s <= 0:
            return None
        chunk = s.prefill_chunk
        hint_fn = getattr(s.pool, "prefix_hint_tokens", None)

        def _remaining(r: "Request") -> int:
            left = max(r.prompt_len - r.prefill_pos, 0)
            if hint_fn is not None and r.prefill_pos == 0 and left > 0:
                left = max(left - hint_fn(r.prompt, r.session_id), 1)
            return left

        chunks = sum(math.ceil(_remaining(r) / chunk) for r in s._queue)
        if not in_queue:
            cand = int(prompt_len)
            if hint_fn is not None and prompt is not None and cand > 0:
                cand = max(cand - hint_fn(prompt, session_id), 1)
            chunks += math.ceil(cand / chunk)
        steps = math.ceil(chunks / s.effective_chunks_per_step())
        if not s.pool.free_slots:
            live = [r for r in s._active.values()]
            if live:
                remaining = [
                    max(r.max_new_tokens - len(r.generated), 1) for r in live
                ]
                mean_rem = sum(remaining) / len(remaining)
                waiters = len(s._queue) + (0 if in_queue else 1)
                generations = math.ceil(waiters / s.pool.num_slots)
                steps += int(mean_rem * generations)
        return steps * step_s

    def retry_after_seconds(self, est_s: Optional[float]) -> float:
        """How long until the backlog should have drained below the SLO
        (floored — a sub-50ms hint tells a client nothing)."""
        if est_s is None:
            return max(self.retry_after_min, 1.0)
        return max(self.retry_after_min, est_s - self.slo_ttft_ms / 1e3)

    def check(self, prompt_len: int, priority: int, prompt=None,
              session_id: Optional[str] = None) -> None:
        """Raise :class:`ServingOverloaded` when the candidate's
        estimated TTFT exceeds the SLO (normal/low priority only)."""
        if self.slo_ttft_ms <= 0 or priority <= PRIORITY_HIGH:
            return
        est = self.estimate_ttft_seconds(
            prompt_len, prompt=prompt, session_id=session_id
        )
        if est is not None and est * 1e3 > self.slo_ttft_ms:
            self.shed += 1
            retry = self.retry_after_seconds(est)
            raise ServingOverloaded(
                f"serving overloaded: estimated TTFT {est * 1e3:.0f}ms exceeds "
                f"slo_ttft_ms={self.slo_ttft_ms:g} "
                f"(queue {self.scheduler.queue_depth}, priority {priority}); "
                f"retry after {retry:.2f}s",
                retry_after=retry,
            )


class ContinuousScheduler:
    def __init__(
        self,
        pool: SlotKVPool,
        prefill_chunk: int,
        prefill_chunks_per_step: int = 1,
        max_queue: int = 64,
        deadline_seconds: float = 0.0,
        capacity: Optional[int] = None,
        slo_ttft_ms: float = 0.0,
        degrade_queue_watermark: float = C.SERVING_DEGRADE_QUEUE_WATERMARK_DEFAULT,
        degrade_engage_steps: int = C.SERVING_DEGRADE_ENGAGE_STEPS_DEFAULT,
        degrade_disengage_steps: int = C.SERVING_DEGRADE_DISENGAGE_STEPS_DEFAULT,
        degrade_max_new_tokens: int = C.SERVING_DEGRADE_MAX_NEW_TOKENS_DEFAULT,
    ):
        self.pool = pool
        self.prefill_chunk = int(prefill_chunk)
        self.prefill_chunks_per_step = max(1, int(prefill_chunks_per_step))
        self.max_queue = int(max_queue)
        self.deadline_seconds = float(deadline_seconds)
        # admission bound on prompt+generated length (pool capacity
        # clamped by the engine's generation capacity)
        self.capacity = int(capacity) if capacity is not None else pool.max_len
        self._queue: Deque[Request] = deque()
        self._active: Dict[int, Request] = {}  # slot -> request
        self._finished: Dict[int, Request] = {}  # request_id -> request
        self._ids = _REQUEST_IDS
        self.submitted = 0
        self.rejected = 0
        self.expired = 0
        self.shed_count = 0  # queued requests shed by the ladder
        self.cancelled_count = 0  # explicit cancel() retirements
        self.finished_count = 0
        self.degrade_max_new_tokens = max(0, int(degrade_max_new_tokens))
        self.ladder = DegradationLadder(
            max_queue=self.max_queue,
            watermark=degrade_queue_watermark,
            engage_steps=degrade_engage_steps,
            disengage_steps=degrade_disengage_steps,
        )
        self.admission = AdmissionController(self, slo_ttft_ms=slo_ttft_ms)
        # measured serving-step wall feed (seconds; engine-owned so the
        # scheduler stays jax- and telemetry-free)
        self.step_seconds_fn: Optional[Callable[[], Optional[float]]] = None
        # lifecycle observer (the serving engine's telemetry hook):
        # called as on_event(kind, request, now, step) at "admitted",
        # "first_token", "finished", "expired" transitions.  Pure host
        # callback — the scheduler itself stays jax- and telemetry-free.
        self.on_event: Optional[Any] = None
        # TenantRegistry (serving/frontdoor/tenants.py) when the tenant
        # dimension is armed: submits get WFQ tags and _pop_next picks
        # the tenant with the lowest outstanding tag first.  The
        # scheduler stays tenant-policy-free — the registry owns it.
        self.tenants: Optional[Any] = None

    def _emit(self, kind: str, r: Request, now: float, step: int) -> None:
        if self.on_event is not None:
            self.on_event(kind, r, now, step)

    # -- introspection ----------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def live(self) -> int:
        return len(self._active)

    def has_work(self) -> bool:
        return bool(self._queue or self._active)

    def pending_ids(self) -> List[int]:
        """Ids of every request not yet finished (queued + in-flight) —
        the graceful drain's undone set."""
        return sorted(
            [r.request_id for r in self._queue]
            + [r.request_id for r in self._active.values()]
        )

    def upcoming_hints(self, limit: int = 4) -> List[Tuple[Any, Optional[str]]]:
        """(prompt, session_id) of the next admits in priority-FIFO
        order — the KV tier manager's prefetch contract (docs/serving.md
        §KV tiering): pages these requests need promote back to T0
        *before* their prefill chunk runs.  Read-only on the queue."""
        if limit <= 0 or not self._queue:
            return []
        # priority-then-FIFO, matching _pop_next (0 = high; stable sort
        # preserves FIFO within a tier)
        ordered = sorted(
            self._queue, key=lambda r: getattr(r, "priority", 1)
        )
        return [
            (r.prompt, getattr(r, "session_id", None))
            for r in ordered[:limit]
        ]

    def request(self, request_id: int) -> Optional[Request]:
        if request_id in self._finished:
            return self._finished[request_id]
        for r in self._active.values():
            if r.request_id == request_id:
                return r
        for r in self._queue:
            if r.request_id == request_id:
                return r
        return None

    def pop_finished(self) -> Dict[int, Request]:
        out, self._finished = self._finished, {}
        return out

    def effective_chunks_per_step(self) -> int:
        """The prefill chunk budget after the degradation ladder: rung 2
        ("shrink_prefill") caps it at one chunk/step so decode latency
        for the live set is protected at the cost of new-request TTFT."""
        return 1 if self.ladder.level >= 2 else self.prefill_chunks_per_step

    # -- admission --------------------------------------------------------
    def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        eos_token_id: Optional[int] = None,
        deadline_seconds: Optional[float] = None,
        now: float = 0.0,
        step: int = 0,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        seed: int = 0,
        priority: int = PRIORITY_NORMAL,
        request_id: Optional[int] = None,
        bypass_admission: bool = False,
        client_key: Optional[str] = None,
        session_id: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> Request:
        """``priority``: 0 high (never TTFT-shed) / 1 normal / 2 low
        (first shed when the ladder tops out).  ``request_id`` +
        ``bypass_admission`` are the journal-replay surface: replayed
        requests were *already accepted* before the crash, so they keep
        their ids and skip every overload test."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if do_sample and temperature <= 0.0:
            raise ValueError(f"temperature must be > 0 when sampling, got {temperature}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if priority not in (PRIORITY_HIGH, PRIORITY_NORMAL, PRIORITY_LOW):
            raise ValueError(
                f"priority must be {PRIORITY_HIGH} (high), {PRIORITY_NORMAL} "
                f"(normal) or {PRIORITY_LOW} (low), got {priority}"
            )
        total = prompt.shape[0] + int(max_new_tokens)
        if total > self.capacity:
            raise ValueError(
                f"prompt_len + max_new_tokens = {prompt.shape[0]}+{max_new_tokens} "
                f"= {total} exceeds the serving capacity {self.capacity} "
                f"(pool max_len={self.pool.max_len})"
            )
        if not bypass_admission:
            if len(self._queue) >= self.max_queue:
                self.rejected += 1
                retry = self.admission.retry_after_seconds(
                    self.admission.estimate_ttft_seconds(prompt.shape[0])
                )
                raise ServingQueueFull(
                    f"serving queue is full ({len(self._queue)} waiting >= "
                    f"max_queue={self.max_queue}); retry after ~{retry:.2f}s "
                    f"or raise serving.max_queue",
                    retry_after=retry,
                )
            if self.ladder.level >= 3 and priority >= PRIORITY_LOW:
                # rung 3: low priority is shed at the door, not queued
                # then expired — the queue is for work that can be served
                self.rejected += 1
                self.admission.shed += 1
                retry = self.admission.retry_after_seconds(
                    self.admission.estimate_ttft_seconds(prompt.shape[0])
                )
                raise ServingOverloaded(
                    f"serving overloaded: degradation ladder at rung "
                    f"{self.ladder.level} ({self.ladder.rung}) sheds low-priority "
                    f"submits; retry after {retry:.2f}s",
                    retry_after=retry,
                )
            # estimated-TTFT admission test (high priority bypasses)
            try:
                self.admission.check(
                    prompt.shape[0], priority, prompt=prompt,
                    session_id=session_id,
                )
            except ServingOverloaded:
                self.rejected += 1
                raise
        req = Request(
            request_id=next(self._ids) if request_id is None else int(request_id),
            prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            eos_token_id=eos_token_id,
            deadline_seconds=deadline_seconds,
            do_sample=bool(do_sample),
            temperature=float(temperature),
            top_k=int(top_k),
            seed=int(seed),
            priority=int(priority),
            client_key=client_key,
            session_id=session_id,
            tenant=tenant,
            submit_time=now,
            submit_step=step,
        )
        if self.tenants is not None:
            # weighted-fair queueing ahead of the priority tiers: the
            # tag fixes this request's place in the tenant-fair pop
            # order (replays are tagged too — fairness applies to the
            # recovered queue exactly as it did to the original)
            req.wfq_tag = self.tenants.tag(tenant, cost=float(total))
        if request_id is not None:
            self._ids.advance_past(request_id)
        self._queue.append(req)
        self.submitted += 1
        return req

    # -- per-step policy --------------------------------------------------
    def sweep_expired(self, now: float, step: int) -> int:
        """Expire queued requests past their queue-wait deadline.  Runs
        inside every :meth:`tick`, AND host-side from the engine's
        ``stats()``/``drain()`` — an idle engine (submitted work but no
        ``step()`` being driven) must still expire waiters rather than
        hold them past their deadline forever."""
        if not self._queue:
            return 0
        n = 0
        kept: Deque[Request] = deque()
        for r in self._queue:
            deadline = (
                r.deadline_seconds
                if r.deadline_seconds is not None
                else self.deadline_seconds
            )
            if deadline and (now - r.submit_time) > deadline:
                r.status = EXPIRED
                r.finish_reason = "expired"
                r.finish_time = now
                r.finish_step = step
                # same backoff contract as shed: every involuntary
                # retirement carries a retry_after hint
                r.retry_after = self.admission.retry_after_seconds(
                    self.admission.estimate_ttft_seconds(r.prompt_len, in_queue=True)
                )
                self._finished[r.request_id] = r
                self.expired += 1
                n += 1
                logger.warning(
                    f"serving: request {r.request_id} expired after "
                    f"{now - r.submit_time:.3f}s in queue (deadline {deadline:g}s)"
                )
                self._emit("expired", r, now, step)
            else:
                kept.append(r)
        self._queue = kept
        return n

    def shed_queued_low_priority(self, now: float, step: int) -> int:
        """Ladder rung 3: retire queued low-priority requests with a
        ``retry_after`` hint — explicit shed beats silent deadline death
        under sustained overload."""
        if not any(r.priority >= PRIORITY_LOW for r in self._queue):
            return 0
        n = 0
        kept: Deque[Request] = deque()
        for r in self._queue:
            if r.priority >= PRIORITY_LOW:
                r.status = SHED
                r.finish_reason = "shed"
                r.finish_time = now
                r.finish_step = step
                r.retry_after = self.admission.retry_after_seconds(
                    self.admission.estimate_ttft_seconds(r.prompt_len, in_queue=True)
                )
                self._finished[r.request_id] = r
                self.shed_count += 1
                n += 1
                self._emit("shed", r, now, step)
            else:
                kept.append(r)
        self._queue = kept
        if n:
            logger.warning(
                f"serving: shed {n} queued low-priority request(s) at ladder "
                f"rung {self.ladder.level}"
            )
        return n

    def cancel(self, request_id: int, now: float, step: int) -> bool:
        """Retire a queued or in-flight request without finishing it —
        the hedge loser's retirement path (docs/serving.md §Fleet).  An
        in-flight cancel frees the slot immediately (the freed slot's
        stale cache is unreachable by the overwrite-before-attend
        invariant); the result surfaces with status CANCELLED so the
        engine journals a retire record.  False when the id is unknown
        or already retired."""
        for i, r in enumerate(self._queue):
            if r.request_id == request_id:
                del self._queue[i]
                self._retire_cancelled(r, now, step)
                return True
        for slot, r in list(self._active.items()):
            if r.request_id == request_id:
                del self._active[slot]
                self._release_slot(slot, r, now)
                self._retire_cancelled(r, now, step)
                return True
        return False

    def _release_slot(self, slot: int, r: Request, now: float) -> None:
        """Return a slot to the pool: the paged pool's ``retire`` hook
        sees the request (so a finished turn can park under its
        session); the slot pool just frees."""
        retire = getattr(self.pool, "retire", None)
        if retire is not None:
            retire(slot, r, now=now)
        else:
            self.pool.free(slot)

    def _retire_cancelled(self, r: Request, now: float, step: int) -> None:
        r.status = CANCELLED
        r.finish_reason = "cancelled"
        r.finish_time = now
        r.finish_step = step
        self._finished[r.request_id] = r
        self.cancelled_count += 1
        self._emit("cancelled", r, now, step)

    def _pop_next(self) -> Request:
        """Highest-priority (lowest tier number) queued request, FIFO
        within a tier — an O(queue) scan, fine at max_queue scale.
        With a TenantRegistry attached, weighted-fair queueing picks the
        tenant FIRST (lowest outstanding virtual tag) and the
        priority-then-FIFO scan runs within that tenant only."""
        if self.tenants is not None:
            i = self.tenants.pick(self._queue)
            r = self._queue[i]
            del self._queue[i]
            return r
        best_i, best = 0, None
        for i, r in enumerate(self._queue):
            if best is None or r.priority < best.priority:
                best_i, best = i, r
                if r.priority == PRIORITY_HIGH:
                    break
        del self._queue[best_i]
        return best

    def tick(self, now: float, step: int, admit: bool = True) -> StepPlan:
        """Expire over-deadline waiters, update the degradation ladder,
        admit queued requests into free slots (priority-then-FIFO), and
        pick this step's prefill chunks.  ``admit=False`` is drain mode:
        in-flight requests keep decoding, the queue stays parked (its
        journaled work replays on the restarted engine)."""
        # 1) queue-wait deadlines
        self.sweep_expired(now, step)
        # 2) degradation ladder (hysteresis inside)
        self.ladder.update(len(self._queue))
        if admit and self.ladder.level >= 3:
            self.shed_queued_low_priority(now, step)
        # 3) admission: queued -> free slots (priority, then FIFO)
        while admit and self._queue and self.pool.free_slots:
            r = self._pop_next()
            if self.ladder.level >= 1 and self.degrade_max_new_tokens:
                # rung 1: clamp the generation budget of NEW admits only
                # — in-flight budgets are a contract already accepted
                if r.max_new_tokens > self.degrade_max_new_tokens:
                    r.max_new_tokens = self.degrade_max_new_tokens
                    r.degraded = True
            alloc_request = getattr(self.pool, "alloc_request", None)
            if alloc_request is not None:
                # hit-aware paged allocation: the pool resolves the
                # longest cached prefix / session rebind and sets
                # r.prefill_pos past it (serving/kvcache)
                r.prefill_pos = 0
                slot = alloc_request(r, now=now)
            else:
                r.prefill_pos = 0
                slot = self.pool.alloc(r.request_id)
            if slot is None:
                # out of pages (paged pool under sharing pressure):
                # park the request back at the queue head — retiring
                # slots free pages and the next tick retries
                self._queue.appendleft(r)
                break
            r.slot = slot
            r.status = PREFILL
            r.admit_time = now
            r.admit_step = step
            self._active[r.slot] = r
            self._emit("admitted", r, now, step)
        # 4) prefill chunk budget, FIFO over mid-prefill slots
        jobs: List[PrefillJob] = []
        budget = self.effective_chunks_per_step()
        prefilling = sorted(
            (r for r in self._active.values() if r.status == PREFILL),
            key=lambda r: r.request_id,
        )
        for r in prefilling:
            pos = r.prefill_pos
            while budget > 0 and pos < r.prompt_len:
                length = min(self.prefill_chunk, r.prompt_len - pos)
                chunk = np.zeros((self.prefill_chunk,), np.int32)
                chunk[:length] = r.prompt[pos : pos + length]
                jobs.append(
                    PrefillJob(
                        req=r,
                        start=pos,
                        tokens=chunk,
                        length=length,
                        final=pos + length >= r.prompt_len,
                        take_idx=length - 1,
                    )
                )
                pos += length
                budget -= 1
            if budget == 0:
                break
        return StepPlan(prefill_jobs=jobs)

    def note_prefill(self, job: PrefillJob, first_token: int, now: float, step: int) -> None:
        """A chunk landed; on the final chunk the sampled first token
        arrives (the TTFT moment) and the request joins the decode set —
        or retires immediately when its budget is a single token / the
        first token is EOS."""
        r = job.req
        r.prefill_pos = job.start + job.length
        if not job.final:
            return
        r.status = DECODE
        r.generated = [int(first_token)]
        r.first_token_time = now
        r.first_token_step = step
        self._emit("first_token", r, now, step)
        if len(r.generated) >= r.max_new_tokens or (
            r.eos_token_id is not None and first_token == r.eos_token_id
        ):
            self._finish(r, now, step)

    def decode_inputs(self) -> Tuple[np.ndarray, np.ndarray, List[Request]]:
        """Fixed-shape decode-step inputs over the whole pool.

        Decoding slots feed their latest token at its true position;
        every other slot gets a *safe* garbage position — one whose
        write is overwritten before it can ever be attended (the next
        chunk start for mid-prefill slots, 0 for free slots)."""
        toks = np.zeros((self.pool.num_slots,), np.int32)
        pos = np.zeros((self.pool.num_slots,), np.int32)
        decoding: List[Request] = []
        for slot, r in self._active.items():
            if r.status == DECODE:
                toks[slot] = r.generated[-1]
                pos[slot] = r.prompt_len + len(r.generated) - 1
                decoding.append(r)
            else:  # mid-prefill: next chunk overwrites this position
                pos[slot] = r.prefill_pos
        return toks, pos, decoding

    def sampling_inputs(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Fixed-shape per-slot sampling vectors (ride the same decode
        signature every step): do_sample flags, temperatures, top-k
        bounds, and seeds.  Non-active / non-sampling slots keep the
        greedy defaults — their computed token is either discarded
        (non-decoding) or the bare argmax (the solo-``generate()``
        bit-match path)."""
        S = self.pool.num_slots
        flags = np.zeros((S,), bool)
        temps = np.ones((S,), np.float32)
        topks = np.zeros((S,), np.int32)
        seeds = np.zeros((S,), np.uint32)
        for slot, r in self._active.items():
            flags[slot] = r.do_sample
            temps[slot] = np.float32(r.temperature)
            topks[slot] = np.int32(r.top_k)
            seeds[slot] = np.uint32(r.seed & 0xFFFFFFFF)
        return flags, temps, topks, seeds

    def note_decode(self, tokens_by_slot: Dict[int, int], now: float, step: int) -> None:
        """Append this step's token per decoding slot; retire at EOS or
        budget — the slot frees *this* token, not at batch end."""
        for slot, tok in tokens_by_slot.items():
            r = self._active[slot]
            r.generated.append(int(tok))
            if (r.eos_token_id is not None and tok == r.eos_token_id) or len(
                r.generated
            ) >= r.max_new_tokens:
                self._finish(r, now, step)

    def _finish(self, r: Request, now: float, step: int) -> None:
        r.status = DONE
        r.finish_reason = (
            "eos"
            if (r.eos_token_id is not None and r.generated and r.generated[-1] == r.eos_token_id)
            else "length"
        )
        r.finish_time = now
        r.finish_step = step
        del self._active[r.slot]
        self._release_slot(r.slot, r, now)
        self._finished[r.request_id] = r
        self.finished_count += 1
        self._emit("finished", r, now, step)
