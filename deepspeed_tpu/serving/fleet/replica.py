"""Replica handles: the interface the FleetRouter routes against.

A replica is anything that can ``submit/step/cancel/pop_results`` and
answer liveness + load questions — duck-typed, so the router serves

* :class:`LocalReplica` — an in-process :class:`~deepspeed_tpu.serving.
  engine.ServingEngine` built by a factory over its own journal
  directory.  ``kill()`` models process loss faithfully: the engine
  object is DROPPED without drain, so only journal-committed state
  survives — exactly the durable set a ``kill -9`` leaves behind.
  ``restart()`` rebuilds through the factory and replays the journal
  under original ids (the lossless-restart contract).
* process replicas — ``tools/fleet_chaos.py`` implements the same
  surface over a child-process JSONL pipe whose EOF is the death
  signal (the heartbeat channel's SIGKILL shape, PR 5).

The required surface (see :class:`LocalReplica` for semantics):
``name``, ``alive()``, ``submit(prompt, **kw) -> id``, ``cancel(id)``,
``step()``, ``has_work()``, ``pop_results()``, ``result(id)``,
``first_token_seen(id)``, ``estimate_ttft(prompt_len)``,
``queue_depth()``, ``degrade_level()``, ``draining()``,
``client_request_id(key)``, ``restart() -> replayed ids``, ``stats()``.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.utils.logging import logger


class ReplicaDeadError(RuntimeError):
    """The replica's process is gone (or its in-process stand-in was
    killed): the route attempt never reached a journal ack, so the
    router may safely retry the request on another replica."""


class LocalReplica:
    """In-process replica over a factory-built ServingEngine.

    The factory MUST bind a stable per-replica ``journal_dir`` — the
    journal is the identity that survives ``kill()``; a journal-less
    factory still restarts, but replays nothing (lossy, logged).

    ``warm`` (optional) runs against every factory-built engine BEFORE
    it serves — restart included, before ``recover()`` replays — so a
    rebuilt replica compiles its two executables off the routing path
    instead of charging the jit trace to the replayed requests' TTFT.
    """

    def __init__(self, name: str, factory: Callable[[], Any],
                 warm: Optional[Callable[[Any], None]] = None):
        self.name = str(name)
        self._factory = factory
        self._warm = warm
        self.engine = factory()
        if warm is not None:
            warm(self.engine)
        self._dead = False
        self.kills = 0
        if self.engine._journal is None:
            logger.warning(
                f"fleet: replica {self.name} has no journal armed — a death "
                "loses its accepted work (restart replays nothing)"
            )

    # -- liveness ---------------------------------------------------------
    def alive(self) -> bool:
        return not self._dead

    def kill(self, reason: str = "killed") -> None:
        """Model a process loss: drop the engine mid-flight.  No drain,
        no final commit — the journal keeps only what was committed at
        the moment of death, which is the whole point."""
        self._dead = True
        self.engine = None
        self.kills += 1
        logger.warning(f"fleet: replica {self.name} killed ({reason})")

    def restart(self) -> List[int]:
        """Rebuild through the factory over the same journal directory
        and replay: incomplete acknowledged requests come back under
        their ORIGINAL ids (greedy and seeded-sampling replays
        bit-match the uninterrupted run — docs/serving.md §Resilience).
        The warm hook runs before the replay so the rebuilt engine's
        compile cost never lands on the replayed requests."""
        self.engine = self._factory()
        if self._warm is not None:
            self._warm(self.engine)
        self._dead = False
        return self.engine.recover()

    def _require_alive(self):
        if self._dead or self.engine is None:
            raise ReplicaDeadError(f"replica {self.name} is dead")
        return self.engine

    # -- request surface --------------------------------------------------
    def submit(self, prompt, **kw) -> int:
        return self._require_alive().submit(prompt, **kw)

    def cancel(self, request_id: int) -> bool:
        if self._dead or self.engine is None:
            return False
        return self.engine.cancel(request_id)

    def step(self) -> bool:
        return self._require_alive().step()

    def has_work(self) -> bool:
        if self._dead or self.engine is None:
            return False
        return self.engine.scheduler.has_work()

    def pop_results(self) -> Dict[int, Any]:
        if self._dead or self.engine is None:
            return {}
        return self.engine.pop_results()

    def result(self, request_id: int) -> Optional[Any]:
        if self._dead or self.engine is None:
            return None
        return self.engine.result(request_id)

    def first_token_seen(self, request_id: int) -> bool:
        r = self.result(request_id)
        return r is not None and r.first_token_time is not None

    def client_request_id(self, client_key: str) -> Optional[int]:
        if self._dead or self.engine is None:
            return None
        return self.engine.client_request_id(client_key)

    # -- load / health feeds ----------------------------------------------
    def estimate_ttft(self, prompt_len: int) -> Optional[float]:
        """The replica's own admission estimate (queue backlog over its
        measured step rate) — the router's least-estimated-TTFT placement
        signal.  None on a cold replica (no measurement = no penalty)."""
        if self._dead or self.engine is None:
            return None
        return self.engine.scheduler.admission.estimate_ttft_seconds(prompt_len)

    def kv_affinity(self, prompt, session_id: Optional[str] = None) -> float:
        """Prompt tokens this replica could serve from its paged KV —
        a parked session for ``session_id`` or a cached prefix — the
        router's placement-affinity signal (docs/serving.md §Paged KV &
        prefix caching).  With KV tiering armed the count is priced by
        residency (HBM/host 1.0 > host 0.75 > disk 0.5): a replica that
        must promote from disk offers less than one already holding the
        pages warm.  Side-effect-free; 0 on the slot-contiguous pool, a
        dead replica, or a miss."""
        if self._dead or self.engine is None:
            return 0.0
        priced = getattr(self.engine.pool, "affinity_tokens", None)
        if priced is not None:
            return float(priced(prompt, session_id=session_id))
        hint = getattr(self.engine.pool, "prefix_hint_tokens", None)
        if hint is None:
            return 0.0
        return float(hint(prompt, session_id=session_id))

    def queue_depth(self) -> int:
        if self._dead or self.engine is None:
            return 0
        return self.engine.scheduler.queue_depth

    def degrade_level(self) -> int:
        if self._dead or self.engine is None:
            return 0
        return self.engine.scheduler.ladder.level

    def draining(self) -> bool:
        if self._dead or self.engine is None:
            return False
        wd = self.engine._watchdog
        return bool(wd is not None and wd.draining)

    def stats(self) -> Dict[str, Any]:
        if self._dead or self.engine is None:
            return {"dead": True}
        return self.engine.stats()

    # -- live migration (docs/serving.md §Elastic fleet) ------------------
    def export_sessions(self, dest_dir: str) -> List[str]:
        """Scale-down: write this replica's parked sessions and pinned
        prefixes into ``dest_dir`` in the spill wire format (read-only
        on the pool — retryable).  Empty on a slot-contiguous pool.
        Fault site ``migrate.export`` (fail / latency / sigkill)."""
        engine = self._require_alive()
        faults.check("migrate.export")
        faults.check_latency("migrate.export")
        export = getattr(engine.pool, "export_sessions", None)
        if export is None:
            return []
        return export(dest_dir, now=time.monotonic())

    def import_sessions(self, src_dir: str) -> Dict[str, int]:
        """Survivor side: adopt every manifest-verified entry under
        ``src_dir``.  Fault site ``migrate.import``."""
        engine = self._require_alive()
        faults.check("migrate.import")
        faults.check_latency("migrate.import")
        imp = getattr(engine.pool, "import_sessions", None)
        if imp is None:
            return {}
        return imp(src_dir, now=time.monotonic())

    def sweep_sessions(self, now: float) -> int:
        """TTL-sweep parked sessions host-side.  The engine sweeps per
        step, so an idle (drained-but-alive) replica never steps and
        never expires — the autoscaler tick calls this instead
        (docs/serving.md §Elastic fleet)."""
        if self._dead or self.engine is None:
            return 0
        sweep = getattr(self.engine.pool, "sweep", None)
        if sweep is None:
            return 0
        return int(sweep(now))


__all__ = ["LocalReplica", "ReplicaDeadError"]
