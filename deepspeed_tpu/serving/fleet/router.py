"""FleetRouter: the front door over N serving-engine replicas.

One ``submit()`` surface for the whole fleet (docs/serving.md §Fleet):

* **placement** — least-estimated-TTFT: each routable replica prices
  the candidate through its own admission controller (queue backlog
  over its measured step rate), degraded replicas are deprioritized,
  and ties rotate round-robin.  A replica that rejects with
  ``retry_after`` is held under router-level backpressure for exactly
  that long — the engine's hint IS the router's schedule.
* **failure handling** — a submit that fails before the journal ack is
  retried on another replica (bounded by ``route_retries``; safe
  because an un-acknowledged request is un-journaled by the WAL
  contract).  Per-replica circuit breakers (consecutive-failure trip,
  half-open probes, seeded-jitter exponential backoff) take chronically
  failing replicas out of rotation.  Optional tail-latency hedging
  duplicates a still-first-token-less request to a second replica after
  ``hedge_factor x`` the observed p99 TTFT; the first leg to produce a
  token wins and the loser is cancelled via scheduler retirement.
* **lossless restart** — on replica death (liveness EOF, an injected
  ``replica.death``, or a route failure surfacing
  :class:`~deepspeed_tpu.serving.fleet.replica.ReplicaDeadError`) the
  router marks it dead and hands it to the
  :class:`~deepspeed_tpu.serving.fleet.supervisor.ReplicaSupervisor`;
  the restarted engine replays its journal under ORIGINAL ids and the
  router re-binds in-flight handles to the replayed requests —
  acknowledged work completes bit-identically.  Requests whose results
  died with an unrestartable replica are re-fired on another replica:
  generation is a deterministic function of the journaled fields, so
  the re-run reproduces the same tokens.
* **at-most-once admission** — ``client_key`` dedups against the
  router's handle map AND every live replica's journal-backed key map,
  so a client retry after a crash adopts the original admission instead
  of double-serving.

Fault sites (chaos matrix): ``router.route`` (fail + recurring
latency), ``router.hedge``, ``replica.death``.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from deepspeed_tpu import telemetry as _telemetry
from deepspeed_tpu.config.config import FleetConfig
from deepspeed_tpu.resilience import faults
from deepspeed_tpu.resilience.policy import RetryPolicy
from deepspeed_tpu.serving.fleet.health import (
    DEAD,
    HEALTHY,
    CircuitBreaker,
    ReplicaHealth,
)
from deepspeed_tpu.serving.fleet.replica import ReplicaDeadError
from deepspeed_tpu.serving.fleet.supervisor import RESTART_PENDING
from deepspeed_tpu.serving.scheduler import ServingOverloaded, ServingQueueFull
from deepspeed_tpu.utils.logging import log_dist, logger


class FleetOverloaded(ServingOverloaded):
    """Every routable replica rejected (or none is routable).
    ``retry_after`` is the soonest any replica expects to admit — the
    minimum over the per-replica hints, the fleet-level backpressure
    contract."""


@dataclasses.dataclass
class FleetHandle:
    """One client request as the router tracks it: the primary binding,
    the optional hedge leg, and the original submit parameters (the
    hedge/re-fire path re-submits from these — deterministic outputs
    make that a bit-identical re-run, not a different answer)."""

    handle_id: int
    prompt: np.ndarray
    kwargs: Dict[str, Any]
    client_key: Optional[str]
    submit_time: float
    replica: str
    request_id: int
    hedge_wanted: bool = False
    hedge_replica: Optional[str] = None
    hedge_request_id: Optional[int] = None
    hedged_at: Optional[float] = None
    winner: Optional[str] = None
    refires: int = 0
    done: bool = False


class FleetRouter:
    def __init__(
        self,
        replicas: List[Any],
        config: Any = None,
        supervisor: Any = None,
        seed: int = 0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not replicas:
            raise ValueError("FleetRouter requires at least one replica")
        if config is None:
            config = FleetConfig()
        elif isinstance(config, dict):
            config = FleetConfig.from_dict(config)
        self.config = config
        self._clock = clock
        self._supervisor = supervisor
        self._replicas: Dict[str, Any] = {}
        self._order: List[str] = []
        self._health: Dict[str, ReplicaHealth] = {}
        self._seed = int(seed)
        self._policy = RetryPolicy(
            backoff_seconds=config.breaker_backoff_seconds,
            backoff_max_seconds=config.breaker_backoff_max_seconds,
        )
        # guards fleet MEMBERSHIP (_replicas/_order/_health): the
        # autoscaler's warm-pool add and scale-down remove may race the
        # routing thread's iteration (ds_race: scale-down-while-route).
        # Routing itself stays single-threaded; iteration takes
        # snapshots and tolerates names vanishing mid-walk.
        self._mlock = threading.RLock()
        self._added = 0  # lifetime adds (stable breaker seed offsets)
        for rep in replicas:
            self.add_replica(rep)
        self._rr = 0  # round-robin tie-break rotation
        self._next_handle = 0
        self._handles: Dict[int, FleetHandle] = {}
        self._by_rid: Dict[Tuple[str, int], int] = {}
        self._results: Dict[int, Any] = {}
        self._client_handles: Dict[str, int] = {}
        self._backpressure: Dict[str, float] = {}  # name -> held until
        self._refire_pending: List[int] = []
        self._restarting: Set[str] = set()  # background restarts underway
        self._ttft_ms: List[float] = []  # delivered-TTFT window (hedge p99)
        # counters (mirrored into the telemetry registry when armed)
        self.routed = 0
        self.rejections = 0  # per-replica retry_after rejections absorbed
        self.failovers = 0  # submits that succeeded on a non-first replica
        self.route_failures = 0
        self.deaths = 0
        self.hedges = 0
        self.hedge_wins = 0  # hedge leg beat the primary
        self.hedge_cancelled = 0  # loser legs retired
        self.refired = 0
        self.affinity_routes = 0  # placements won by KV affinity
        self.last_failover: Optional[Dict[str, Any]] = None
        self.telemetry = _telemetry.manager_for("fleet")
        log_dist(
            f"fleet: router over {len(self._order)} replica(s) "
            f"({', '.join(self._order)}); breaker trips at "
            f"{config.breaker_failures} consecutive failures, hedging "
            f"{'on' if config.hedge else 'off'}"
        )

    # ------------------------------------------------------------------
    # membership (docs/serving.md §Elastic fleet)
    # ------------------------------------------------------------------
    def add_replica(self, rep: Any) -> None:
        """Bring a replica into rotation (elastic scale-up; also the
        constructor's own registration path).  Safe against a concurrent
        routing walk — membership mutates under ``_mlock`` and the walks
        snapshot."""
        name = rep.name
        with self._mlock:
            if name in self._replicas:
                raise ValueError(f"duplicate replica name {name!r}")
            health = ReplicaHealth(
                name,
                CircuitBreaker(
                    failure_threshold=self.config.breaker_failures,
                    policy=self._policy,
                    halfopen_probes=self.config.breaker_halfopen_probes,
                    seed=self._seed + self._added,
                    clock=self._clock,
                ),
            )
            self._added += 1
            self._replicas[name] = rep
            self._order.append(name)
            self._health[name] = health

    def remove_replica(self, name: str) -> Any:
        """Take a replica out of the fleet entirely (elastic scale-down,
        after drain + migration).  Refuses while any unresolved handle
        is still bound to it — the autoscaler must drain first."""
        with self._mlock:
            if name not in self._replicas:
                raise ValueError(f"unknown replica {name!r}")
            bound = self.inflight_on(name)
            if bound:
                raise ValueError(
                    f"replica {name!r} still holds {bound} in-flight "
                    f"handle(s); drain before removing"
                )
            rep = self._replicas.pop(name)
            self._order.remove(name)
            self._health.pop(name, None)
            self._backpressure.pop(name, None)
            self._restarting.discard(name)
            return rep

    def begin_drain(self, name: str, reason: str = "scale-down") -> None:
        """Stop routing NEW work at a replica; in-flight work keeps
        stepping to completion (DRAINING is stepped but not routable)."""
        h = self._health.get(name)
        if h is None:
            raise ValueError(f"unknown replica {name!r}")
        h.mark_draining(reason)

    def abort_drain(self, name: str) -> None:
        """Put a draining replica back into rotation (scale-down aborted
        at its migration deadline)."""
        h = self._health.get(name)
        if h is not None:
            h.mark_undrained()

    def inflight_on(self, name: str) -> int:
        """Unresolved handles whose primary or hedge leg is bound to
        ``name`` — the scale-down gate."""
        return sum(
            1 for hd in self._handles.values()
            if not hd.done and (hd.replica == name or hd.hedge_replica == name)
        )

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------
    def _pick(self, prompt_len: int, exclude: Set[str], now: float,
              prompt: Optional[np.ndarray] = None,
              session_id: Optional[str] = None) -> Optional[str]:
        """Least-estimated-TTFT over routable, non-backpressured
        replicas; degraded states rank after healthy; ties rotate.
        When ``prompt`` is given, KV affinity dominates within a health
        tier: the replica holding the request's parked session or
        longest cached prefix wins placement (docs/serving.md §Paged KV
        & prefix caching).  Hedge legs pass no prompt — a hedge exists
        to ESCAPE the primary, so it must not be pulled back by the
        primary's warm cache."""
        scored = []
        order = list(self._order)  # snapshot: membership may mutate
        n = len(order)
        for i, name in enumerate(order):
            if name in exclude:
                continue
            rep = self._replicas.get(name)
            h = self._health.get(name)
            if rep is None or h is None:
                continue  # removed mid-walk
            if not rep.alive() or not h.routable(now):
                continue
            if self._backpressure.get(name, 0.0) > now:
                continue  # honoring the replica's own retry_after
            aff = 0.0
            if prompt is not None:
                probe = getattr(rep, "kv_affinity", None)
                if probe is not None:
                    try:
                        # float: tier-priced affinity (host 0.75 / disk
                        # 0.5 per token) must keep its fraction so warm
                        # residency outbids a disk-resident copy
                        aff = float(probe(prompt, session_id=session_id))
                    except Exception:  # a probe failure must not unroute
                        aff = 0.0
            est = rep.estimate_ttft(prompt_len)
            scored.append((
                0 if h.state == HEALTHY else 1,
                -aff,
                est if est is not None else 0.0,
                rep.queue_depth(),
                (i - self._rr) % n,
                name,
            ))
        if not scored:
            return None
        self._rr += 1
        best = min(scored)
        if best[1] < 0:
            self.affinity_routes += 1
            if self.telemetry.collect:
                self.telemetry.counter("fleet/affinity_routes").inc()
        return best[-1]

    def _route(
        self,
        prompt: np.ndarray,
        kwargs: Dict[str, Any],
        exclude: Set[str],
        now: float,
        client_key: Optional[str] = None,
    ) -> Tuple[str, int]:
        """One placement: try up to ``route_retries + 1`` replicas.  A
        retry is safe exactly because a failed submit never produced a
        journal ack (the WAL contract: the id is acknowledged only after
        the submit record commits)."""
        hints: List[float] = []
        tried: Set[str] = set(exclude)
        attempts = 0
        while attempts <= self.config.route_retries:
            name = self._pick(len(prompt), tried, now, prompt=prompt,
                              session_id=kwargs.get("session_id"))
            if name is None:
                break
            attempts += 1
            tried.add(name)
            rep = self._replicas.get(name)
            h = self._health.get(name)
            if rep is None or h is None:
                continue  # removed between pick and submit
            try:
                rid = rep.submit(prompt, client_key=client_key, **kwargs)
            except ServingQueueFull as e:
                # overload is not a breaker failure — the replica is
                # alive and telling us exactly when to come back
                self.rejections += 1
                if e.retry_after:
                    self._backpressure[name] = max(
                        self._backpressure.get(name, 0.0), now + e.retry_after
                    )
                    hints.append(e.retry_after)
                continue
            except ReplicaDeadError:
                self._handle_death(name, "died at submit", now)
                continue
            except Exception as e:
                self.route_failures += 1
                tripped = h.breaker.record_failure(now)
                if self.telemetry.collect:
                    self.telemetry.counter("fleet/route_failures").inc()
                    if tripped:
                        self.telemetry.counter("fleet/breaker_trips").inc()
                logger.warning(f"fleet: submit to {name} failed ({e!r}); "
                               f"{'breaker OPEN, ' if tripped else ''}trying next")
                continue
            h.breaker.record_success()
            if attempts > 1:
                self.failovers += 1
                if self.telemetry.collect:
                    self.telemetry.counter("fleet/failovers").inc()
            return name, rid
        retry = min(hints) if hints else self._soonest_retry(now)
        raise FleetOverloaded(
            f"fleet overloaded: no replica admitted the request "
            f"({attempts} tried, {len(self._order)} total); retry after "
            f"~{retry:.2f}s",
            retry_after=retry,
        )

    def _soonest_retry(self, now: float) -> float:
        """When nothing is routable and nobody handed us a hint: the
        soonest a breaker half-opens or a backpressure hold expires."""
        candidates = [u - now for u in list(self._backpressure.values()) if u > now]
        for h in list(self._health.values()):
            if h.state != DEAD and h.breaker.retry_at is not None:
                candidates.append(h.breaker.retry_at - now)
        return max(min(candidates), 0.05) if candidates else 1.0

    # ------------------------------------------------------------------
    # request API
    # ------------------------------------------------------------------
    def submit(
        self,
        prompt,
        max_new_tokens: Optional[int] = None,
        client_key: Optional[str] = None,
        hedge: Optional[bool] = None,
        **kw,
    ) -> int:
        """Route one request into the fleet; returns a fleet-level
        handle id (stable across failover, restart, and hedging).
        Raises :class:`FleetOverloaded` (with the min ``retry_after``
        over the replicas' hints) when no replica admits."""
        faults.check("router.route")
        faults.check_latency("router.route")
        now = self._clock()
        if client_key is not None:
            known = self._client_handles.get(client_key)
            if known is not None:
                return known
            adopted = self._adopt_by_client_key(client_key, prompt, kw, now)
            if adopted is not None:
                return adopted
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        kwargs = dict(kw)
        if max_new_tokens is not None:
            kwargs["max_new_tokens"] = max_new_tokens
        name, rid = self._route(prompt, kwargs, set(), now, client_key=client_key)
        hid = self._next_handle
        self._next_handle += 1
        hd = FleetHandle(
            handle_id=hid,
            prompt=prompt,
            kwargs=kwargs,
            client_key=client_key,
            submit_time=now,
            replica=name,
            request_id=rid,
            hedge_wanted=self.config.hedge if hedge is None else bool(hedge),
        )
        self._handles[hid] = hd
        self._by_rid[(name, rid)] = hid
        if client_key is not None:
            self._client_handles[client_key] = hid
        if name not in self._replicas:
            # the replica was removed (elastic scale-down) between
            # placement and binding: nobody will ever step or collect
            # it, so re-fire now.  remove_replica refuses while a BOUND
            # handle exists, so exactly one side of this race acts —
            # either the removal saw the handle and refused, or we see
            # the removal here and re-route (ds_race:
            # scale-down-while-route).
            self._refire(hd, {name}, now)
        self.routed += 1
        if self.telemetry.collect:
            self.telemetry.counter("fleet/routed", replica=name).inc()
        return hid

    def _adopt_by_client_key(
        self, client_key: str, prompt, kw: Dict[str, Any], now: float
    ) -> Optional[int]:
        """Journal-checked dedup: if any live replica already
        acknowledged this key (possibly before a crash/restart), bind a
        handle to the EXISTING admission instead of submitting again."""
        for name in list(self._order):
            rep = self._replicas.get(name)
            if rep is None or not rep.alive():
                continue
            rid = rep.client_request_id(client_key)
            if rid is None:
                continue
            r = rep.result(rid)
            if r is None:
                # the admission was delivered and discharged — adopting
                # the dead id would strand the handle; treat the retry
                # as a new request instead
                continue
            hid = self._next_handle
            self._next_handle += 1
            hd = FleetHandle(
                handle_id=hid,
                prompt=np.asarray(prompt, np.int32).reshape(-1),
                kwargs=dict(kw),
                client_key=client_key,
                submit_time=now,
                replica=name,
                request_id=rid,
            )
            self._handles[hid] = hd
            self._by_rid[(name, rid)] = hid
            self._client_handles[client_key] = hid
            # the admission may have already retired: surface its result
            if r.finish_time is not None:
                hd.done = True
                hd.winner = name
                self._results[hid] = r
            log_dist(
                f"fleet: client_key {client_key!r} deduped to replica "
                f"{name} request {rid} (at-most-once admission)"
            )
            return hid
        return None

    def step(self) -> bool:
        """One fleet step: drive every live replica, detect deaths (and
        restart through the supervisor), collect results, resolve and
        launch hedges.  Returns whether any handle is still unresolved."""
        now = self._clock()
        self._poll_restarts(now)
        self._retry_refires(now)
        stepped = False
        for name in list(self._order):
            rep = self._replicas.get(name)
            h = self._health.get(name)
            if rep is None or h is None:
                continue  # removed mid-walk
            if h.state == DEAD:
                continue
            if rep.alive() and faults.check_flag("replica.death"):
                rep.kill("injected replica.death")
            if not rep.alive():
                self._handle_death(name, "replica process lost", now)
                continue
            try:
                if rep.has_work():
                    rep.step()
                    stepped = True
            except ReplicaDeadError:
                self._handle_death(name, "died mid-step", now)
                continue
            except Exception as e:
                tripped = h.breaker.record_failure(now)
                self.route_failures += 1
                logger.warning(
                    f"fleet: replica {name} step failed ({e!r})"
                    + ("; breaker OPEN" if tripped else "")
                )
                continue
            self._collect(name, rep, now)
            h.observe(rep.degrade_level(), rep.draining())
        self._resolve_hedges(now)
        self._maybe_hedge(now)
        if self._restarting and not stepped:
            # the fleet is idle waiting on a background rebuild: yield
            # the GIL so the restart thread makes progress instead of
            # busy-spinning (survivors with live work never pause here)
            time.sleep(0.002)
        return self.has_work()

    def has_work(self) -> bool:
        return any(not hd.done for hd in self._handles.values())

    def drain(self, max_steps: Optional[int] = None) -> Dict[int, Any]:
        """Step until every handle resolves (or ``max_steps``); returns
        and clears the {handle_id: result} map."""
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self.pop_results()

    def result(self, handle_id: int) -> Optional[Any]:
        return self._results.get(handle_id)

    def handle(self, handle_id: int) -> Optional[FleetHandle]:
        return self._handles.get(handle_id)

    def pop_results(self) -> Dict[int, Any]:
        out = {}
        for hid in [h.handle_id for h in self._handles.values() if h.done]:
            r = self._results.pop(hid, None)
            if r is not None:
                out[hid] = r
            hd = self._handles.pop(hid)
            if hd.client_key is not None:
                self._client_handles.pop(hd.client_key, None)
        return out

    # ------------------------------------------------------------------
    # collection + hedging
    # ------------------------------------------------------------------
    def _collect(self, name: str, rep, now: float) -> None:
        for rid, r in rep.pop_results().items():
            hid = self._by_rid.pop((name, rid), None)
            if hid is None:
                continue  # an already-settled hedge loser
            hd = self._handles.get(hid)
            if hd is None or hd.done:
                continue
            if getattr(r, "finish_reason", None) == "cancelled":
                continue  # the loser's retirement record
            if hd.hedge_request_id is not None:
                # a finished leg wins outright; retire the other
                if name == hd.replica:
                    self._cancel_leg(hd.hedge_replica, hd.hedge_request_id)
                elif name == hd.hedge_replica:
                    self._cancel_leg(hd.replica, hd.request_id)
                    hd.replica, hd.request_id = name, rid
                    self.hedge_wins += 1
                hd.hedge_replica = hd.hedge_request_id = hd.hedged_at = None
            hd.done = True
            hd.winner = name
            self._results[hid] = r
            if r.first_token_time is not None:
                self._ttft_ms.append((r.first_token_time - r.submit_time) * 1e3)
                if len(self._ttft_ms) > 1024:
                    del self._ttft_ms[:512]
                if self.telemetry.collect:
                    self.telemetry.histogram("fleet/ttft_ms").observe(
                        self._ttft_ms[-1]
                    )

    def _cancel_leg(self, name: Optional[str], rid: Optional[int]) -> None:
        """Loser retirement: scheduler-level cancel on whichever replica
        holds the losing leg (frees its slot mid-decode)."""
        if name is None or rid is None:
            return
        self._by_rid.pop((name, rid), None)
        rep = self._replicas.get(name)
        if rep is not None and rep.alive():
            try:
                if rep.cancel(rid):
                    self.hedge_cancelled += 1
                    if self.telemetry.collect:
                        self.telemetry.counter("fleet/hedge_cancelled").inc()
            except Exception as e:  # a failed cancel is cosmetic, not fatal
                logger.warning(f"fleet: cancel of {rid} on {name} failed: {e!r}")

    def hedge_delay_seconds(self) -> Optional[float]:
        """``hedge_factor x`` the observed p99 delivered-TTFT; None
        until ``hedge_min_observations`` samples exist (hedging with no
        tail evidence would just double-submit everything)."""
        if not self.config.hedge and not any(
            hd.hedge_wanted for hd in self._handles.values()
        ):
            return None
        if len(self._ttft_ms) < self.config.hedge_min_observations:
            return None
        p99_s = float(np.percentile(np.asarray(self._ttft_ms), 99)) / 1e3
        return max(p99_s * self.config.hedge_factor, 1e-4)

    def _maybe_hedge(self, now: float) -> None:
        delay = self.hedge_delay_seconds()
        if delay is None:
            return
        for hd in list(self._handles.values()):
            if (
                hd.done
                or not hd.hedge_wanted
                or hd.hedge_request_id is not None
                or now - hd.submit_time < delay
            ):
                continue
            prim = self._replicas.get(hd.replica)
            if prim is not None and prim.alive() and prim.first_token_seen(hd.request_id):
                continue  # the primary already produced a token
            faults.check("router.hedge")
            name2 = self._pick(len(hd.prompt), {hd.replica}, now)
            if name2 is None:
                continue
            rep2 = self._replicas.get(name2)
            if rep2 is None:
                continue
            try:
                # NB no client_key: the hedge is the router's own
                # duplicate, not a second client admission
                rid2 = rep2.submit(hd.prompt, **hd.kwargs)
            except ServingQueueFull:
                continue
            except Exception as e:
                self._health[name2].breaker.record_failure(now)
                logger.warning(f"fleet: hedge submit to {name2} failed: {e!r}")
                continue
            hd.hedge_replica, hd.hedge_request_id, hd.hedged_at = name2, rid2, now
            self._by_rid[(name2, rid2)] = hd.handle_id
            if name2 not in self._replicas:
                # same bind-vs-remove window as submit: drop the leg
                # (the primary is still running; re-hedging may re-arm)
                self._by_rid.pop((name2, rid2), None)
                hd.hedge_replica = hd.hedge_request_id = hd.hedged_at = None
                continue
            self.hedges += 1
            if self.telemetry.collect:
                self.telemetry.counter("fleet/hedges").inc()
            log_dist(
                f"fleet: hedged handle {hd.handle_id} to {name2} after "
                f"{now - hd.submit_time:.3f}s (delay {delay:.3f}s)"
            )

    def _resolve_hedges(self, now: float) -> None:
        """First-token-wins: the first leg to produce a token becomes
        the primary; the other is cancelled via scheduler retirement."""
        for hd in self._handles.values():
            if hd.done or hd.hedge_request_id is None:
                continue
            prim, sec = self._replicas.get(hd.replica), self._replicas.get(hd.hedge_replica)
            p_seen = prim is not None and prim.alive() and prim.first_token_seen(hd.request_id)
            s_seen = sec is not None and sec.alive() and sec.first_token_seen(hd.hedge_request_id)
            if p_seen:  # primary wins ties (it was first to be asked)
                self._cancel_leg(hd.hedge_replica, hd.hedge_request_id)
            elif s_seen:
                self._cancel_leg(hd.replica, hd.request_id)
                hd.replica, hd.request_id = hd.hedge_replica, hd.hedge_request_id
                self.hedge_wins += 1
            else:
                continue
            hd.hedge_replica = hd.hedge_request_id = hd.hedged_at = None

    # ------------------------------------------------------------------
    # death, restart, re-binding
    # ------------------------------------------------------------------
    def mark_dead(self, name: str, reason: str = "declared dead") -> None:
        """External death signal (heartbeat EOF observer, chaos tool)."""
        self._handle_death(name, reason, self._clock())

    def on_peer_event(self, name: str, kind: str, reason: str = "") -> None:
        """PR 5 heartbeat-channel feed: route a PeerEvent at the named
        replica (``dead`` -> death handling + restart, ``bye`` ->
        draining, no new routes)."""
        if kind == "dead":
            self._handle_death(name, reason or "heartbeat EOF", self._clock())
        else:
            h = self._health.get(name)
            if h is not None:
                h.on_peer_event(kind, reason)

    def _handle_death(self, name: str, reason: str, now: float) -> None:
        h = self._health.get(name)
        rep = self._replicas.get(name)
        if h is None or rep is None or h.state == DEAD:
            return
        h.mark_dead(reason, now)
        self.deaths += 1
        self.last_failover = {"replica": name, "reason": reason, "at": now}
        if self.telemetry.collect:
            self.telemetry.counter("fleet/deaths", replica=name).inc()
        replayed = None
        if self._supervisor is not None:
            replayed = self._supervisor.handle_death(rep, reason)
        if replayed is RESTART_PENDING:
            # background restart underway: the replica stays DEAD (and
            # out of placement) while its handles stay bound — they will
            # be re-bound or re-fired when the restart resolves, and the
            # surviving replicas keep serving in the meantime
            self._restarting.add(name)
            return
        if replayed is not None:
            h.revive()
            if self.telemetry.collect:
                self.telemetry.counter("fleet/restarts", replica=name).inc()
            self._rebind(name, set(int(r) for r in replayed), now)
        else:
            self._refire_all(name, now)

    def _poll_restarts(self, now: float) -> None:
        """Resolve background restarts (supervisor ``background=True``):
        revive + re-bind on success, re-fire the stranded handles when
        the replica stays dead."""
        if not self._restarting or self._supervisor is None:
            return
        for rep, replayed in self._supervisor.drain_completed():
            name = rep.name
            self._restarting.discard(name)
            if replayed is not None:
                self._health[name].revive()
                if self.telemetry.collect:
                    self.telemetry.counter("fleet/restarts", replica=name).inc()
                self._rebind(name, set(int(r) for r in replayed), now)
            else:
                self._refire_all(name, now)

    def _rebind(self, name: str, replayed: Set[int], now: float) -> None:
        """The restarted replica replayed its journal under original
        ids: handles whose request is in the replay set stay bound (the
        replay completes them bit-identically); handles whose request
        is NOT there (retired before the crash, result lost with the
        process) re-fire elsewhere."""
        rebound = refired = 0
        for hd in list(self._handles.values()):
            if hd.done:
                continue
            if hd.hedge_replica == name and hd.hedge_request_id is not None:
                if hd.hedge_request_id not in replayed:
                    # the hedge leg died unreplayed: drop it (the
                    # primary is still running; re-hedging may re-arm)
                    self._by_rid.pop((name, hd.hedge_request_id), None)
                    hd.hedge_replica = hd.hedge_request_id = hd.hedged_at = None
            if hd.replica != name:
                continue
            if hd.request_id in replayed:
                rebound += 1
            else:
                self._refire(hd, {name}, now)
                refired += 1
        log_dist(
            f"fleet: replica {name} re-bound {rebound} in-flight handle(s) "
            f"to replayed requests, re-fired {refired}"
        )

    def _refire_all(self, name: str, now: float) -> None:
        """The replica stays dead: every handle bound to it re-fires on
        the rest of the fleet (deterministic generation makes the re-run
        reproduce the lost outputs)."""
        for hd in list(self._handles.values()):
            if hd.done:
                continue
            if hd.hedge_replica == name and hd.hedge_request_id is not None:
                self._by_rid.pop((name, hd.hedge_request_id), None)
                hd.hedge_replica = hd.hedge_request_id = hd.hedged_at = None
            if hd.replica == name:
                self._refire(hd, {name}, now)

    def _refire(self, hd: FleetHandle, exclude: Set[str], now: float) -> None:
        self._by_rid.pop((hd.replica, hd.request_id), None)
        try:
            name2, rid2 = self._route(
                hd.prompt, hd.kwargs, exclude, now, client_key=hd.client_key
            )
        except ServingQueueFull:
            # the rest of the fleet is saturated right now: park the
            # handle and retry at the next step
            if hd.handle_id not in self._refire_pending:
                self._refire_pending.append(hd.handle_id)
            return
        hd.replica, hd.request_id = name2, rid2
        hd.refires += 1
        self.refired += 1
        self._by_rid[(name2, rid2)] = hd.handle_id
        if self.telemetry.collect:
            self.telemetry.counter("fleet/refired").inc()

    def _retry_refires(self, now: float) -> None:
        pending, self._refire_pending = self._refire_pending, []
        for hid in pending:
            hd = self._handles.get(hid)
            if hd is None or hd.done:
                continue
            dead = {n for n, h in list(self._health.items()) if h.state == DEAD}
            self._refire(hd, dead, now)

    # ------------------------------------------------------------------
    # introspection (ds_report fleet rows, bench records)
    # ------------------------------------------------------------------
    def replicas_by_state(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for h in list(self._health.values()):
            out[h.state] = out.get(h.state, 0) + 1
        return out

    def stats(self) -> Dict[str, Any]:
        return {
            "replicas": len(self._order),
            "replica_states": self.replicas_by_state(),
            "replica_health": {
                n: h.snapshot() for n, h in list(self._health.items())
            },
            "routed": self.routed,
            "rejections": self.rejections,
            "failovers": self.failovers,
            "route_failures": self.route_failures,
            "deaths": self.deaths,
            "restarts": sum(h.restarts for h in list(self._health.values())),
            "refired": self.refired,
            "affinity_routes": self.affinity_routes,
            "hedges": self.hedges,
            "hedge_wins": self.hedge_wins,
            "hedge_cancelled": self.hedge_cancelled,
            "inflight": sum(1 for h in self._handles.values() if not h.done),
            "last_failover": self.last_failover,
        }


__all__ = ["FleetRouter", "FleetHandle", "FleetOverloaded"]
