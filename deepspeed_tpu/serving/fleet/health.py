"""Fleet health plane: per-replica state machine + circuit breaker.

Every replica behind the :class:`~deepspeed_tpu.serving.fleet.router.
FleetRouter` carries a :class:`ReplicaHealth` — a four-state machine

    healthy -> degraded -> healthy      (ladder pressure, reversible)
    any     -> draining                 (SIGTERM observed; no new routes)
    any     -> dead                     (heartbeat EOF / process loss)
    dead    -> healthy                  (supervised restart + replay)

— fed by the replica's own telemetry (degradation-ladder rung, shed
rate) and by death signals (a heartbeat channel's ``PeerEvent`` for
process replicas, the handle's liveness flag in process).

The :class:`CircuitBreaker` is the route-failure half of the plane:
``breaker_failures`` CONSECUTIVE failures trip it OPEN, after which the
replica is skipped for a backoff drawn from PR 2's
:class:`~deepspeed_tpu.resilience.policy.RetryPolicy` schedule —
exponential across consecutive trips, capped, seeded jitter, the same
deterministic curve checkpoint I/O retries use.  When the backoff
elapses the breaker admits ``halfopen_probes`` HALF_OPEN probe
requests: one success re-closes (and resets the backoff exponent), one
failure re-opens with the next, longer backoff.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional

from deepspeed_tpu.resilience.policy import RetryPolicy
from deepspeed_tpu.utils.logging import logger

# replica states
HEALTHY = "healthy"
DEGRADED = "degraded"
DRAINING = "draining"
DEAD = "dead"

# breaker states
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure breaker with seeded-jitter exponential
    backoff.  ``clock`` is injectable so tests run at full speed."""

    def __init__(
        self,
        failure_threshold: int = 3,
        policy: Optional[RetryPolicy] = None,
        halfopen_probes: int = 1,
        seed: int = 0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.policy = policy if policy is not None else RetryPolicy()
        self.halfopen_probes = max(1, int(halfopen_probes))
        self._rng = random.Random(seed)
        self._clock = clock
        self.state = CLOSED
        self.consecutive_failures = 0
        self.trips = 0  # lifetime CLOSED->OPEN transitions
        self.retry_at: Optional[float] = None  # OPEN until (monotonic)
        self._backoff_attempt = 0  # resets on a half-open success
        self._probes_left = 0

    def allow(self, now: Optional[float] = None) -> bool:
        """May a request route to this replica right now?  An OPEN
        breaker whose backoff has elapsed transitions to HALF_OPEN here
        and hands out its probe tokens."""
        if self.state == CLOSED:
            return True
        now = self._clock() if now is None else now
        if self.state == OPEN:
            if self.retry_at is not None and now < self.retry_at:
                return False
            self.state = HALF_OPEN
            self._probes_left = self.halfopen_probes
        if self._probes_left > 0:
            self._probes_left -= 1
            return True
        return False

    def record_success(self) -> None:
        """A routed request was acknowledged: a half-open probe success
        closes the breaker and resets the backoff exponent."""
        self.consecutive_failures = 0
        if self.state != CLOSED:
            self.state = CLOSED
            self.retry_at = None
            self._backoff_attempt = 0

    def record_failure(self, now: Optional[float] = None) -> bool:
        """A routed request failed; returns True when this failure trips
        (or re-trips) the breaker OPEN."""
        now = self._clock() if now is None else now
        if self.state == HALF_OPEN:
            self._open(now)
            return True
        self.consecutive_failures += 1
        if self.state == CLOSED and self.consecutive_failures >= self.failure_threshold:
            self._open(now)
            return True
        return False

    def _open(self, now: float) -> None:
        self.state = OPEN
        self.trips += 1
        self._backoff_attempt += 1
        self.consecutive_failures = 0
        self._probes_left = 0
        pause = self.policy.delay(self._backoff_attempt, self._rng)
        self.retry_at = now + pause
        logger.warning(
            f"fleet: circuit breaker OPEN (trip {self.trips}); half-open "
            f"probe in {pause:.2f}s"
        )

    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "trips": self.trips,
            "consecutive_failures": self.consecutive_failures,
            "retry_at": self.retry_at,
        }


class ReplicaHealth:
    """One replica's state machine + breaker, as the router sees it."""

    def __init__(self, name: str, breaker: CircuitBreaker):
        self.name = name
        self.breaker = breaker
        self.state = HEALTHY
        self.reason: Optional[str] = None
        self.died_at: Optional[float] = None
        self.deaths = 0
        self.restarts = 0

    # -- transitions ------------------------------------------------------
    def mark_degraded(self, reason: str = "ladder engaged") -> None:
        if self.state == HEALTHY:
            self.state = DEGRADED
            self.reason = reason

    def mark_healthy(self) -> None:
        if self.state == DEGRADED:
            self.state = HEALTHY
            self.reason = None

    def mark_draining(self, reason: str = "drain signal") -> None:
        if self.state != DEAD:
            self.state = DRAINING
            self.reason = reason

    def mark_undrained(self) -> None:
        """Drain abandoned (e.g. an elastic scale-down aborted at its
        migration deadline): back into rotation.  Not a restart — the
        process never went away, so no counter moves."""
        if self.state == DRAINING:
            self.state = HEALTHY
            self.reason = None

    def mark_dead(self, reason: str, now: Optional[float] = None) -> None:
        if self.state != DEAD:
            self.state = DEAD
            self.reason = reason
            self.died_at = now if now is not None else time.monotonic()
            self.deaths += 1
            logger.warning(f"fleet: replica {self.name} marked dead ({reason})")

    def revive(self) -> None:
        """A supervised restart replayed the journal: back to healthy
        with a fresh breaker streak (the restarted process has not
        failed anything yet)."""
        self.state = HEALTHY
        self.reason = None
        self.restarts += 1
        self.breaker.record_success()

    # -- feeds ------------------------------------------------------------
    def observe(self, degrade_level: int, draining: bool = False) -> None:
        """Per-step telemetry feed: the replica's degradation-ladder
        rung (and drain flag) maps onto the reversible health states.
        Dead replicas only leave DEAD through :meth:`revive`."""
        if self.state == DEAD:
            return
        if draining:
            self.mark_draining()
            return
        if self.state == DRAINING:
            return
        if degrade_level >= 1:
            self.mark_degraded(f"ladder rung {degrade_level}")
        else:
            self.mark_healthy()

    def on_peer_event(self, kind: str, reason: str = "") -> None:
        """PR 5 heartbeat-channel feed: a ``dead`` event (socket EOF —
        what a kill -9 looks like from outside) kills the replica; a
        ``bye`` marks it draining (it announced a graceful exit)."""
        if kind == "dead":
            self.mark_dead(reason or "heartbeat EOF")
        elif kind == "bye":
            self.mark_draining(reason or "peer said bye")

    def routable(self, now: Optional[float] = None) -> bool:
        return self.state not in (DEAD, DRAINING) and self.breaker.allow(now)

    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "reason": self.reason,
            "deaths": self.deaths,
            "restarts": self.restarts,
            "breaker": self.breaker.snapshot(),
        }


__all__ = [
    "CircuitBreaker", "ReplicaHealth",
    "HEALTHY", "DEGRADED", "DRAINING", "DEAD",
    "CLOSED", "OPEN", "HALF_OPEN",
]
