"""FleetAutoscaler: load-driven replica count with live KV migration.

The elastic half of the fleet story (docs/serving.md §Elastic fleet).
The reference DeepSpeed ships ``deepspeed/elasticity/`` and
``runner.py --restarts`` because production fleets must survive spiky
traffic and node churn; here the same need is served by ONE component
watching the router's own signals:

* **signals** — per-replica queue depth and admitted-TTFT estimate
  (both straight off the replica surface the router already routes by)
  plus the router's rejection counter (the shed-rate proxy: every
  ``FleetOverloaded`` the fleet absorbed since the last tick).
* **hysteresis** — a tick is *hot* when any routable replica's queue
  depth or TTFT estimate crosses its scale-up threshold (or the fleet
  shed since the last tick), *cold* when every routable replica sits at
  or under ``scale_down_queue_depth`` with no shed.  ``engage_ticks``
  consecutive hot ticks trigger a scale-up, ``disengage_ticks``
  consecutive cold ticks a scale-down, each then held off by its own
  cooldown — four independent knobs so spiky load cannot flap the
  fleet.
* **scale-up** — replicas come from a :class:`WarmPool`: a background
  filler thread builds engines through the factory (and PR 14's warm
  hook, so the two executables compile OFF the routing thread — XLA
  compilation releases the GIL) and parks them ready; ``tick()`` just
  adopts one, which is O(bookkeeping) on the routing thread.  Fault
  site ``fleet.scale_up`` (fail / latency).
* **scale-down** — the victim transitions to DRAINING (no new routes;
  in-flight work keeps stepping), and once idle its parked sessions and
  pinned prefixes are **live-migrated** to a survivor: the victim's
  ``export_sessions`` writes the PR 15 spill wire format (manifest-last
  per entry, read-only on the victim — retryable), the survivor's
  ``import_sessions`` adopts every manifest-verified entry, router
  affinity re-points because the survivor now answers ``kv_affinity``
  for those sessions, and the post-migration turn continues
  bit-identically.  Export/import failures retry up to
  ``migration_retries`` times; a victim that dies mid-migration is
  handed to the router's death path (journal replay — zero acknowledged
  loss); a victim still holding in-flight work past
  ``migration_deadline_seconds`` ABORTS the scale-down and returns to
  rotation (scale-down never proceeds over live requests).

``tick()`` runs on the routing thread (call it between ``step()``s, the
same discipline the router's own bookkeeping follows).  The warm-pool
filler is the only thread the autoscaler itself starts.
"""
from __future__ import annotations

import shutil
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

from deepspeed_tpu.config.config import ElasticConfig
from deepspeed_tpu.resilience import faults
from deepspeed_tpu.serving.fleet.health import DRAINING
from deepspeed_tpu.serving.fleet.replica import ReplicaDeadError
from deepspeed_tpu.utils.logging import log_dist, logger


class WarmPool:
    """Pre-built replicas, filled by a background daemon thread so
    scale-up never charges an XLA compile to the routing thread.

    ``factory(name) -> replica`` builds one ready-to-serve replica (a
    :class:`LocalReplica` factory typically runs the warm hook inside).
    The filler keeps ``size`` replicas parked; :meth:`take` pops one in
    O(1).  ``size=0`` disables the pool (``take`` builds inline)."""

    def __init__(self, factory: Callable[[str], Any], size: int = 1,
                 name_prefix: str = "elastic"):
        self._factory = factory
        self.size = max(0, int(size))
        self._prefix = str(name_prefix)
        self._lock = threading.Lock()
        self._ready: Deque[Any] = deque()
        self._built = 0  # lifetime builds -> unique replica names
        self._failures = 0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.size > 0:
            self._thread = threading.Thread(
                target=self._fill_loop, name="fleet-warm-pool", daemon=True
            )
            self._thread.start()

    def _next_name(self) -> str:
        with self._lock:
            self._built += 1
            return f"{self._prefix}{self._built}"

    def _fill_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                deficit = self.size - len(self._ready)
            if deficit <= 0:
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            name = self._next_name()
            try:
                rep = self._factory(name)
            except Exception as e:
                with self._lock:
                    self._failures += 1
                logger.warning(f"fleet: warm-pool build of {name} failed: {e!r}")
                self._wake.wait(timeout=0.2)  # don't spin on a broken factory
                self._wake.clear()
                continue
            with self._lock:
                self._ready.append(rep)

    def take(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Pop a warm replica.  With an empty pool: waits up to
        ``timeout`` for the filler (None = no wait), then falls back to
        an INLINE build — correct but slow, and logged as such."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            rep = None
            with self._lock:
                if self._ready:
                    rep = self._ready.popleft()
            if rep is not None:
                # the Event is self-synchronized: signal the refill
                # outside the lock so every _wake access is lock-free
                self._wake.set()
                return rep
            if self._thread is None or deadline is None:
                break
            if time.monotonic() >= deadline:
                break
            time.sleep(0.005)
        name = self._next_name()
        logger.warning(
            f"fleet: warm pool empty; building replica {name} inline "
            "(scale-up pays the compile)"
        )
        try:
            return self._factory(name)
        except Exception as e:
            with self._lock:
                self._failures += 1
            logger.error(f"fleet: inline replica build of {name} failed: {e!r}")
            return None

    def ready(self) -> int:
        with self._lock:
            return len(self._ready)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "size": self.size,
                "ready": len(self._ready),
                "built": self._built,
                "build_failures": self._failures,
            }


class FleetAutoscaler:
    """Drive the router's replica count from its own load signals.

    ``router`` — a :class:`FleetRouter`.  ``replica_factory(name)``
    builds one ready replica (feeds the warm pool).  ``config`` — an
    :class:`ElasticConfig` (or dict).  ``clock`` is injectable so tests
    run hysteresis and cooldowns at full speed."""

    # drain phases (one victim at a time; stats() surfaces the phase)
    _IDLE = "idle"
    _DRAIN_WAIT = "draining"
    _MIGRATING = "migrating"

    def __init__(
        self,
        router: Any,
        replica_factory: Callable[[str], Any],
        config: Any = None,
        clock: Callable[[], float] = time.monotonic,
        handoff_root: Optional[str] = None,
    ):
        if config is None:
            config = ElasticConfig()
        elif isinstance(config, dict):
            config = ElasticConfig.from_dict(config)
        self.config = config
        self.router = router
        self._clock = clock
        self._handoff_root = handoff_root
        self.pool = WarmPool(replica_factory, size=config.warm_pool_size)
        self._hot_ticks = 0
        self._cold_ticks = 0
        self._last_scale_up = -float("inf")
        self._last_scale_down = -float("inf")
        self._last_rejections = int(getattr(router, "rejections", 0))
        self._hot_since: Optional[float] = None  # reaction-time anchor
        self._cold_since: Optional[float] = None
        # drain state (at most one victim at a time)
        self._phase = self._IDLE
        self._victim: Optional[str] = None
        self._drain_started = 0.0
        # counters / event log (ds_report + bench read these)
        self.ticks = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.scale_downs_aborted = 0
        self.migrations_completed = 0
        self.migrations_failed = 0
        self.sessions_migrated = 0
        self.last_scale_up_reaction_s: Optional[float] = None
        self.last_scale_down_reaction_s: Optional[float] = None
        self.events: Deque[Dict[str, Any]] = deque(maxlen=32)
        log_dist(
            f"fleet: autoscaler armed ({config.min_replicas}.."
            f"{config.max_replicas} replicas, up at queue>"
            f"{config.scale_up_queue_depth} or ttft>"
            f"{config.scale_up_ttft_seconds}s x{config.engage_ticks} ticks, "
            f"down at queue<={config.scale_down_queue_depth} "
            f"x{config.disengage_ticks} ticks, warm pool "
            f"{config.warm_pool_size})"
        )

    # -- signal plane -----------------------------------------------------
    def _routable(self) -> List[str]:
        out = []
        for name in list(self.router._order):
            rep = self.router._replicas.get(name)
            h = self.router._health.get(name)
            if rep is None or h is None:
                continue
            if rep.alive() and h.routable(self._clock()):
                out.append(name)
        return out

    def _read_signals(self) -> Dict[str, Any]:
        names = self._routable()
        depths, ests = [], []
        for name in names:
            rep = self.router._replicas.get(name)
            if rep is None:
                continue
            depths.append(int(rep.queue_depth()))
            est = rep.estimate_ttft(1)
            if est is not None:
                ests.append(float(est))
        rejections = int(getattr(self.router, "rejections", 0))
        shed = rejections - self._last_rejections
        self._last_rejections = rejections
        return {
            "routable": len(names),
            "max_queue_depth": max(depths) if depths else 0,
            "max_ttft_est": max(ests) if ests else 0.0,
            "shed": max(0, shed),
        }

    # -- the tick ---------------------------------------------------------
    def tick(self) -> None:
        """One autoscaler evaluation; call on the routing thread between
        router steps.  Cheap: signal reads + bookkeeping; the only heavy
        work (engine builds) happens on the warm-pool filler thread."""
        now = self._clock()
        self.ticks += 1
        self._sweep_idle_sessions(now)
        if self._phase != self._IDLE:
            self._continue_drain(now)
            return
        sig = self._read_signals()
        n = len(self.router._order)
        hot = (
            sig["max_queue_depth"] > self.config.scale_up_queue_depth
            or sig["max_ttft_est"] > self.config.scale_up_ttft_seconds
            or sig["shed"] > 0
        )
        cold = (
            not hot
            and sig["shed"] == 0
            and sig["max_queue_depth"] <= self.config.scale_down_queue_depth
        )
        if hot:
            self._hot_ticks += 1
            self._cold_ticks = 0
            self._cold_since = None
            if self._hot_since is None:
                self._hot_since = now
        elif cold:
            self._cold_ticks += 1
            self._hot_ticks = 0
            self._hot_since = None
            if self._cold_since is None:
                self._cold_since = now
        else:
            self._hot_ticks = self._cold_ticks = 0
            self._hot_since = self._cold_since = None
        if (
            self._hot_ticks >= self.config.engage_ticks
            and n < self.config.max_replicas
            and now - self._last_scale_up >= self.config.scale_up_cooldown_seconds
        ):
            self._scale_up(now)
        elif (
            self._cold_ticks >= self.config.disengage_ticks
            and n > self.config.min_replicas
            and now - self._last_scale_down
            >= self.config.scale_down_cooldown_seconds
        ):
            self.request_scale_down(now=now)

    # -- scale-up ---------------------------------------------------------
    def _scale_up(self, now: float) -> None:
        try:
            faults.check("fleet.scale_up")
            faults.check_latency("fleet.scale_up")
            rep = self.pool.take()
        except Exception as e:
            logger.warning(f"fleet: scale-up failed: {e!r}")
            self.events.append({"kind": "scale_up_failed", "at": now,
                                "reason": repr(e)})
            self._hot_ticks = 0  # re-earn the trigger rather than spin
            return
        if rep is None:
            self._hot_ticks = 0
            return
        self.router.add_replica(rep)
        self.scale_ups += 1
        self._last_scale_up = now
        reaction = (now - self._hot_since) if self._hot_since is not None else 0.0
        self.last_scale_up_reaction_s = reaction
        self._hot_ticks = 0
        self._hot_since = None
        self.events.append({
            "kind": "scale_up", "at": now, "replica": rep.name,
            "reaction_s": reaction,
        })
        log_dist(
            f"fleet: scaled UP to {len(self.router._order)} replicas "
            f"(+{rep.name}, reaction {reaction:.3f}s)"
        )

    # -- scale-down / migration -------------------------------------------
    def request_scale_down(self, name: Optional[str] = None,
                           now: Optional[float] = None) -> bool:
        """Begin draining a victim (default: the most recently added
        routable replica — LIFO keeps the original fleet stable).
        Returns False when no eligible victim exists or a drain is
        already underway."""
        if self._phase != self._IDLE:
            return False
        now = self._clock() if now is None else now
        if name is None:
            routable = self._routable()
            if len(self.router._order) <= self.config.min_replicas:
                return False
            if not routable:
                return False
            name = routable[-1]
        elif name not in self.router._replicas:
            return False
        self.router.begin_drain(name, "elastic scale-down")
        self._phase = self._DRAIN_WAIT
        self._victim = name
        self._drain_started = now
        self.events.append({"kind": "drain_start", "at": now, "replica": name})
        log_dist(f"fleet: draining replica {name} for scale-down")
        return True

    def _continue_drain(self, now: float) -> None:
        name = self._victim
        rep = self.router._replicas.get(name)
        h = self.router._health.get(name)
        if rep is None or h is None:
            self._finish_drain(now, removed=False)
            return
        if not rep.alive() or h.state not in (DRAINING,):
            # the victim died (or was revived by someone else) while
            # draining: the router's death path owns it now — journal
            # replay reproduces anything the migration would have moved
            self._abort_drain(now, reason="victim left draining state")
            return
        inflight = self.router.inflight_on(name)
        if inflight > 0:
            if now - self._drain_started > self.config.migration_deadline_seconds:
                # NEVER proceed over live requests: give up the
                # scale-down and put the victim back into rotation
                self._abort_drain(
                    now,
                    reason=f"{inflight} in-flight past the "
                    f"{self.config.migration_deadline_seconds}s deadline",
                )
            return
        self._phase = self._MIGRATING
        self._migrate(name, rep, now)

    def _pick_survivor(self, victim: str) -> Optional[Any]:
        for name in reversed(self._routable()):
            if name != victim:
                return self.router._replicas.get(name)
        return None

    def _migrate(self, victim_name: str, victim: Any, now: float) -> None:
        """Move the victim's parked sessions + pinned prefixes to a
        survivor.  Bounded retries; total failure only costs warmth
        (the next turn re-prefills), never acknowledged work."""
        survivor = self._pick_survivor(victim_name)
        exporter = getattr(victim, "export_sessions", None)
        importer = getattr(survivor, "import_sessions", None) if survivor else None
        if exporter is None or importer is None:
            self._finish_drain(now, removed=True)  # nothing to move
            return
        handoff = tempfile.mkdtemp(
            prefix=f"migrate_{victim_name}_", dir=self._handoff_root
        )
        attempts = self.config.migration_retries + 1
        moved = None
        for attempt in range(attempts):
            try:
                exported = exporter(handoff)
                counts = importer(handoff)
                moved = (exported, counts)
                break
            except ReplicaDeadError:
                # the victim's process died mid-migration: hand it to
                # the router's death path — the supervisor restart +
                # journal replay keeps acknowledged work lossless, and
                # this scale-down is abandoned
                self.migrations_failed += 1
                self.events.append({
                    "kind": "migration_died", "at": now, "replica": victim_name,
                })
                logger.warning(
                    f"fleet: replica {victim_name} died mid-migration; "
                    "falling back to journal replay"
                )
                shutil.rmtree(handoff, ignore_errors=True)
                self._phase = self._IDLE
                self._victim = None
                self.router.mark_dead(victim_name, "died mid-migration")
                return
            except Exception as e:
                logger.warning(
                    f"fleet: migration attempt {attempt + 1}/{attempts} "
                    f"from {victim_name} failed: {e!r}"
                )
        shutil.rmtree(handoff, ignore_errors=True)
        if moved is None:
            # migration never succeeded: proceed with removal anyway —
            # sessions the victim had spilled remain on ITS spill_dir
            # (journal/spill recovery territory); the fleet only loses
            # warmth, not acknowledged work
            self.migrations_failed += 1
            self.events.append({
                "kind": "migration_failed", "at": now, "replica": victim_name,
            })
        else:
            exported, counts = moved
            self.migrations_completed += 1
            self.sessions_migrated += int(counts.get("sessions", 0))
            self.events.append({
                "kind": "migration", "at": now, "replica": victim_name,
                "exported": len(exported), "imported": dict(counts),
            })
        self._finish_drain(now, removed=True)

    def _abort_drain(self, now: float, reason: str) -> None:
        name = self._victim
        self.scale_downs_aborted += 1
        self.events.append({
            "kind": "drain_aborted", "at": now, "replica": name,
            "reason": reason,
        })
        logger.warning(f"fleet: scale-down of {name} aborted ({reason})")
        h = self.router._health.get(name)
        if h is not None and h.state == DRAINING:
            self.router.abort_drain(name)
        self._phase = self._IDLE
        self._victim = None
        self._cold_ticks = 0
        self._cold_since = None
        self._last_scale_down = now  # cooldown before the next try

    def _finish_drain(self, now: float, removed: bool) -> None:
        name = self._victim
        if removed and name in self.router._replicas:
            try:
                self.router.remove_replica(name)
            except ValueError as e:  # late-bound handle appeared: abort
                self._abort_drain(now, reason=str(e))
                return
        self.scale_downs += 1
        self._last_scale_down = now
        reaction = now - self._drain_started
        self.last_scale_down_reaction_s = reaction
        self._phase = self._IDLE
        self._victim = None
        self._cold_ticks = 0
        self._cold_since = None
        self.events.append({
            "kind": "scale_down", "at": now, "replica": name,
            "reaction_s": reaction,
        })
        log_dist(
            f"fleet: scaled DOWN to {len(self.router._order)} replicas "
            f"(-{name}, drain+migrate {reaction:.3f}s)"
        )

    # -- idle-session TTL sweep (satellite: PR 10's bug shape) ------------
    def _sweep_idle_sessions(self, now: float) -> None:
        """An idle replica never steps, so its per-step pool sweep never
        runs and parked sessions never expire — sweep from the tick so a
        drained-but-alive replica still releases pages."""
        for name in list(self.router._order):
            rep = self.router._replicas.get(name)
            sweep = getattr(rep, "sweep_sessions", None) if rep else None
            if sweep is None:
                continue
            try:
                sweep(time.monotonic())
            except Exception:
                pass  # a dying replica's sweep must not kill the tick

    # -- introspection ----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            "replicas": len(self.router._order),
            "min_replicas": self.config.min_replicas,
            "max_replicas": self.config.max_replicas,
            "phase": self._phase,
            "victim": self._victim,
            "ticks": self.ticks,
            "hot_ticks": self._hot_ticks,
            "cold_ticks": self._cold_ticks,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "scale_downs_aborted": self.scale_downs_aborted,
            "migrations_completed": self.migrations_completed,
            "migrations_failed": self.migrations_failed,
            "sessions_migrated": self.sessions_migrated,
            "last_scale_up_reaction_s": self.last_scale_up_reaction_s,
            "last_scale_down_reaction_s": self.last_scale_down_reaction_s,
            "warm_pool": self.pool.stats(),
            "last_events": list(self.events)[-8:],
        }

    def stop(self) -> None:
        self.pool.stop()


__all__ = ["FleetAutoscaler", "WarmPool"]
