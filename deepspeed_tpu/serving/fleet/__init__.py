"""Fleet front-door: health-gated routing over N serving replicas with
failover, hedged retries, and lossless supervised restart.

See docs/serving.md §Fleet for the architecture."""
from deepspeed_tpu.serving.fleet.health import (
    CLOSED,
    DEAD,
    DEGRADED,
    DRAINING,
    HALF_OPEN,
    HEALTHY,
    OPEN,
    CircuitBreaker,
    ReplicaHealth,
)
from deepspeed_tpu.serving.fleet.elastic import FleetAutoscaler, WarmPool
from deepspeed_tpu.serving.fleet.replica import LocalReplica, ReplicaDeadError
from deepspeed_tpu.serving.fleet.router import (
    FleetHandle,
    FleetOverloaded,
    FleetRouter,
)
from deepspeed_tpu.serving.fleet.supervisor import ReplicaSupervisor

__all__ = [
    "FleetAutoscaler",
    "WarmPool",
    "FleetRouter",
    "FleetHandle",
    "FleetOverloaded",
    "LocalReplica",
    "ReplicaDeadError",
    "ReplicaSupervisor",
    "CircuitBreaker",
    "ReplicaHealth",
    "HEALTHY",
    "DEGRADED",
    "DRAINING",
    "DEAD",
    "CLOSED",
    "OPEN",
    "HALF_OPEN",
]
