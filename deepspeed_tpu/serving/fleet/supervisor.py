"""ReplicaSupervisor: restart dead replicas, losslessly.

The fleet's half of PR 5's supervision story: where the training
supervisor answers a dead rank with a rendezvous-wide restart, the
serving fleet answers a dead REPLICA with a local restart + journal
replay — the other replicas keep serving throughout.

Policy: at most ``max_restarts`` restarts per replica (a crash-looping
replica eventually stays dead rather than flapping forever), with the
:class:`~deepspeed_tpu.resilience.policy.RetryPolicy` backoff schedule
between attempts — exponential, capped, seeded jitter, the same curve
the circuit breaker and checkpoint I/O use.  ``sleep`` is injectable so
tests run at full speed.

Two execution modes:

* **sync** (default) — ``handle_death`` blocks through backoff +
  restart + replay and returns the replayed ids (the router re-binds
  in-flight handles to them) or None when the replica must stay dead
  (budget exhausted, or the restart itself failed — a factory raise
  counts as a consumed attempt).
* **background** (``background=True``) — ``handle_death`` returns the
  :data:`RESTART_PENDING` sentinel immediately and runs the restart on
  a daemon thread; the surviving replicas keep serving while the
  replacement rebuilds and warms (XLA compilation releases the GIL, so
  the routing loop genuinely overlaps it).  The router polls
  :meth:`drain_completed` each step and revives/re-binds on completion
  — this is what keeps admitted-TTFT near steady-state during a
  failover instead of charging every in-flight request the rebuild.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.resilience.policy import RetryPolicy
from deepspeed_tpu.utils.logging import logger

# handle_death's "restart underway" answer in background mode — distinct
# from None ("stays dead") and from a (possibly empty) replayed-id list
RESTART_PENDING = object()


class ReplicaSupervisor:
    def __init__(
        self,
        max_restarts: int = 3,
        policy: Optional[RetryPolicy] = None,
        seed: int = 0,
        sleep: Callable[[float], None] = time.sleep,
        background: bool = False,
        restart_budget_reset_seconds: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.max_restarts = max(0, int(max_restarts))
        self.policy = policy if policy is not None else RetryPolicy(
            backoff_seconds=0.2, backoff_max_seconds=5.0
        )
        self._rng = random.Random(seed)
        self._sleep = sleep
        self.background = bool(background)
        # leaky-bucket budget: every restart_budget_reset_seconds of
        # clean service since the last consumed attempt forgives one
        # attempt, so a long-lived elastic fleet isn't permanently
        # condemned by one bad hour.  0 = legacy never-decays behaviour.
        self.restart_budget_reset_seconds = max(
            0.0, float(restart_budget_reset_seconds)
        )
        self._clock = clock
        self._attempts: Dict[str, int] = {}  # name -> restarts consumed
        self._last_attempt_at: Dict[str, float] = {}
        self.restarts = 0  # successful restarts, fleet-wide
        self._lock = threading.Lock()
        self._threads: Dict[str, threading.Thread] = {}
        self._completed: List[Tuple[Any, Optional[List[int]]]] = []

    def attempts(self, name: str) -> int:
        self._decay_budget(name)
        return self._attempts.get(name, 0)

    def _decay_budget(self, name: str) -> None:
        """Forgive one consumed attempt per full reset interval of
        service since the last consumed attempt (leaky bucket)."""
        reset = self.restart_budget_reset_seconds
        if reset <= 0:
            return
        n = self._attempts.get(name, 0)
        if n <= 0:
            return
        last = self._last_attempt_at.get(name)
        if last is None:
            return
        forgiven = int((self._clock() - last) // reset)
        if forgiven <= 0:
            return
        remaining = max(0, n - forgiven)
        self._attempts[name] = remaining
        # the un-forgiven remainder keeps accruing from the same epoch
        self._last_attempt_at[name] = last + forgiven * reset
        logger.info(
            f"fleet: replica {name} earned back {n - remaining} restart "
            f"attempt(s) after clean service ({remaining} consumed remain)"
        )

    def handle_death(self, replica, reason: str):
        """Restart ``replica`` (anything with ``restart() -> replayed
        ids``) under the budget.  Returns the replayed ids, None when it
        must stay dead, or :data:`RESTART_PENDING` in background mode."""
        name = replica.name
        self._decay_budget(name)
        n = self._attempts.get(name, 0)
        if n >= self.max_restarts:
            logger.error(
                f"fleet: replica {name} dead ({reason}) and its restart "
                f"budget ({self.max_restarts}) is exhausted; it stays dead"
            )
            return None
        self._attempts[name] = n + 1
        self._last_attempt_at[name] = self._clock()
        pause = self.policy.delay(n + 1, self._rng)
        logger.warning(
            f"fleet: restarting replica {name} ({reason}); attempt "
            f"{n + 1}/{self.max_restarts} after {pause:.2f}s backoff"
            + (" [background]" if self.background else "")
        )
        if not self.background:
            self._sleep(pause)
            return self._restart(replica)
        t = threading.Thread(
            target=self._bg_restart, args=(replica, pause),
            name=f"fleet-restart-{name}", daemon=True,
        )
        with self._lock:
            self._threads[name] = t
        t.start()
        return RESTART_PENDING

    def _restart(self, replica) -> Optional[List[int]]:
        try:
            replayed = replica.restart()
        except Exception as e:
            logger.error(f"fleet: replica {replica.name} restart failed: {e!r}")
            return None
        with self._lock:
            # both the router's sync path and N background restart
            # threads land here — an unlocked += drops restarts
            self.restarts += 1
        logger.warning(
            f"fleet: replica {replica.name} restarted; journal replayed "
            f"{len(replayed)} request(s) under original ids"
        )
        return replayed

    def _bg_restart(self, replica, pause: float) -> None:
        self._sleep(pause)
        replayed = self._restart(replica)
        with self._lock:
            self._completed.append((replica, replayed))
            self._threads.pop(replica.name, None)

    def pending(self) -> bool:
        """Any background restart still underway?"""
        with self._lock:
            return bool(self._threads)

    def drain_completed(self) -> List[Tuple[Any, Optional[List[int]]]]:
        """Pop finished background restarts: (replica, replayed ids or
        None).  The router calls this each step and revives/re-binds."""
        with self._lock:
            out, self._completed = self._completed, []
        return out


__all__ = ["ReplicaSupervisor", "RESTART_PENDING"]
