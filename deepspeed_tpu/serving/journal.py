"""Write-ahead request journal: crash-recoverable serving state.

A serving engine crash (kill -9, OOM, preemption past the drain
deadline) must not lose accepted work.  The journal records every
request's lifecycle as append-only JSONL segments under one directory:

* ``submit`` — the full request (prompt, budget, eos, priority,
  sampling params), written and **fsynced before the request id is
  returned to the client**: an acknowledged request is durable.
* ``admit`` — the *effective* generation budget at admission (the
  degradation ladder may have clamped ``max_new_tokens``; a replay must
  reproduce the clamped run, not the requested one).
* ``first`` / ``retire`` — progress + completion markers.  A ``retire``
  makes the request complete: it never replays.
* ``reject`` — involuntary retirement (shed / expired), terminal like a
  retire but committed *immediately* by the engine: a crash right after
  a shed must not resurrect the shed request at replay.
* ``drain`` — the graceful-drain marker listing the ids left undone
  (informational; the undone set is derivable from submit−retire).

Recovery is replay-from-scratch: a restarted engine resubmits every
incomplete request (submitted, never retired) under its **original
request id**.  Greedy decoding is deterministic and per-request
sampling keys are ``fold_in(seed, position)`` — functions of journaled
fields only — so replayed outputs bit-match an uninterrupted run
(pinned in tests/test_serving_resilience.py).

Durability protocol (PR 2's `resilience/atomic.py` discipline):

* appends go to the ACTIVE segment (``wal_<n>.jsonl``); ``commit()``
  flushes + fsyncs it — the serving engine commits on every accepted
  submit and at each step boundary that retired work;
* every line carries a crc32 of its payload, so a torn tail (crash
  mid-append) is detected and dropped at replay instead of poisoning
  it; a corrupt line *followed by valid ones* is real corruption and
  raises;
* a journal instance never appends to a pre-existing file — it opens a
  fresh segment past the highest on disk (the old tail may be torn);
* segment **compaction** (bounded disk): once more than
  ``keep_segments`` sealed segments exist, the incomplete set is
  rewritten into one compact segment through the atomic tmp→rename
  protocol *before* the old segments are deleted — a kill between the
  rename and the deletes leaves duplicates, which replay dedups by id.

``serving.journal.commit`` is a fault-injection site: an injected
commit failure raises :class:`JournalError`, and the engine's response
is a **clean quarantine** — the directory is renamed ``.corrupt`` (kept
for post-mortem, never replayed) and journaling disables, while serving
continues.
"""
from __future__ import annotations

import json
import os
import re
import zlib
from typing import Any, Dict, List, Optional

from deepspeed_tpu.resilience import atomic, faults
from deepspeed_tpu.utils.logging import logger

SEGMENT_RE = re.compile(r"^wal_(\d{6})\.jsonl$")
QUARANTINE_SUFFIX = ".corrupt"

SUBMIT = "submit"
ADMIT = "admit"
FIRST = "first"
RETIRE = "retire"
REJECT = "reject"  # involuntary retirement (shed/expired): never replays
DRAIN = "drain"


class JournalError(RuntimeError):
    """A journal write/commit failed (or the log is corrupt beyond the
    torn-tail case).  The serving engine quarantines on this."""


def _encode(rec: Dict[str, Any]) -> str:
    payload = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    crc = zlib.crc32(payload.encode()) & 0xFFFFFFFF
    return f"{payload} {crc:08x}\n"


def _decode(line: str) -> Optional[Dict[str, Any]]:
    """Parse one journal line; None when the line fails its crc or does
    not parse (the torn-tail shape)."""
    line = line.rstrip("\n")
    if len(line) < 10 or line[-9] != " ":
        return None
    payload, crc_hex = line[:-9], line[-8:]
    try:
        if (zlib.crc32(payload.encode()) & 0xFFFFFFFF) != int(crc_hex, 16):
            return None
        rec = json.loads(payload)
    except (ValueError, TypeError):
        return None
    return rec if isinstance(rec, dict) and "t" in rec else None


def _segment_files(path: str) -> List[str]:
    try:
        names = os.listdir(path)
    except OSError:
        return []
    return sorted(n for n in names if SEGMENT_RE.match(n))


def read_records(path: str) -> List[Dict[str, Any]]:
    """All valid records across the journal's segments, in write order.
    A single invalid TAIL line per segment is dropped (torn append); an
    invalid line followed by valid ones raises :class:`JournalError`."""
    out: List[Dict[str, Any]] = []
    for name in _segment_files(path):
        full = os.path.join(path, name)
        with open(full) as f:
            lines = f.readlines()
        bad_at: Optional[int] = None
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            rec = _decode(line)
            if rec is None:
                bad_at = i
                continue
            if bad_at is not None:
                raise JournalError(
                    f"journal segment {name} line {bad_at + 1} is corrupt but "
                    f"later lines are valid — not a torn tail; quarantine the journal"
                )
            out.append(rec)
        if bad_at is not None:
            logger.warning(
                f"serving journal: dropped torn tail line {bad_at + 1} of {name} "
                "(crash mid-append)"
            )
    return out


def incomplete_requests(path: str) -> List[Dict[str, Any]]:
    """The replay set: merged submit records (admit-effective budget,
    duplicate submits deduped by id — compaction/replay re-journaling
    both produce them) for every id without a ``retire``."""
    merged: Dict[int, Dict[str, Any]] = {}
    for rec in read_records(path):
        t, rid = rec.get("t"), rec.get("id")
        if t == SUBMIT:
            merged[rid] = dict(rec)
        elif t == ADMIT and rid in merged:
            merged[rid]["max_new"] = rec.get("max_new", merged[rid].get("max_new"))
        elif t in (RETIRE, REJECT):
            # a reject is terminal exactly like a retire: a shed/expired
            # request must never be resurrected by recover()
            merged.pop(rid, None)
    return [merged[k] for k in sorted(merged)]


def client_keys(path: str) -> Dict[str, int]:
    """client_key -> request id over every journaled submit (latest
    wins): the at-most-once admission lookup — a resubmit carrying a
    key the journal has already acknowledged is a duplicate, even
    across a crash/restart (docs/serving.md §Fleet)."""
    out: Dict[str, int] = {}
    for rec in read_records(path):
        if rec.get("t") == SUBMIT and rec.get("ck"):
            out[str(rec["ck"])] = int(rec["id"])
    return out


class RequestJournal:
    def __init__(self, path: str, segment_records: int = 512, keep_segments: int = 4):
        self.path = os.path.abspath(path)
        self.segment_records = max(1, int(segment_records))
        self.keep_segments = max(1, int(keep_segments))
        os.makedirs(self.path, exist_ok=True)
        segs = _segment_files(self.path)
        self._seq = (int(SEGMENT_RE.match(segs[-1]).group(1)) + 1) if segs else 0
        self._fh = None
        self._segment_count = 0  # records in the active segment
        self._pending = 0  # appended-but-uncommitted records
        self.records = 0
        self.commits = 0
        self.quarantined: Optional[str] = None
        # the highest request id ever journaled here: the engine bumps
        # the process-global id counter past it at open, so a restarted
        # process that submits BEFORE recover() cannot reuse an
        # incomplete journaled id (whose retire record would silently
        # drop the old acknowledged request from the replay set)
        self.last_request_id = -1
        # client_key -> id over journaled submits (at-most-once lookup;
        # kept current by record_submit so the engine never re-reads)
        self.client_keys: Dict[str, int] = {}
        if segs:
            try:
                for rec in read_records(self.path):
                    rid = rec.get("id", -1)
                    if isinstance(rid, int):
                        self.last_request_id = max(self.last_request_id, rid)
                    if rec.get("t") == SUBMIT and rec.get("ck"):
                        self.client_keys[str(rec["ck"])] = int(rec["id"])
            except JournalError:
                pass  # replay (recover) surfaces + quarantines corruption
            # restart-loop bound: every construction opens a fresh
            # segment, and count-based rotation may never fire in a
            # crash-looping service — compact here when over the bound
            if len(segs) > self.keep_segments:
                try:
                    self._compact(segs)
                except JournalError:
                    pass  # corrupt log: leave it for recover() to quarantine
        self._open_segment()

    # -- segment plumbing -------------------------------------------------
    def _segment_name(self, seq: int) -> str:
        return os.path.join(self.path, f"wal_{seq:06d}.jsonl")

    def _open_segment(self) -> None:
        self._fh = open(self._segment_name(self._seq), "w")
        self._segment_count = 0

    def _append(self, rec: Dict[str, Any]) -> None:
        if self._fh is None:
            raise JournalError("journal is closed" + (
                f" (quarantined to {self.quarantined})" if self.quarantined else ""))
        try:
            self._fh.write(_encode(rec))
        except OSError as e:
            raise JournalError(f"journal append failed: {e}") from e
        self._segment_count += 1
        self._pending += 1
        self.records += 1
        if self._segment_count >= self.segment_records:
            self._rotate()

    def _rotate(self) -> None:
        """Seal the active segment (commit + close), compact if the
        sealed count exceeds the bound, open the next."""
        self.commit()
        self._fh.close()
        self._fh = None
        self._seq += 1
        sealed = _segment_files(self.path)
        if len(sealed) > self.keep_segments:
            self._compact(sealed)
        self._open_segment()
        atomic.fsync_dir(self.path)

    def _compact(self, sealed: List[str]) -> None:
        """Rewrite the incomplete set into one compact segment via the
        atomic tmp→rename protocol, THEN delete the older segments (a
        kill in between leaves duplicate submits; replay dedups)."""
        live = incomplete_requests(self.path)
        dest = self._segment_name(self._seq)
        self._seq += 1
        tmp = dest + ".tmp"
        with open(tmp, "w") as f:
            for rec in live:
                f.write(_encode(rec))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, dest)
        atomic.fsync_dir(self.path)
        for name in sealed:
            try:
                os.unlink(os.path.join(self.path, name))
            except OSError as e:
                logger.warning(f"serving journal: compaction could not delete {name}: {e}")
        logger.info(
            f"serving journal: compacted {len(sealed)} segments -> "
            f"{os.path.basename(dest)} ({len(live)} incomplete requests)"
        )

    # -- record API -------------------------------------------------------
    def record_submit(self, req) -> None:
        """One scheduler Request -> a durable submit record.  The caller
        commits before acknowledging the id to the client."""
        self._append({
            "t": SUBMIT, "id": int(req.request_id),
            "prompt": [int(x) for x in req.prompt],
            "max_new": int(req.max_new_tokens),
            "eos": None if req.eos_token_id is None else int(req.eos_token_id),
            "priority": int(getattr(req, "priority", 1)),
            "deadline": req.deadline_seconds,
            "do_sample": bool(req.do_sample),
            "temperature": float(req.temperature),
            "top_k": int(req.top_k),
            "seed": int(req.seed),
            **({"ck": str(req.client_key)} if getattr(req, "client_key", None) else {}),
            **({"sid": str(req.session_id)} if getattr(req, "session_id", None) else {}),
            **({"tn": str(req.tenant)} if getattr(req, "tenant", None) else {}),
        })
        if getattr(req, "client_key", None):
            self.client_keys[str(req.client_key)] = int(req.request_id)

    def record_admit(self, req) -> None:
        self._append({"t": ADMIT, "id": int(req.request_id),
                      "max_new": int(req.max_new_tokens),
                      "hit": int(getattr(req, "prefix_hint", 0))})

    def record_first_token(self, req) -> None:
        self._append({"t": FIRST, "id": int(req.request_id),
                      "tok": int(req.generated[0]) if req.generated else None})

    def record_retire(self, req) -> None:
        # ``n`` is the REALIZED token count — the billing ground truth
        # per-tenant accounting reconciles against across a crash (at
        # most one retire per id, so a tenant is never double-billed)
        self._append({"t": RETIRE, "id": int(req.request_id),
                      "reason": req.finish_reason or "?",
                      "n": len(getattr(req, "generated", []) or [])})

    def record_reject(self, req) -> None:
        """Involuntary retirement (shed / expired): terminal like a
        retire, but named so post-mortems can tell a served request from
        a shed one.  The engine commits this record IMMEDIATELY — a
        crash between a shed and the next step boundary must not
        resurrect the shed request at recover()."""
        self._append({"t": REJECT, "id": int(req.request_id),
                      "reason": req.finish_reason or "?",
                      "retry_after": req.retry_after})

    def record_drain(self, undone: List[int]) -> None:
        self._append({"t": DRAIN, "id": -1, "undone": [int(x) for x in undone]})

    @property
    def dirty(self) -> bool:
        return self._pending > 0

    def commit(self) -> None:
        """Make every appended record durable (flush + fsync).  Site
        ``serving.journal.commit`` injects failures here; any failure is
        a :class:`JournalError` the engine answers with quarantine."""
        if self._fh is None:
            raise JournalError("journal is closed" + (
                f" (quarantined to {self.quarantined})" if self.quarantined else ""))
        if self._pending == 0:
            return
        try:
            faults.check("serving.journal.commit", path=self.path)
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except OSError as e:
            raise JournalError(f"journal commit failed: {e}") from e
        self._pending = 0
        self.commits += 1

    def incomplete(self) -> List[Dict[str, Any]]:
        """The replay set from THIS journal's directory (reads the
        segments back — the on-disk truth, not in-memory state)."""
        if self.dirty:
            self.commit()
        return incomplete_requests(self.path)

    def close(self) -> None:
        if self._fh is not None:
            try:
                self.commit()
            finally:
                self._fh.close()
                self._fh = None

    def quarantine(self) -> str:
        """Move the whole journal directory aside (``.corrupt``, counter
        suffixed) and disable this instance — the clean response to a
        failed commit: serving continues, nothing half-durable ever
        replays, the evidence stays on disk."""
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
        dest = self.path + QUARANTINE_SUFFIX
        n = 1
        while os.path.exists(dest):
            dest = f"{self.path}{QUARANTINE_SUFFIX}{n}"
            n += 1
        try:
            os.rename(self.path, dest)
        except OSError as e:
            logger.warning(f"serving journal: quarantine rename failed: {e}")
            dest = self.path
        self.quarantined = dest
        logger.warning(f"serving journal: quarantined to {dest}; journaling disabled")
        return dest


__all__ = [
    "RequestJournal", "JournalError", "incomplete_requests", "read_records",
    "client_keys", "SUBMIT", "ADMIT", "FIRST", "RETIRE", "REJECT", "DRAIN",
]
