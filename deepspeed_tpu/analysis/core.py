"""Core lint model: severities, findings, rules, and the rule registry.

The linter is a pure-``ast`` pass (no imports of the linted code, no JAX
at analysis time) so it runs in well under a second on this package and
can gate CI on machines with no accelerator at all.  Rules register
themselves via :func:`register`; the runner instantiates each selected
rule once per invocation and feeds it either one file at a time
(``scope == "file"``) or the whole project (``scope == "project"``, for
cross-file checks like config-key drift).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Dict, Iterable, Optional

if TYPE_CHECKING:  # pragma: no cover - typing only
    from deepspeed_tpu.analysis.context import FileContext, ProjectContext


class Severity(enum.IntEnum):
    """Finding tiers.  A fails CI on new findings, B is a warning the
    report surfaces, C is advice.  Ordering: A > B > C."""

    C = 1
    B = 2
    A = 3

    @classmethod
    def parse(cls, name: str) -> "Severity":
        try:
            return cls[name.upper()]
        except KeyError:
            raise ValueError(f"unknown severity tier {name!r} (expected A, B or C)")


@dataclass
class Finding:
    """One lint hit.  ``fingerprint`` is filled in by the runner (it
    depends on the baseline root, which rules don't know about)."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: Severity = Severity.A
    fingerprint: str = ""

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.severity.name}] {self.rule}: {self.message}"


@dataclass
class Rule:
    """A registered rule.  ``check`` receives a ``FileContext`` for
    file-scope rules or a ``ProjectContext`` for project-scope rules and
    yields findings (severity defaults to the rule tier but a rule may
    emit mixed tiers, e.g. config-key drift)."""

    id: str
    tier: Severity
    description: str
    check: Callable[..., Iterable[Finding]]
    scope: str = "file"  # "file" | "project"


_REGISTRY: Dict[str, Rule] = {}


def register(rule_id: str, tier: Severity, description: str, scope: str = "file"):
    """Decorator: register ``fn(ctx) -> Iterable[Finding]`` as a rule."""

    def deco(fn):
        if rule_id in _REGISTRY:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        _REGISTRY[rule_id] = Rule(id=rule_id, tier=tier, description=description, check=fn, scope=scope)
        return fn

    return deco


def all_rules() -> Dict[str, Rule]:
    """Return the registry, importing the built-in rule modules on first
    use so ``import deepspeed_tpu.analysis`` stays cheap."""
    import deepspeed_tpu.analysis.rules  # noqa: F401  (side effect: registration)

    return dict(_REGISTRY)


def get_rule(rule_id: str) -> Rule:
    rules = all_rules()
    if rule_id not in rules:
        raise KeyError(f"unknown rule {rule_id!r}; known: {sorted(rules)}")
    return rules[rule_id]


def make_finding(
    rule: Rule, ctx: "FileContext", node, message: str, severity: Optional[Severity] = None
) -> Finding:
    """Convenience for rules: build a Finding anchored at an AST node."""
    return Finding(
        rule=rule.id,
        path=ctx.path,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0) + 1,
        message=message,
        severity=severity if severity is not None else rule.tier,
    )
