"""Lint runner: file collection, rule dispatch, suppression + baseline
filtering.  ``lint_paths`` is the library entry point (the CLI and the
test suite both go through it)."""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

from deepspeed_tpu.analysis import baseline as baseline_mod
from deepspeed_tpu.analysis.context import FileContext, ProjectContext
from deepspeed_tpu.analysis.core import Finding, Severity, all_rules

_SKIP_DIRS = {"__pycache__", ".git", ".tox", ".venv", "node_modules", "build", "dist"}


def collect_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        else:
            raise FileNotFoundError(p)
    return out


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)  # new, reportable
    baselined: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    files: int = 0
    parse_errors: List[Finding] = field(default_factory=list)
    baseline_path: Optional[str] = None

    def count(self, tier: Severity) -> int:
        return sum(1 for f in self.findings if f.severity == tier)

    def failing(self, fail_on: Severity = Severity.A) -> List[Finding]:
        return [f for f in self.findings + self.parse_errors if f.severity >= fail_on]

    @property
    def all_current(self) -> List[Finding]:
        """Every live (non-suppressed) finding — what --write-baseline records."""
        return self.findings + self.baselined


def _select_rules(select: Optional[Iterable[str]], disable: Optional[Iterable[str]]):
    rules = all_rules()
    if select:
        unknown = set(select) - set(rules)
        if unknown:
            raise KeyError(f"unknown rule(s): {sorted(unknown)}")
        rules = {rid: r for rid, r in rules.items() if rid in set(select)}
    if disable:
        unknown = set(disable) - set(all_rules())
        if unknown:
            raise KeyError(f"unknown rule(s): {sorted(unknown)}")
        rules = {rid: r for rid, r in rules.items() if rid not in set(disable)}
    return rules


def parse_files(paths: Sequence[str], result: LintResult) -> tuple:
    """Read + parse every .py under ``paths`` into FileContexts,
    recording unreadable/unparseable files as tier-A ``parse-error``
    findings on ``result``.  Shared by ds_lint and ds_race (the race
    runner reuses the whole parse stage, then runs its own rules)."""
    contexts: List[FileContext] = []
    sources: Dict[str, str] = {}
    for path in collect_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            result.parse_errors.append(
                Finding("parse-error", path, 1, 1, f"cannot read file: {e}", Severity.A)
            )
            continue
        sources[path] = source
        try:
            contexts.append(FileContext.parse(path, source))
        except SyntaxError as e:
            result.parse_errors.append(
                Finding("parse-error", path, e.lineno or 1, 1, f"syntax error: {e.msg}", Severity.A)
            )
    result.files = len(contexts)
    return contexts, sources


def lint_paths(
    paths: Sequence[str],
    select: Optional[Iterable[str]] = None,
    disable: Optional[Iterable[str]] = None,
    baseline_path: Optional[str] = None,
    use_baseline: bool = True,
) -> LintResult:
    result = LintResult()

    # -- parse ---------------------------------------------------------
    contexts, sources = parse_files(paths, result)
    by_path = {fc.path: fc for fc in contexts}

    # -- run rules -----------------------------------------------------
    root = os.path.commonpath([os.path.abspath(p) for p in paths]) if paths else os.getcwd()
    if os.path.isfile(root):
        root = os.path.dirname(root)
    project = ProjectContext(root=root, files=contexts)

    raw: List[Finding] = []
    for rule in _select_rules(select, disable).values():
        if rule.scope == "project":
            raw.extend(rule.check(rule, project))
        else:
            for fc in contexts:
                raw.extend(rule.check(rule, fc))

    # -- suppressions --------------------------------------------------
    live: List[Finding] = []
    for f in raw:
        fc = by_path.get(f.path)
        if fc is not None and fc.suppressions.is_suppressed(f.rule, f.line):
            result.suppressed += 1
        else:
            live.append(f)

    # -- baseline ------------------------------------------------------
    if baseline_path is None and use_baseline:
        baseline_path = baseline_mod.discover(paths)
    result.baseline_path = baseline_path
    fp_root = os.path.dirname(os.path.abspath(baseline_path)) if baseline_path else root
    baseline_mod.assign_fingerprints(live, fp_root, sources)

    known: Set[str] = set()
    if use_baseline and baseline_path and os.path.isfile(baseline_path):
        known = baseline_mod.load(baseline_path)
    for f in live:
        (result.baselined if f.fingerprint in known else result.findings).append(f)

    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    result.baselined.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return result
