"""``ds_lint`` command-line interface (and the ``deepspeed_tpu.analysis``
subcommand router: ``sanitize`` dispatches to ds_san, ``race`` to
ds_race, ``lint``/bare paths run the AST linter).

Exit codes: 0 clean (or only findings below the failing tier), 1 new
findings at/above the failing tier (default: tier A), 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from deepspeed_tpu.analysis import baseline as baseline_mod
from deepspeed_tpu.analysis.core import Severity, all_rules
from deepspeed_tpu.analysis.runner import LintResult, lint_paths


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ds_lint",
        description="JAX trace-safety & sharding static analysis for deepspeed_tpu "
        "(AST-based; never imports the linted code).",
    )
    p.add_argument("paths", nargs="*", help="files or directories to lint")
    p.add_argument("--baseline", metavar="PATH", help=f"baseline file (default: nearest {baseline_mod.BASELINE_NAME})")
    p.add_argument("--no-baseline", action="store_true", help="ignore any baseline file")
    p.add_argument(
        "--write-baseline", action="store_true",
        help="record all current findings as the new baseline and exit 0",
    )
    p.add_argument("--select", metavar="RULES", help="comma-separated rule ids to run (default: all)")
    p.add_argument("--disable", metavar="RULES", help="comma-separated rule ids to skip")
    p.add_argument(
        "--fail-on", default="A", choices=["A", "B", "C"],
        help="lowest tier that fails the run (default: A)",
    )
    p.add_argument("--format", default="text", choices=["text", "json"], dest="fmt")
    p.add_argument("--list-rules", action="store_true", help="print the rule catalog and exit")
    p.add_argument("-q", "--quiet", action="store_true", help="findings only, no summary")
    return p


def _split(raw: Optional[str]) -> Optional[List[str]]:
    if not raw:
        return None
    return [part.strip() for part in raw.split(",") if part.strip()]


def _print_catalog() -> None:
    rules = all_rules()
    width = max(len(r) for r in rules)
    for rid in sorted(rules, key=lambda r: (-rules[r].tier, r)):
        rule = rules[rid]
        print(f"[{rule.tier.name}] {rid.ljust(width)}  {rule.description}")


def _summarize(result: LintResult, elapsed: float, fail_on: Severity, quiet: bool) -> None:
    if quiet:
        return
    tiers = ", ".join(f"{result.count(t)} tier-{t.name}" for t in (Severity.A, Severity.B, Severity.C))
    bits = [f"{len(result.findings)} finding(s) ({tiers})", f"{result.files} file(s)"]
    if result.baselined:
        bits.append(f"{len(result.baselined)} baselined")
    if result.suppressed:
        bits.append(f"{result.suppressed} suppressed")
    if result.parse_errors:
        bits.append(f"{len(result.parse_errors)} unparsable")
    print(f"ds_lint: {', '.join(bits)} in {elapsed:.2f}s (failing tier: {fail_on.name}+)")


def cli_main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv and argv[0] == "sanitize":
        # the runtime sanitizer lives behind its own subcommand so the
        # lint path stays jax-free (and sub-second)
        from deepspeed_tpu.analysis.sanitizer.cli import sanitize_main

        return sanitize_main(argv[1:])
    if argv and argv[0] == "race":
        # lock-discipline analysis + stress harness; its static mode is
        # jax-free like lint, --stress imports the runtime
        from deepspeed_tpu.analysis.race.cli import cli_main as race_main

        return race_main(argv[1:])
    if argv and argv[0] == "shard":
        # partition-spec dataflow + compiled-collective audit; imports
        # the runtime (it compiles the engines), unlike lint/race
        from deepspeed_tpu.analysis.shard.cli import cli_main as shard_main

        return shard_main(argv[1:])
    if argv and argv[0] == "lint":
        argv = argv[1:]
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        _print_catalog()
        return 0
    if not args.paths:
        print("ds_lint: no paths given (try `ds_lint deepspeed_tpu/`)", file=sys.stderr)
        return 2
    fail_on = Severity.parse(args.fail_on)
    baseline_path = args.baseline
    if args.write_baseline and baseline_path is None:
        # Resolve the target file BEFORE linting so fingerprints are
        # rooted at its directory — otherwise a first-time baseline
        # would be written with roots that never match on re-read.
        baseline_path = baseline_mod.discover(args.paths) or os.path.join(
            os.getcwd(), baseline_mod.BASELINE_NAME
        )
    start = time.monotonic()
    try:
        result = lint_paths(
            args.paths,
            select=_split(args.select),
            disable=_split(args.disable),
            baseline_path=baseline_path,
            use_baseline=not args.no_baseline,
        )
    except (FileNotFoundError, KeyError, ValueError) as e:
        print(f"ds_lint: error: {e}", file=sys.stderr)
        return 2
    elapsed = time.monotonic() - start

    if args.write_baseline:
        baseline_mod.save(baseline_path, result.all_current)
        print(f"ds_lint: wrote {len(result.all_current)} finding(s) to {baseline_path}")
        return 0

    if args.fmt == "json":
        print(
            json.dumps(
                {
                    "findings": [
                        {
                            "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
                            "severity": f.severity.name, "message": f.message,
                            "fingerprint": f.fingerprint,
                        }
                        for f in result.findings + result.parse_errors
                    ],
                    "baselined": len(result.baselined),
                    "suppressed": result.suppressed,
                    "files": result.files,
                },
                indent=1,
            )
        )
    else:
        for f in result.parse_errors + result.findings:
            print(f.format())
        _summarize(result, elapsed, fail_on, args.quiet)

    return 1 if result.failing(fail_on) else 0


def main() -> None:
    sys.exit(cli_main())


if __name__ == "__main__":
    main()
