"""Traced-function discovery.

The highest-value rules (host syncs, side effects, np.random) only apply
*inside a JAX trace*: ``np.array(x)`` in a host path is fine, the same
call inside a ``@jax.jit`` step function is a silent device→host sync on
every step.  This module computes, per file, the set of function defs
that (conservatively) execute under trace:

1. functions decorated with a trace transform (``@jax.jit``,
   ``@functools.partial(jax.jit, ...)``, ``@jax.checkpoint`` ...);
2. functions *passed to* a trace-transform call anywhere in the module
   (``jax.jit(step)``, ``jax.lax.scan(body, ...)``,
   ``jax.grad(loss_fn)``), including through this repo's mesh wrappers
   (``self._scoped(fn)``, ``scoped_to(mesh, fn)``,
   ``self._get_compiled(name, fn)``);
3. the closure: functions defined inside a traced function, and local
   functions *called* from a traced body (``f()`` or ``self.f()``).

This is a lexical, per-module analysis: cross-module call graphs are out
of scope, which keeps the linter O(parse) and false-positive-poor; the
baseline file absorbs what it can't see.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from deepspeed_tpu.analysis.context import FileContext

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)

# Parameter annotations that declare a host-side contract: a helper whose
# every parameter is one of these never receives tracers, so the call-graph
# closure below doesn't follow edges into it (e.g. flash_attention's
# `_drop_threshold(keep_prob: float)` computing a host constant).
_HOST_ANNOTATIONS = {
    "float", "int", "bool", "str", "bytes", "tuple", "list", "dict",
    "np.ndarray", "numpy.ndarray", "Path",
}

# Last path segment of a jax transform that establishes a trace.
TRANSFORMS = {
    "jit", "pjit", "grad", "value_and_grad", "vmap", "pmap", "checkpoint",
    "remat", "shard_map", "scan", "cond", "while_loop", "fori_loop",
    "switch", "associative_scan", "custom_jvp", "custom_vjp", "named_call",
}
# This repo's jit-adjacent wrappers: functions passed through them end up
# under jax.jit (runtime/engine.py:_get_compiled, parallel/sequence.py).
LOCAL_WRAPPERS = {"_scoped", "scoped_to", "_get_compiled"}


def is_trace_entry(resolved: Optional[str]) -> bool:
    if not resolved:
        return False
    parts = resolved.split(".")
    last = parts[-1]
    if last in LOCAL_WRAPPERS:
        return True
    if last not in TRANSFORMS:
        return False
    # Require a jax-ish head so a user-defined `scan()` helper doesn't
    # mark its callbacks; bare names come from `from jax import jit`.
    return parts[0] in ("jax", "self") or len(parts) == 1


def iter_own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested function
    defs (nested defs are analyzed as their own traced functions)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, FunctionNode):
            stack.extend(ast.iter_child_nodes(node))


def _decorator_targets(ctx: FileContext, dec: ast.AST) -> List[str]:
    """Resolved names a decorator may apply: the decorator itself, its
    call target, and (for functools.partial) the partial'd function."""
    out = []
    if isinstance(dec, ast.Call):
        r = ctx.resolve(dec.func)
        if r:
            out.append(r)
        if r and r.split(".")[-1] == "partial":
            for arg in dec.args[:1]:
                ra = ctx.resolve(arg)
                if ra:
                    out.append(ra)
    else:
        r = ctx.resolve(dec)
        if r:
            out.append(r)
    return out


def collect_functions(tree: ast.Module) -> List[ast.AST]:
    return [n for n in ast.walk(tree) if isinstance(n, FunctionNode)]


def _host_only_signature(fn: ast.AST) -> bool:
    """True when every parameter is annotated with a host-side type —
    such helpers are host computations even when called from traced
    code, so trace-ness doesn't propagate into them."""
    args = fn.args
    params = list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
    if not params or (params and params[0].arg in ("self", "cls")):
        return False
    for p in params:
        if p.annotation is None:
            return False
        ann = ast.unparse(p.annotation)
        if ann not in _HOST_ANNOTATIONS:
            return False
    return True


def find_traced_functions(ctx: FileContext) -> Set[int]:
    """Return ``id()``s of FunctionDef nodes considered traced."""
    defs = collect_functions(ctx.tree)
    by_name: Dict[str, List[ast.AST]] = {}
    for fn in defs:
        by_name.setdefault(fn.name, []).append(fn)

    traced: Set[int] = set()

    # 1. trace-transform decorators
    for fn in defs:
        for dec in fn.decorator_list:
            if any(is_trace_entry(t) for t in _decorator_targets(ctx, dec)):
                traced.add(id(fn))
                break

    # 2. functions referenced in the args of a trace-transform call
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and is_trace_entry(ctx.resolve(node.func))):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for ref in ast.walk(arg):
                name = None
                if isinstance(ref, ast.Name):
                    name = ref.id
                elif isinstance(ref, ast.Attribute):
                    name = ref.attr
                if name:
                    for fnode in by_name.get(name, ()):
                        traced.add(id(fnode))

    # 3. closure: nested defs + locally-called functions, to fixpoint
    changed = True
    while changed:
        changed = False
        for fn in defs:
            if id(fn) not in traced:
                continue
            for sub in ast.walk(fn):
                if isinstance(sub, FunctionNode) and sub is not fn and id(sub) not in traced:
                    traced.add(id(sub))
                    changed = True
                elif isinstance(sub, ast.Call):
                    cname = None
                    if isinstance(sub.func, ast.Name):
                        cname = sub.func.id
                    elif (
                        isinstance(sub.func, ast.Attribute)
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == "self"
                    ):
                        cname = sub.func.attr
                    for fnode in by_name.get(cname, ()):
                        if id(fnode) not in traced and not _host_only_signature(fnode):
                            traced.add(id(fnode))
                            changed = True
    return traced


def traced_defs(ctx: FileContext) -> List[ast.AST]:
    """The traced FunctionDef nodes themselves, in source order."""
    ids = ctx.traced_functions()
    return [fn for fn in collect_functions(ctx.tree) if id(fn) in ids]
