"""``python -m deepspeed_tpu.analysis`` — subcommand router:

* ``sanitize [...]`` / ``sanitize -- <cmd>`` — ds_san runtime sanitizer;
* ``lint [...]`` or bare paths — ds_lint (same CLI as bin/ds_lint).
"""
from deepspeed_tpu.analysis.cli import main

main()
