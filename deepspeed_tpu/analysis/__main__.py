"""``python -m deepspeed_tpu.analysis`` — same CLI as bin/ds_lint."""
from deepspeed_tpu.analysis.cli import main

main()
