"""``deepspeed_tpu.analysis`` — ds_lint, the repo's JAX trace-safety and
sharding static-analysis subsystem.

Usage:

* CLI: ``bin/ds_lint deepspeed_tpu/`` or ``python -m deepspeed_tpu.analysis``;
* library: :func:`lint_paths` returns a structured :class:`LintResult`.

Design: pure-``ast`` (never imports the linted code, no JAX needed at
analysis time), a severity-tiered rule registry, inline suppressions
(``# ds-lint: disable=<rule>``), and a checked-in baseline for
grandfathered findings.  See docs/ds_lint.md for the rule catalog.
"""
from deepspeed_tpu.analysis.core import Finding, Rule, Severity, all_rules, get_rule, register
from deepspeed_tpu.analysis.runner import LintResult, collect_py_files, lint_paths

__all__ = [
    "Finding",
    "LintResult",
    "Rule",
    "Severity",
    "all_rules",
    "collect_py_files",
    "get_rule",
    "lint_paths",
    "register",
]
