"""``deepspeed_tpu.analysis`` — the repo's own correctness tooling:
ds_lint (AST trace-safety/sharding static analysis) and ds_san (the
trace-time & runtime sanitizer, :mod:`deepspeed_tpu.analysis.sanitizer`).

Usage:

* CLI: ``bin/ds_lint deepspeed_tpu/`` or ``python -m deepspeed_tpu.analysis``
  (``sanitize`` subcommand dispatches to ds_san);
* library: :func:`lint_paths` returns a structured :class:`LintResult`.

Design: the lint path is pure-``ast`` (never imports the linted code, no
JAX needed at analysis time), a severity-tiered rule registry, inline
suppressions (``# ds-lint: disable=<rule>``), and a checked-in baseline
for grandfathered findings.  ds_san reuses the same Finding/severity/
baseline/suppression machinery at runtime (docs/ds_san.md); importing it
(and therefore JAX) stays lazy so the linter keeps its sub-second start.
See docs/ds_lint.md for the rule catalog.
"""
from deepspeed_tpu.analysis.core import Finding, Rule, Severity, all_rules, get_rule, register
from deepspeed_tpu.analysis.runner import LintResult, collect_py_files, lint_paths

__all__ = [
    "Finding",
    "LintResult",
    "Rule",
    "Severity",
    "all_rules",
    "collect_py_files",
    "get_rule",
    "lint_paths",
    "register",
]
