"""Per-file and per-project analysis context.

``FileContext`` owns one parsed module: source text, AST, the import
alias table (so rules resolve ``np.array`` vs ``import numpy as xp``),
and the inline-suppression table parsed from ``# ds-lint:`` comments.

Suppression syntax (checked by tests/test_ds_lint.py):

* ``x = float(y)  # ds-lint: disable=host-sync-in-jit`` — same line;
* a standalone ``# ds-lint: disable=<rule>[,<rule>...]`` comment line
  suppresses the next non-comment line;
* ``# ds-lint: disable-file=<rule>[,<rule>...]`` anywhere suppresses the
  rule(s) for the whole file;
* ``all`` is accepted in place of a rule list.

The ``ds-race`` and ``ds-shard`` tools share the table:
``# ds-race: disable=...`` / ``# ds-shard: disable=...`` are parsed
identically (rule ids are disjoint across tools, so one table serves
all of them without cross-talk).
"""
from __future__ import annotations

import ast
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Dict, List, Optional, Set, Tuple

_SUPPRESS_RE = re.compile(r"#\s*ds-(?:lint|race|shard):\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\- ]+)")


def _parse_rule_list(raw: str) -> Set[str]:
    return {part.strip() for part in raw.split(",") if part.strip()}


@dataclass
class Suppressions:
    by_line: Dict[int, Set[str]] = field(default_factory=dict)
    file_wide: Set[str] = field(default_factory=set)

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        if "all" in self.file_wide or rule_id in self.file_wide:
            return True
        rules = self.by_line.get(line, ())
        return "all" in rules or rule_id in rules


def parse_suppressions(source: str) -> Suppressions:
    """Tokenize the file and collect ``# ds-lint:`` pragmas.  Falls back
    to a line-regex scan if tokenization fails (e.g. decode edge cases)
    so a weird file can't crash the linter."""
    sup = Suppressions()
    comments: List[Tuple[int, int, str]] = []  # (line, col, text)
    try:
        for tok in tokenize.generate_tokens(StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, text in enumerate(source.splitlines(), start=1):
            if "#" in text:
                col = text.index("#")
                comments.append((i, col, text[col:]))
    lines = source.splitlines()

    def _next_code_line(after: int) -> int:
        # The first following line that isn't blank or comment-only.
        for i in range(after, len(lines)):
            stripped = lines[i].strip()
            if stripped and not stripped.startswith("#"):
                return i + 1
        return after + 1

    for line, col, text in comments:
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        kind, rules = m.group(1), _parse_rule_list(m.group(2))
        if kind == "disable-file":
            sup.file_wide |= rules
        elif col == 0 or not lines[line - 1][:col].strip():
            # Standalone comment: applies to the next non-comment line.
            sup.by_line.setdefault(_next_code_line(line), set()).update(rules)
        else:
            sup.by_line.setdefault(line, set()).update(rules)
    return sup


@dataclass
class FileContext:
    path: str  # as given to the runner (display path)
    source: str
    tree: ast.Module
    suppressions: Suppressions
    # import alias -> canonical dotted module ("np" -> "numpy",
    # "jnp" -> "jax.numpy", "jax" -> "jax"); from-imports map the bound
    # name to "module.name" ("device_get" -> "jax.device_get").
    aliases: Dict[str, str] = field(default_factory=dict)
    _traced: Optional[set] = None  # lazily-computed traced FunctionDef ids

    @classmethod
    def parse(cls, path: str, source: str) -> "FileContext":
        tree = ast.parse(source, filename=path)
        ctx = cls(path=path, source=source, tree=tree, suppressions=parse_suppressions(source))
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        ctx.aliases[a.asname] = a.name
                    else:
                        # `import jax.numpy` binds the ROOT name `jax`,
                        # not the dotted module — map it to itself so a
                        # sibling `import jax` isn't shadowed.
                        root = a.name.split(".")[0]
                        ctx.aliases[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    ctx.aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return ctx

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted canonical name for a Name/Attribute chain, resolving
        the leading segment through the import table.  ``np.random.rand``
        -> ``numpy.random.rand``; unknown heads resolve to themselves so
        local helpers still produce a usable name."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = self.aliases.get(node.id, node.id)
        parts.append(head)
        return ".".join(reversed(parts))

    def traced_functions(self) -> set:
        """ids of FunctionDef nodes that execute under a JAX trace (see
        deepspeed_tpu.analysis.traced)."""
        if self._traced is None:
            from deepspeed_tpu.analysis.traced import find_traced_functions

            self._traced = find_traced_functions(self)
        return self._traced


@dataclass
class ProjectContext:
    root: str
    files: List[FileContext]

    def find(self, suffix: str) -> Optional[FileContext]:
        """First file whose normalized path ends with ``suffix``."""
        suffix = suffix.replace("\\", "/")
        for fc in self.files:
            if fc.path.replace("\\", "/").endswith(suffix):
                return fc
        return None
