"""Sharding-drift checker.

The engine declares a partition spec for every state leaf
(``engine._state_shardings``) and pins compiled outputs to it with
``out_shardings`` — but host-side mutation (checkpoint restore through a
different path, a user poking ``engine.state``, an elastic resize bug)
can leave a leaf placed differently than declared.  GSPMD will happily
keep running: it inserts resharding collectives at the next step, the
program is *correct* and silently slower — exactly the class of
regression arXiv:2004.13336 shows erases a sharded-update win.  The
checker compares actual ``Array.sharding`` against the declared spec
(``Sharding.is_equivalent_to``, which normalizes replicated-axis
spellings) every N steps and after checkpoint load.
"""
from __future__ import annotations

from typing import Any

from deepspeed_tpu.analysis.sanitizer.core import caller_site


class ShardingDriftChecker:
    def __init__(self, san, enabled: bool = True, interval: int = 16):
        self.san = san
        self.enabled = enabled
        self.interval = max(1, int(interval))
        self._last_checked_step = -1

    def due(self, step: int) -> bool:
        """True when at least ``interval`` steps passed since the last
        sweep (the engine calls this once per optimizer-step boundary).
        Interval-crossing, not modulo: ``train_batches`` advances the
        step count by whole runs and overflow skips shift it, so exact
        multiples can be arbitrarily rare."""
        if not self.enabled:
            return False
        return step - self._last_checked_step >= self.interval

    def check(self, tree: Any, declared: Any, label: str, step: int = -1) -> int:
        """Compare every array leaf's actual sharding against the
        declared sharding tree (same structure).  Returns the number of
        drifted leaves."""
        if not self.enabled:
            return 0
        import jax

        self._last_checked_step = step
        site = caller_site(skip_engine=True)
        drifted = 0
        actual_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        declared_leaves = jax.tree_util.tree_leaves(declared)
        if len(actual_leaves) != len(declared_leaves):
            self.san.record(
                "san-sharding-drift",
                f"'{label}': state has {len(actual_leaves)} leaves but the declared "
                f"sharding tree has {len(declared_leaves)} — structures diverged",
                site=site,
            )
            return 1
        for (path, leaf), want in zip(actual_leaves, declared_leaves):
            have = getattr(leaf, "sharding", None)
            if have is None or not hasattr(want, "is_equivalent_to"):
                continue
            try:
                same = want.is_equivalent_to(have, getattr(leaf, "ndim", 0))
            except (ValueError, TypeError):
                same = want == have
            if not same:
                drifted += 1
                self.san.record(
                    "san-sharding-drift",
                    f"'{label}' leaf {jax.tree_util.keystr(path)}: declared "
                    f"{_spec_str(want)} but placed {_spec_str(have)}"
                    + (f" at step {step}" if step >= 0 else ""),
                    site=site,
                )
        return drifted

    def check_state(self, engine, label: str = "engine.state", step: int = -1) -> int:
        """Engine state vs its declared sharding tree (the per-N-steps
        and post-checkpoint-load hook)."""
        if not self.enabled:
            return 0
        if step < 0:
            step = getattr(engine, "_host_global_step", -1)
        return self.check(engine.state, engine._state_shardings, label, step=step)


def _spec_str(sh: Any) -> str:
    spec = getattr(sh, "spec", None)
    return f"{spec}" if spec is not None else f"{sh}"
