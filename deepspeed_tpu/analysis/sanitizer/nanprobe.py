"""NaN/Inf provenance probe.

When the DivergenceGuard trips (N consecutive overflow/NaN-skipped
steps), the normal diagnosis is a dead run and a shrug: the compiled
step returns one scalar loss and XLA tells you nothing about *where*
the first non-finite value was born.  The probe re-runs the step's
forward loss under ``jax.experimental.checkify`` with ``float_checks``
— every op instrumented — and converts the first failing check into a
``san-nonfinite`` finding naming the guilty primitive.

The re-run is deliberately forward-only: it reuses the engine's current
params, the last fed batch's micro-batch 0, and that micro-batch's rng
fold (on an overflow-skipped step the params are unchanged, so micro 0
reproduces exactly; a NaN born only in a later micro-batch of a gas>1
step needs gas=1 to reproduce, and after an unscaled-bf16 NaN update
the probe names the first producer under the poisoned params — still
what you need to find the unstable op).  Cost is one extra trace +
forward per guard trip, never on the hot path.
"""
from __future__ import annotations

from typing import Any, Optional

from deepspeed_tpu.analysis.sanitizer.core import caller_site


class NanProbe:
    def __init__(self, san, enabled: bool = True):
        self.san = san
        self.enabled = enabled
        self.probes_run = 0

    def probe_fn(self, fn, *args, label: str = "fn") -> Optional[str]:
        """Run ``fn(*args)`` under checkify float checks; returns the
        error message (and records a finding) or None if clean."""
        if not self.enabled:
            return None
        import jax
        from jax.experimental import checkify

        self.probes_run += 1
        site = caller_site(skip_engine=True)
        try:
            checked = checkify.checkify(fn, errors=checkify.float_checks)
            # diagnostic one-shot re-run: layout is whatever the inputs
            # carry; GSPMD propagation is fine off the hot path
            err, _ = jax.jit(checked)(*args)  # ds-lint: disable=bare-jit
            msg = err.get()
        except Exception as e:  # a model checkify can't trace: report, don't crash
            from deepspeed_tpu.utils.logging import logger

            logger.warning(f"ds_san: nonfinite probe for '{label}' failed to run: {e!r}")
            return None
        if not msg:
            return None
        first = str(msg).splitlines()[0]
        self.san.record(
            "san-nonfinite",
            f"divergence probe '{label}': first non-finite op — {first}",
            site=site,
        )
        return first

    def probe_engine_step(self, engine, last_batch: Any) -> Optional[str]:
        """Re-run the engine's forward loss on the last fed micro-batch
        under checkify.  ``last_batch`` is the engine's ``("stacked",
        tree)`` / ``("micro", tree)`` record — stacked trees carry a
        leading gas axis that must be peeled to micro-batch 0; micro
        trees (the forward()/step() API) are already one micro-batch."""
        if not self.enabled or last_batch is None:
            return None
        import jax

        kind, tree = last_batch

        def first_micro(x):
            return x[0] if getattr(x, "ndim", 0) >= 1 else x

        mb = jax.tree.map(first_micro, tree) if kind == "stacked" else tree
        # rebuild the rng of the failing forward: micro_step has already
        # advanced past the batch (by gas for the stacked paths, by 1 for
        # the micro API) — folding with the CURRENT value would probe a
        # different dropout draw than the one that diverged.  Micro 0 of
        # the batch is what `mb` holds, so that's the fold target; a NaN
        # born only in a later micro-batch needs gas=1 to reproduce.
        back = engine.gradient_accumulation_steps if kind == "stacked" else 1
        micro0 = jax.numpy.maximum(engine.state["micro_step"] - back, 0)
        rng = jax.random.fold_in(engine.state["rng"], micro0)
        ls_state = engine.state["loss_scale"]

        def fwd(params, batch):
            _, loss = engine._compute_loss(params, batch, rng, ls_state)
            return loss

        return self.probe_fn(fwd, engine.state["params"], mb, label="engine.forward")
