"""ds_san smoke loop: a tiny end-to-end training run with every checker
armed, used by ``python -m deepspeed_tpu.analysis sanitize`` and the CI
``sanitize`` job.

Two modes:

* **clean** (``--clean``): train a few steps through the prefetcher,
  checkpoint save+load, report.  Gate: zero findings — proves the
  engine's own hot path is sanitizer-clean (the regression CI cares
  about exactly this).
* **seeded** (default): additionally commit one deliberate violation
  per checker — a recompile storm from shape-drifting calls, an implicit
  host→device transfer, a use-after-donation, a sharding-drift
  injection, a NaN batch — and then *verify* each was caught and that
  the storm + transfer findings are attributed to this file's guilty
  lines.  Gate: all seeded findings present, correctly attributed, and
  nothing unexpected.  This is the sanitizer's own self-test: a checker
  that silently stops firing fails the run.
"""
from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, List, Tuple

HIDDEN = 16
_EXPECTED_SEEDED = {
    "san-recompile",
    "san-recompile-storm",
    "san-transfer",
    "san-donation",
    "san-sharding-drift",
    "san-nonfinite",
}


def _model():
    """Self-contained 2-layer MLP (no test-package imports): callable
    ``(params, batch, rng) -> mse loss`` plus an init."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(0)
    params = {
        f"layer_{i}": {
            "w": rng.standard_normal((HIDDEN, HIDDEN)).astype(np.float32) / np.sqrt(HIDDEN),
            "b": np.zeros((HIDDEN,), np.float32),
        }
        for i in range(2)
    }

    def loss_fn(p, batch, rng=None):
        h = batch["x"].astype(jnp.float32)
        h = jax.nn.relu(h @ p["layer_0"]["w"] + p["layer_0"]["b"])
        h = h @ p["layer_1"]["w"] + p["layer_1"]["b"]
        return jnp.mean((h - batch["y"].astype(jnp.float32)) ** 2)

    return loss_fn, params


def _batches(n: int, batch_size: int, seed: int = 0, poison: bool = False):
    import numpy as np

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.standard_normal((batch_size, HIDDEN)).astype(np.float32)
        if poison:
            x[0, 0] = np.nan
        out.append({"x": x, "y": (x * 0.1).astype(np.float32)})
    return out


def run_smoke(
    san,
    seed_violations: bool = True,
    steps: int = 4,
    ckpt_dir: str | None = None,
) -> Dict[str, Any]:
    """Run the loop under the (already installed) sanitizer ``san``.
    Returns ``{"verified": [...], "missing": [...], "misattributed":
    [...], "unexpected": [Finding...]}`` — empty lists = success."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.analysis.sanitizer.core import TransferViolation

    loss_fn, params = _model()
    dp = jax.device_count()
    config = {
        "train_batch_size": dp,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10_000,
        # threshold 2 so two poisoned steps trip the guard (and the
        # ds_san NaN probe); check_loss is the only NaN signal in fp32
        "resilience": {"divergence": {"threshold": 2, "action": "warn", "check_loss": True}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=loss_fn, model_parameters=params, config=config)
    assert engine._sanitizer is san, "smoke engine did not pick up the installed sanitizer"

    # -- clean phase: prefetched training + checkpoint roundtrip --------
    for batch in engine.prefetch_loader(iter(_batches(steps, dp))):
        engine.train_batch(batch)
    tmp = ckpt_dir or tempfile.mkdtemp(prefix="ds_san_smoke_")
    engine.save_checkpoint(tmp)
    engine.load_checkpoint(tmp)
    baseline_findings = len(san.findings)

    result: Dict[str, Any] = {"verified": [], "missing": [], "misattributed": [], "unexpected": []}
    if not seed_violations:
        result["unexpected"] = list(san.findings)
        return result

    guilty_lines: Dict[str, Tuple[str, int]] = {}

    # -- (1) recompile storm: one call site, budget+2 distinct shapes ---
    # deliberately bare toy jit: the fixture's point is the cache misses
    f = san.recompile.wrap(jax.jit(lambda x: x * x), site="smoke.varying_shape")  # ds-lint: disable=bare-jit
    for i in range(san.config.compile_budget + 2):
        _ = f(jnp.zeros((i + 1,), jnp.float32))  # ds_san-smoke: seeded recompile storm
    me = os.path.abspath(__file__)
    with open(me, "r") as fh:
        for lineno, line in enumerate(fh, start=1):
            if "seeded recompile storm" in line and "lineno" not in line:
                guilty_lines["san-recompile-storm"] = (me, lineno)
            if "seeded implicit transfer" in line and "lineno" not in line:
                guilty_lines["san-transfer"] = (me, lineno)

    # -- (2) implicit transfer: fresh host bytes mixed into device math -
    dev = jnp.zeros((4,), jnp.float32) + 0  # committed device array
    try:
        with san.transfer.guard("smoke.transfer"):
            _ = dev + np.ones((4,), np.float32)  # ds_san-smoke: seeded implicit transfer
        result["missing"].append("san-transfer (guard did not trip)")
    except TransferViolation:
        pass

    # -- (3) use-after-donation: stale reference to a donated state leaf
    stale = engine.state["params"]["layer_0"]["w"]
    engine.train_batch(_batches(1, dp, seed=7)[0])  # donates the old state
    try:
        with san.donation.watch("smoke.stale_param"):
            np.asarray(stale)
        result["missing"].append("san-donation (stale use did not raise)")
    except RuntimeError:
        pass

    # -- (4) sharding drift: re-place a leaf off its declared spec ------
    from jax.sharding import NamedSharding, PartitionSpec as P

    good = engine.state["params"]["layer_0"]["b"]
    wide_axes = [a for a in engine.mesh.axis_names if engine.mesh.shape[a] > 1]
    if wide_axes:
        engine.state["params"]["layer_0"]["b"] = jax.device_put(
            np.zeros((HIDDEN,), np.float32),
            NamedSharding(engine.mesh, P(wide_axes[0])),
        )
        san.drift.check_state(engine, label="smoke.drift", step=-2)
        engine.state["params"]["layer_0"]["b"] = good  # undo the injection
    else:
        # single-device meshes cannot express drift; synthesize the
        # declared/actual mismatch directly so the checker still runs
        class _NeverEq:
            spec = "P('data')"

            def is_equivalent_to(self, other, ndim):
                return False

        san.drift.check(
            {"b": engine.state["params"]["layer_0"]["b"]}, {"b": _NeverEq()},
            label="smoke.drift", step=-2,
        )
        engine.state["params"]["layer_0"]["b"] = good

    # -- (5) non-finite provenance: two poisoned steps trip the guard ---
    for batch in _batches(2, dp, seed=11, poison=True):
        engine.train_batch(batch)

    # -- verify: every seeded rule fired; storm+transfer point here -----
    seen = {f.rule for f in san.findings}
    expected = set(_EXPECTED_SEEDED)
    if san.config.compile_budget < 2:
        # every post-first compile escalates straight to storm; there is
        # no budget headroom for a tier-B san-recompile to exist
        expected.discard("san-recompile")
    for rule in sorted(expected):
        if rule in seen:
            result["verified"].append(rule)
        else:
            result["missing"].append(rule)
    for rule in ("san-recompile-storm", "san-transfer"):
        want = guilty_lines.get(rule)
        hits = [f for f in san.findings if f.rule == rule]
        if want and hits and not any(
            os.path.abspath(f.path) == want[0] and f.line == want[1] for f in hits
        ):
            result["misattributed"].append(
                f"{rule}: expected {os.path.basename(want[0])}:{want[1]}, got "
                + ", ".join(f"{os.path.basename(f.path)}:{f.line}" for f in hits)
            )
    result["unexpected"] = [
        f for f in san.findings[:baseline_findings]
    ]  # findings from the CLEAN phase are never expected
    return result
