"""``deepspeed_tpu.analysis.sanitizer`` — ds_san, the trace-time &
runtime sanitizer.

ds_lint (the sibling AST linter) proves the *source* looks trace-safe;
ds_san proves the *running program* stays on the hot path.  It is an
opt-in instrumentation layer (config block ``sanitizer``, env
``DS_SAN=1``, CLI ``python -m deepspeed_tpu.analysis sanitize``) that
wraps the engine step, jit entry points, the overlap prefetcher and the
resilience checkpoint paths with five checkers:

* **recompile** — fingerprints abstract argument signatures per compiled
  function; on a cache miss explains *which* arg's shape/dtype/static
  value changed, and fails when compiles exceed a budget
  (``san-recompile`` / ``san-recompile-storm``);
* **transfer** — wires ``jax.transfer_guard`` around the hot region and
  attributes any implicit device↔host transfer to a Python stack frame
  (``san-transfer``);
* **donation** — registers donated buffers per call site and attributes
  use-after-donation errors to the donating call (``san-donation``);
* **sharding** — compares actual ``Array.sharding`` of engine params /
  optimizer state against the declared partition specs every N steps and
  after checkpoint load (``san-sharding-drift``);
* **nonfinite** — on a DivergenceGuard trip, re-runs the step's forward
  under ``checkify`` to name the first op producing non-finite values
  (``san-nonfinite``).

Findings flow through the same :class:`~deepspeed_tpu.analysis.core.
Finding` / severity / baseline machinery as ds_lint: one report format,
one suppression syntax (``# ds-lint: disable=<rule>`` on the attributed
line), one CI gate.  See docs/ds_san.md.
"""
from deepspeed_tpu.analysis.sanitizer.core import (  # noqa: F401
    RULES,
    Sanitizer,
    TransferViolation,
    caller_site,
    get_active,
    install,
    maybe_from_config,
    uninstall,
)

__all__ = [
    "RULES",
    "Sanitizer",
    "TransferViolation",
    "caller_site",
    "get_active",
    "install",
    "maybe_from_config",
    "uninstall",
]
