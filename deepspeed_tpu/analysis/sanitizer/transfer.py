"""Transfer guard: attribute implicit device↔host transfers.

Wires ``jax.transfer_guard("disallow")`` around guarded regions (the
engine step, the prefetcher's place stage, the CLI smoke loop).
Explicit transfers — ``jax.device_put`` / ``jax.device_get`` — always
pass; an *implicit* one (``float(loss)``, ``np.asarray(device_arr)``,
mixing a host constant into device math, which re-stages bytes through
the host every step) raises inside XLA.  The checker converts that into
a ``san-transfer`` finding anchored at the deepest user frame of the
traceback — the line that wrote the implicit transfer — then raises
:class:`TransferViolation` so the caller decides whether to continue
(fixtures, smoke loop) or die loudly (default sanitize runs).

On CPU backends device→host reads are zero-copy and do not trip the
guard; host→device staging (the common per-step cost on TPU) trips on
every backend, which is what the CI fixtures exercise.
"""
from __future__ import annotations

import contextlib
from typing import Optional

from deepspeed_tpu.analysis.sanitizer.core import TransferViolation, caller_site


def _is_guard_error(e: BaseException) -> bool:
    s = str(e)
    return "Disallowed" in s and "transfer" in s


class TransferChecker:
    def __init__(self, san, enabled: bool = True, level: str = "disallow"):
        self.san = san
        self.enabled = enabled
        self.level = level
        self._depth = 0  # nested guards: inner io_region must not re-arm

    @contextlib.contextmanager
    def guard(self, region: str = "region"):
        """Guarded hot region: implicit transfers inside become
        ``san-transfer`` findings + :class:`TransferViolation`."""
        if not self.enabled:
            yield
            return
        import jax

        self._depth += 1
        try:
            with jax.transfer_guard(self.level):
                yield
        except Exception as e:  # XlaRuntimeError has no stable import path
            if isinstance(e, TransferViolation) or not _is_guard_error(e):
                # an inner nested guard already recorded + wrapped this
                # violation; re-recording would double-count it
                raise
            site = caller_site(tb=e.__traceback__)
            detail = str(e).splitlines()[0]
            finding = self.san.record(
                "san-transfer",
                f"implicit transfer in guarded region '{region}': {detail}",
                site=site,
            )
            raise TransferViolation(
                f"ds_san: implicit transfer at {site[0]}:{site[1]} "
                f"(region '{region}'): {detail}",
                finding=finding,
            ) from e
        finally:
            self._depth -= 1

    @contextlib.contextmanager
    def io_region(self):
        """Checkpoint/host-I/O region: transfers are the *job* here, so
        the guard is relaxed to 'allow' (still nested-safe inside an
        armed ``guard``)."""
        if not self.enabled or self._depth == 0:
            yield
            return
        import jax

        with jax.transfer_guard("allow"):
            yield

    def wrap_callable(self, fn, region: str):
        """``fn`` executed under :meth:`guard` — used to instrument the
        prefetcher's place stage without importing sanitizer types
        there."""
        if not self.enabled:
            return fn

        def wrapped(*a, **kw):
            with self.guard(region):
                return fn(*a, **kw)

        return wrapped
