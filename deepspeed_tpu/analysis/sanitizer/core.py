"""ds_san core: the :class:`Sanitizer` (checker registry + finding
sink), call-site attribution, and the install/active machinery.

Activation mirrors ``resilience.faults``: in production no sanitizer is
installed and every engine hook is a near-free ``None`` check.  Under
``DS_SAN=1`` (or a ``sanitizer`` config block with ``enabled: true``)
one module-level :class:`Sanitizer` is installed and the hooks light up.

Findings reuse :class:`deepspeed_tpu.analysis.core.Finding` so ds_lint
and ds_san share one report format, one fingerprint/baseline mechanism
(``.ds_san_baseline.json``) and one suppression syntax — a runtime
finding attributed to ``file:line`` is suppressed by the same
``# ds-lint: disable=<rule>`` pragma an AST finding would be.
"""
from __future__ import annotations

import atexit
import json
import os
import sys
import traceback
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.analysis.core import Finding, Severity

# rule id -> (tier, description).  Tier A fails the gate on new findings.
RULES: Dict[str, Tuple[Severity, str]] = {
    "san-recompile": (
        Severity.B,
        "a compiled function re-traced: the abstract signature of its arguments changed",
    ),
    "san-recompile-storm": (
        Severity.A,
        "compiles for one call site exceeded the budget (silent recompilation storm)",
    ),
    "san-transfer": (
        Severity.A,
        "implicit device<->host transfer inside a guarded hot region",
    ),
    "san-donation": (
        Severity.A,
        "use of a buffer after it was donated to a compiled call",
    ),
    "san-sharding-drift": (
        Severity.A,
        "actual Array.sharding drifted from the declared partition spec",
    ),
    "san-nonfinite": (
        Severity.A,
        "non-finite values produced by the step (first guilty op named by checkify)",
    ),
}


class TransferViolation(RuntimeError):
    """Raised (after the finding is recorded) when the transfer guard
    trips — carries the attributed site so callers can decide to swallow
    (smoke/test fixtures) or propagate (real training loops)."""

    def __init__(self, message: str, finding: Optional[Finding] = None):
        super().__init__(message)
        self.finding = finding


_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))  # .../analysis
_SAN_DIR = os.path.join(_PKG_DIR, "sanitizer")
_DSTPU_DIR = os.path.dirname(_PKG_DIR)  # .../deepspeed_tpu


def _is_internal(path: str) -> bool:
    """Frames the attribution walk skips: the sanitizer itself, jax/
    jaxlib internals, stdlib importlib/contextlib plumbing.  smoke.py is
    exempt — it plays the user code whose guilty lines the self-test
    must attribute."""
    p = path.replace(os.sep, "/")
    if p.endswith("/analysis/sanitizer/smoke.py"):
        return False
    if "/analysis/sanitizer/" in p:
        return True
    for marker in ("/jax/", "/jaxlib/", "/jax_graft/", "/contextlib.py", "/importlib/"):
        if marker in p:
            return True
    return False


_ENGINE_FRAME_SUFFIXES = ("runtime/engine.py", "runtime/checkpointing.py")


def caller_site(tb=None, skip_engine: bool = False) -> Tuple[str, int, str]:
    """``(path, line, function)`` of the frame a finding should anchor
    to.  From a traceback (``tb``) the walk takes the DEEPEST non-internal
    frame — the line that wrote the violating call.  From the live stack
    it takes the NEAREST non-internal caller.  ``skip_engine`` also steps
    over ``runtime/engine.py`` / ``runtime/checkpointing.py`` frames: a
    storm caused by a user loop feeding drifting shapes belongs to the
    loop, not to ``engine.train_batch``, and a drift found on restore
    belongs to the ``load_checkpoint`` call site — anchoring at a fixed
    library line would make every occurrence share one fingerprint."""
    if tb is not None:
        frames = traceback.extract_tb(tb)
    else:
        frames = traceback.extract_stack()[:-1]  # drop caller_site itself
        frames = list(reversed(frames))  # nearest caller first
    candidates = [f for f in frames if not _is_internal(f.filename)]
    if skip_engine:
        candidates = [
            f for f in candidates
            if not f.filename.replace(os.sep, "/").endswith(_ENGINE_FRAME_SUFFIXES)
        ] or candidates
    if tb is not None:
        pick = candidates[-1] if candidates else (frames[-1] if frames else None)
    else:
        pick = candidates[0] if candidates else (frames[0] if frames else None)
    if pick is None:
        return ("<unknown>", 0, "<unknown>")
    return (pick.filename, pick.lineno or 0, pick.name)


class Sanitizer:
    """Checker registry + finding sink for one sanitized run.

    ``config`` is a ``deepspeed_tpu.config.config.SanitizerConfig`` (or
    anything duck-typed like one); ``None`` means all checkers at the
    default budgets."""

    def __init__(self, config: Any = None):
        from deepspeed_tpu.analysis.sanitizer.donation import DonationTracker
        from deepspeed_tpu.analysis.sanitizer.drift import ShardingDriftChecker
        from deepspeed_tpu.analysis.sanitizer.nanprobe import NanProbe
        from deepspeed_tpu.analysis.sanitizer.recompile import RecompileDetector
        from deepspeed_tpu.analysis.sanitizer.transfer import TransferChecker

        if config is None:
            from deepspeed_tpu.config.config import SanitizerConfig

            config = SanitizerConfig(enabled=True)
        self.config = config
        self.findings: List[Finding] = []
        self._suppressed = 0
        self._sources: Dict[str, str] = {}  # path -> source (for fingerprints)
        self._suppressions: Dict[str, Any] = {}  # path -> Suppressions
        checkers = set(config.checkers)
        self.recompile = RecompileDetector(
            self, enabled="recompile" in checkers, budget=config.compile_budget
        )
        self.transfer = TransferChecker(self, enabled="transfer" in checkers)
        self.donation = DonationTracker(self, enabled="donation" in checkers)
        self.drift = ShardingDriftChecker(
            self, enabled="sharding" in checkers, interval=config.drift_interval
        )
        self.nanprobe = NanProbe(self, enabled="nonfinite" in checkers)

    # -- finding sink ---------------------------------------------------
    def _suppressed_at(self, rule: str, path: str, line: int) -> bool:
        """Same pragma syntax as ds_lint, applied to the attributed line."""
        if path not in self._suppressions:
            src = ""
            try:
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
            except OSError:
                pass
            self._sources[path] = src
            from deepspeed_tpu.analysis.context import parse_suppressions

            self._suppressions[path] = parse_suppressions(src)
        return self._suppressions[path].is_suppressed(rule, line)

    def record(
        self,
        rule: str,
        message: str,
        site: Optional[Tuple[str, int, str]] = None,
        severity: Optional[Severity] = None,
    ) -> Optional[Finding]:
        """Build + store one finding; returns None if an inline pragma on
        the attributed line suppresses it."""
        tier, _ = RULES[rule]
        path, line, func = site if site is not None else caller_site()
        if self._suppressed_at(rule, path, line):
            self._suppressed += 1
            return None
        f = Finding(
            rule=rule,
            path=path,
            line=line,
            col=1,
            message=f"{message} [in {func}]" if func not in ("<unknown>", "") else message,
            severity=severity if severity is not None else tier,
        )
        self.findings.append(f)
        from deepspeed_tpu.utils.logging import logger

        logger.warning(f"ds_san: {f.format()}")
        return f

    # -- reporting ------------------------------------------------------
    def assign_fingerprints(self, root: Optional[str] = None) -> None:
        from deepspeed_tpu.analysis import baseline as baseline_mod

        baseline_mod.assign_fingerprints(
            self.findings, root or os.getcwd(), self._sources
        )

    def to_json(self) -> Dict[str, Any]:
        self.assign_fingerprints()
        return {
            "tool": "ds_san",
            "findings": [
                {
                    "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
                    "severity": f.severity.name, "message": f.message,
                    "fingerprint": f.fingerprint,
                }
                for f in self.findings
            ],
            "suppressed": self._suppressed,
            "compiles": self.recompile.compile_counts(),
        }

    def write_report(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=1)
            f.write("\n")

    def summary(self) -> str:
        tiers = ", ".join(
            f"{sum(1 for f in self.findings if f.severity == t)} tier-{t.name}"
            for t in (Severity.A, Severity.B, Severity.C)
        )
        bits = [f"{len(self.findings)} finding(s) ({tiers})"]
        if self._suppressed:
            bits.append(f"{self._suppressed} suppressed")
        return f"ds_san: {', '.join(bits)}"

    def print_report(self, stream=None) -> None:
        stream = stream or sys.stderr
        for f in self.findings:
            print(f.format(), file=stream)
        print(self.summary(), file=stream)


# -- module-level activation (faults.py idiom) --------------------------
_ACTIVE: Optional[Sanitizer] = None


def get_active() -> Optional[Sanitizer]:
    return _ACTIVE


def install(san: Sanitizer) -> Sanitizer:
    global _ACTIVE
    _ACTIVE = san
    return san


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


_ATEXIT_ARMED = False


def _atexit_report() -> None:
    san = _ACTIVE
    if san is None:
        return
    path = (
        getattr(san.config, "report_path", None)
        or os.environ.get("DS_SAN_REPORT")
        or "ds_san_report.json"
    )
    try:
        san.write_report(path)
    except OSError as e:
        print(f"ds_san: could not write report to {path}: {e}", file=sys.stderr)
    san.print_report()


def maybe_from_config(config: Any = None) -> Optional[Sanitizer]:
    """The engine's activation point: return the already-installed
    sanitizer (CLI/smoke installed one), or build+install one when the
    config block or ``DS_SAN=1`` asks for it, else None.  Env-driven
    runs get an atexit report writer (``DS_SAN_REPORT``, default
    ``ds_san_report.json``) so ``sanitize -- <cmd>`` can collect
    findings from the child process."""
    global _ATEXIT_ARMED
    cfg_on = config is not None and getattr(config, "enabled", False)
    if config is not None and getattr(config, "_explicit", False) and not cfg_on:
        # a config block that SAYS `enabled: false` opts this engine out
        # even of a process-wide (env/CLI-installed) sanitizer
        return None
    if _ACTIVE is not None:
        return _ACTIVE
    env_on = os.environ.get("DS_SAN", "") == "1"
    if not (env_on or cfg_on):
        return None
    if not cfg_on:
        # env-armed: a knobs-only config block still supplies the tuning
        from deepspeed_tpu.config.config import SanitizerConfig

        config = SanitizerConfig.from_env(base=config)
    san = install(Sanitizer(config))
    if not _ATEXIT_ARMED:
        atexit.register(_atexit_report)
        _ATEXIT_ARMED = True
    return san
