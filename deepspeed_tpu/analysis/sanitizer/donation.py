"""Donation checker: attribute use-after-donation.

``donate_argnums`` frees an input buffer the moment the compiled call
consumes it; a stale Python reference then raises JAX's bare
``Array has been deleted`` with no hint of *who* donated it or *when*.
The tracker registers every donated leaf (id -> donating site, step,
aval) as the engine hands its state to a donated executable — JAX's
deletion is the poison; the registry is what turns the poison into an
attributed diagnosis:

* :meth:`watch` — context manager that converts a deleted-array
  ``RuntimeError`` into a ``san-donation`` finding naming the donating
  call site and step, then re-raises (semantics are unchanged — the
  value really is gone);
* :meth:`check_live` — proactive sweep of a pytree for already-deleted
  leaves (the engine runs it over checkpoint-save inputs, where feeding
  a donated buffer would otherwise surface as a mid-save crash).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from deepspeed_tpu.analysis.sanitizer.core import caller_site


def _is_deleted_error(e: BaseException) -> bool:
    return "deleted" in str(e).lower() and "array" in str(e).lower()


class DonationTracker:
    def __init__(self, san, enabled: bool = True, max_entries: int = 4096):
        self.san = san
        self.enabled = enabled
        self.max_entries = max_entries
        # id(arr) -> (site label, step, "dtype[shape]")
        self._donated: Dict[int, Tuple[str, int, str]] = {}

    def note(self, tree: Any, site: str, step: int = -1) -> None:
        """Register the leaves of ``tree`` as donated at ``site``.  Call
        with the *pre-call* references of a ``donate_argnums`` argument."""
        if not self.enabled:
            return
        import jax

        for leaf in jax.tree.leaves(tree):
            if hasattr(leaf, "is_deleted"):
                if len(self._donated) >= self.max_entries:
                    self._donated.clear()  # bounded: ids recycle anyway
                # jax's deleted-array message spells avals dtype[d0,d1]
                shape = ",".join(str(d) for d in getattr(leaf, "shape", ()))
                aval = f"{getattr(leaf, 'dtype', '?')}[{shape}]"
                self._donated[id(leaf)] = (site, step, aval)

    def lookup(self, arr: Any) -> Optional[Tuple[str, int, str]]:
        return self._donated.get(id(arr))

    def check_live(self, tree: Any, label: str) -> int:
        """Report every already-deleted leaf in ``tree``; returns the
        count (0 = all live)."""
        if not self.enabled:
            return 0
        import jax

        hits = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            if hasattr(leaf, "is_deleted") and leaf.is_deleted():
                hits += 1
                info = self.lookup(leaf)
                donated = (
                    f"donated to '{info[0]}' at step {info[1]} ({info[2]})"
                    if info
                    else "donated by an untracked call"
                )
                self.san.record(
                    "san-donation",
                    f"'{label}' leaf {jax.tree_util.keystr(path)} is deleted — {donated}",
                    site=caller_site(skip_engine=True),
                )
        return hits

    def watch(self, label: str = "use"):
        """Context manager: a deleted-array error inside becomes an
        attributed ``san-donation`` finding, then re-raises."""
        return _Watch(self, label)


class _Watch:
    def __init__(self, tracker: DonationTracker, label: str):
        self.tracker = tracker
        self.label = label

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is None or not self.tracker.enabled:
            return False
        if isinstance(exc, RuntimeError) and _is_deleted_error(exc):
            # best-effort provenance: JAX's message names the aval; match
            # it against the registry to recover the donating site
            msg = str(exc).splitlines()[0]
            compact = msg.replace(" ", "")
            origin = None
            for site, step, aval in self.tracker._donated.values():
                if aval in compact:  # exact dtype[shape] token; latest wins
                    origin = (site, step, aval)
            donated = (
                f"donated to '{origin[0]}' at step {origin[1]} ({origin[2]})"
                if origin
                else "donating call not in the registry"
            )
            self.tracker.san.record(
                "san-donation",
                f"use-after-donation in '{self.label}': {msg} — {donated}",
                site=caller_site(tb=tb),
            )
        return False  # never swallow: the value really is gone
