"""``python -m deepspeed_tpu.analysis sanitize`` — the ds_san CLI.

Three shapes:

* ``sanitize`` — run the built-in smoke training loop with all five
  checkers armed and one *seeded* violation per checker; verifies every
  checker fired and that the storm + implicit-transfer findings are
  attributed to the guilty source lines.  The sanitizer's self-test.
* ``sanitize --clean`` — same loop with no seeded violations; gates on
  any new finding at/above ``--fail-on`` (CI regression mode: the hot
  path must stay sanitizer-clean).
* ``sanitize -- <cmd> [args...]`` — run an arbitrary training command
  with ``DS_SAN=1`` exported; the child's engine hooks record findings
  and write a JSON report at exit, which this parent reads, filters
  against ``.ds_san_baseline.json``, and gates on.

Exit codes match ds_lint: 0 clean, 1 gate failure, 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
from typing import List, Optional

from deepspeed_tpu.analysis import baseline as baseline_mod
from deepspeed_tpu.analysis.core import Severity

SAN_BASELINE_NAME = ".ds_san_baseline.json"


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ds_san",
        description="trace-time & runtime sanitizer for deepspeed_tpu "
        "(recompile storms, implicit transfers, use-after-donation, "
        "sharding drift, NaN provenance)",
    )
    p.add_argument("--clean", action="store_true", help="smoke loop without seeded violations (CI gate mode)")
    p.add_argument("--steps", type=int, default=4, help="clean training steps in the smoke loop")
    p.add_argument("--budget", type=int, default=None, help="compile budget per call site")
    p.add_argument("--fail-on", default="A", choices=["A", "B", "C"], help="lowest tier that fails the gate")
    p.add_argument("--baseline", metavar="PATH", help=f"baseline file (default: ./{SAN_BASELINE_NAME} if present)")
    p.add_argument("--no-baseline", action="store_true", help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true", help="record current findings as the new baseline")
    p.add_argument("--report", metavar="PATH", help="also write the JSON report here")
    p.add_argument("--format", default="text", choices=["text", "json"], dest="fmt")
    p.add_argument("cmd", nargs=argparse.REMAINDER, help="-- <command> to run under DS_SAN=1")
    return p


def _split_cmd(raw: List[str]) -> Optional[List[str]]:
    if not raw:
        return None
    if raw[0] == "--":
        raw = raw[1:]
    return raw or None


def _baseline_fps(args) -> set:
    if args.no_baseline:
        return set()
    path = args.baseline or (SAN_BASELINE_NAME if os.path.isfile(SAN_BASELINE_NAME) else None)
    if path and os.path.isfile(path):
        return baseline_mod.load(path)
    return set()


def _gate(findings: List[dict], fail_on: Severity, known: set) -> List[dict]:
    """New findings at/above the failing tier."""
    return [
        f for f in findings
        if Severity.parse(f["severity"]) >= fail_on and f.get("fingerprint") not in known
    ]


def _print_findings(findings: List[dict], fmt: str, header: str = "") -> None:
    if fmt == "json":
        print(json.dumps({"findings": findings}, indent=1))
        return
    if header and findings:
        print(header)
    for f in findings:
        print(f"{f['path']}:{f['line']}:{f.get('col', 1)}: [{f['severity']}] {f['rule']}: {f['message']}")


def _run_smoke(args) -> int:
    # A CPU dev box exposes one device; the drift/ZeRO paths need a real
    # mesh.  Must happen before the first jax array op.
    if os.environ.get("JAX_PLATFORMS", "cpu") in ("", "cpu") and "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
        ).strip()

    from deepspeed_tpu.analysis.sanitizer import core as san_core
    from deepspeed_tpu.analysis.sanitizer.smoke import run_smoke
    from deepspeed_tpu.config.config import SanitizerConfig

    cfg_d = {"enabled": True}
    if args.budget is not None:
        cfg_d["compile_budget"] = args.budget
    san = san_core.install(san_core.Sanitizer(SanitizerConfig.from_dict(cfg_d)))
    try:
        result = run_smoke(san, seed_violations=not args.clean, steps=args.steps)
        san.assign_fingerprints()
        report = san.to_json()
        if args.report:
            san.write_report(args.report)
        findings = report["findings"]
        known = _baseline_fps(args)
        if args.write_baseline:
            path = args.baseline or SAN_BASELINE_NAME
            baseline_mod.save(path, san.findings, tool="ds_san")
            print(f"ds_san: wrote {len(san.findings)} finding(s) to {path}")
            return 0
        _print_findings(findings, args.fmt)

        fail_on = Severity.parse(args.fail_on)
        rc = 0
        if args.clean:
            new = _gate(findings, fail_on, known)
            if new:
                print(f"ds_san: FAIL — {len(new)} new finding(s) at tier {args.fail_on}+ in the clean smoke loop")
                rc = 1
            else:
                print(f"ds_san: clean smoke loop — no new findings ({len(findings)} total, {len(known)} baselined)")
        else:
            problems = result["missing"] + result["misattributed"]
            unexpected = _gate(
                [f for f in findings if any(
                    f["rule"] == u.rule and f["line"] == u.line and f["path"] == u.path
                    for u in result["unexpected"]
                )],
                fail_on, known,
            )
            for m in result["missing"]:
                print(f"ds_san: self-test FAIL — checker did not fire: {m}")
            for m in result["misattributed"]:
                print(f"ds_san: self-test FAIL — wrong attribution: {m}")
            if unexpected:
                print(f"ds_san: self-test FAIL — {len(unexpected)} unexpected finding(s) in the clean phase")
            if problems or unexpected:
                rc = 1
            else:
                print(
                    f"ds_san: self-test OK — all {len(result['verified'])} seeded checkers "
                    "fired and attributed correctly "
                    f"({', '.join(result['verified'])})"
                )
        return rc
    finally:
        san_core.uninstall()


def _run_wrapped(args, cmd: List[str]) -> int:
    env = dict(os.environ)
    env["DS_SAN"] = "1"
    report_path = args.report or os.path.join(
        tempfile.mkdtemp(prefix="ds_san_"), "report.json"
    )
    env["DS_SAN_REPORT"] = report_path
    if args.budget is not None:
        env["DS_SAN_BUDGET"] = str(args.budget)
    child = subprocess.call(cmd, env=env)
    if not os.path.isfile(report_path):
        print(
            f"ds_san: wrapped command exited {child} and wrote no report at {report_path} "
            "(did it build a DeepSpeedEngine?)",
            file=sys.stderr,
        )
        return child if child != 0 else 2
    with open(report_path) as f:
        report = json.load(f)
    findings = report.get("findings", [])
    _print_findings(findings, args.fmt)
    known = _baseline_fps(args)
    new = _gate(findings, Severity.parse(args.fail_on), known)
    if args.fmt == "text":
        print(
            f"ds_san: wrapped run exited {child}; {len(findings)} finding(s), "
            f"{len(new)} new at tier {args.fail_on}+ ({len(known)} baselined)"
        )
    if child != 0:
        return child
    return 1 if new else 0


def sanitize_main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    cmd = _split_cmd(args.cmd)
    if cmd:
        return _run_wrapped(args, cmd)
    return _run_smoke(args)


if __name__ == "__main__":
    sys.exit(sanitize_main())
