"""Recompile-storm detector.

A jit cache miss is fully determined by the abstract signature of the
call — leaf shapes/dtypes/shardings, pytree structure, and the values of
non-array ("static") leaves.  The detector fingerprints that signature
per call site; when a site compiles a second time it diffs the new
signature against the previous one and says *which* leaf changed (the
information XLA's "compiling ..." log line never gives you), and when a
site's compile count exceeds the budget it escalates to tier-A
``san-recompile-storm`` — the silent storm that turns a 200ms step into
a 2-minute one.

Two entry points:

* :meth:`RecompileDetector.note` — called by the engine exactly where it
  builds an executable (``_get_compiled`` / ``train_batch`` /
  ``train_batches``), with the argument trees it is compiling for;
* :meth:`RecompileDetector.wrap` — wraps any jitted callable so each
  call computes the signature and misses are detected without engine
  cooperation (the CLI smoke loop and user code use this).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.analysis.sanitizer.core import caller_site


def _leaf_sig(leaf: Any) -> Tuple:
    """Hashable abstract signature of one pytree leaf."""
    shape = getattr(leaf, "shape", None)
    if shape is not None:
        sharding = getattr(leaf, "sharding", None)
        return ("array", tuple(shape), str(getattr(leaf, "dtype", "?")), str(sharding))
    return ("static", repr(leaf)[:120])


def signature(tree: Any) -> Tuple:
    """Abstract signature of an argument pytree, with leaf paths so a
    diff can name the guilty leaf."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return tuple(
        (jax.tree_util.keystr(path), _leaf_sig(leaf)) for path, leaf in leaves
    )


def diff_signatures(old: Tuple, new: Tuple) -> str:
    """Human explanation of the first difference between two signatures."""
    if len(old) != len(new):
        return f"pytree structure changed: {len(old)} -> {len(new)} leaves"
    for (op, osig), (np_, nsig) in zip(old, new):
        if op != np_:
            return f"pytree keys changed: {op!r} -> {np_!r}"
        if osig != nsig:
            kind = osig[0]
            if kind == "array" and nsig[0] == "array":
                parts = []
                for name, i in (("shape", 1), ("dtype", 2), ("sharding", 3)):
                    if osig[i] != nsig[i]:
                        parts.append(f"{name} {osig[i]} -> {nsig[i]}")
                return f"arg '{op}' changed: {', '.join(parts)}"
            return f"arg '{op}' changed: {osig} -> {nsig}"
    return "signature change not in the argument list (donation/compiler options?)"


class RecompileDetector:
    def __init__(self, san, enabled: bool = True, budget: int = 8):
        self.san = san
        self.enabled = enabled
        self.budget = max(1, int(budget))
        # site -> [count, last_signature]
        self._sites: Dict[str, List] = {}

    def compile_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for key, rec in self._sites.items():
            name = key[1] if isinstance(key, tuple) else key
            out[name] = out.get(name, 0) + rec[0]
        return out

    def note(
        self,
        site: str,
        args: Any = None,
        call_site: Optional[Tuple[str, int, str]] = None,
        owner: Any = None,
    ) -> None:
        """Record one compile event for ``site``.  ``args``: the argument
        pytree(s) the executable is being built for (None when the caller
        has no useful tree — only budget counting then).  ``owner``
        scopes the count: two engines in one sanitized process each get
        their own first-compile grace for the same logical site name."""
        if not self.enabled:
            return
        sig = signature(args) if args is not None else None
        rec = self._sites.setdefault((owner, site) if owner is not None else site, [0, None])
        rec[0] += 1
        count, prev = rec[0], rec[1]
        rec[1] = sig
        if count == 1:
            return  # first compile is the expected one
        where = call_site if call_site is not None else caller_site(skip_engine=True)
        why = diff_signatures(prev, sig) if (prev is not None and sig is not None) else "argument diff unavailable"
        if count > self.budget:
            self.san.record(
                "san-recompile-storm",
                f"'{site}' compiled {count}x (budget {self.budget}): {why}",
                site=where,
            )
        else:
            self.san.record(
                "san-recompile",
                f"'{site}' compiled {count}x: {why}",
                site=where,
            )

    def wrap(self, fn, site: Optional[str] = None, owner: Any = None):
        """Wrap a jitted callable: every call computes the abstract
        signature of its arguments; signatures not seen before are cache
        misses by construction and are reported through :meth:`note`
        (attributed to the *calling* line, where the drifting shape comes
        from).  ``owner`` scopes the count like :meth:`note`'s — two
        wrapped engines sharing a site name each keep their first-compile
        grace.  ``.lower``/other jit attributes pass through."""
        if not self.enabled:
            return fn
        detector = self
        label = site or getattr(fn, "__name__", None) or repr(fn)

        class _Wrapped:
            def __init__(self):
                self._seen = set()

            def __call__(self, *a, **kw):
                sig = signature((a, kw))
                if sig not in self._seen:
                    self._seen.add(sig)
                    detector.note(label, (a, kw), call_site=caller_site(), owner=owner)
                return fn(*a, **kw)

            def __getattr__(self, name):
                return getattr(fn, name)

        return _Wrapped()
