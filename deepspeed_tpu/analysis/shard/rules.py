"""ds_shard rule catalog and the audit data model.

Unlike ds_lint/ds_race, ds_shard rules are not AST visitors: Pass 1
consumes :class:`SiteContext` objects (eval-shaped engine trees +
their resolved shardings) and the family rule tables; Pass 2 consumes
optimized HLO text.  The catalog below only carries id/tier/description
so the CLI, baseline, and ds_report treat all four tools uniformly.

Rule catalog (docs/ds_shard.md has the long-form version):

* ``unresolved-partition-spec`` (A) — a param/state/KV leaf does not
  resolve through PartitionRules into a spec the mesh can realize:
  resolution raised, the spec names an axis the mesh doesn't have, the
  spec has more dims than the leaf, or a sharded dim is not divisible
  by its axis size.
* ``conflicting-partition-spec`` (A) — the leaf's *live* sharding
  contradicts the rule-resolved base spec: a dim the table shards over
  a >1-sized axis is not sharded over that axis at runtime (the rule
  engine and the executable disagree about the layout contract).
* ``dead-rule-row`` (B) — a regex row in a family table matches no
  leaf in the family's model corpus: the row documents a layout that
  cannot occur and hides typos (the rule it was meant for never fires).
* ``shadowed-rule-row`` (B) — a row matches leaves, but an earlier row
  wins first-match on every one of them: the row's spec is
  unreachable.
* ``donation-layout-mismatch`` (A) — a donated input's sharding
  differs from the output sharding at the same tree position: XLA
  cannot alias the buffer, so donation silently degrades to a copy
  (doubles peak HBM for the state tree).
* ``replicated-blowup`` (B) — an intermediate above a configurable
  fraction of per-device HBM is materialized with no sharding
  constraint on it; reported with the op's source line (pre-compile
  heuristic: GSPMD may still shard it, but above the threshold that
  bet should be explicit).
* ``unbudgeted-collective`` (A) — a compiled ICI collective whose
  bytes no CommLayer decision record or byte-model row covers within
  tolerance: GSPMD inserted a reshard nobody priced.
* ``unbudgeted-dcn-collective`` (A) — same, for a collective whose
  replica groups cross the DCN seam — including any *uncompressed*
  dense collective at/above the DCN policy floor, budgeted or not
  (PR 8's policy table requires the compressed strategy there).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.analysis.core import Finding, Rule, Severity

_SHARD_REGISTRY: Dict[str, Rule] = {}


def _register(rule_id: str, tier: str, description: str) -> None:
    _SHARD_REGISTRY[rule_id] = Rule(
        id=rule_id, tier=Severity.parse(tier), description=description,
        check=lambda *a, **k: [], scope="project",
    )


_register("unresolved-partition-spec", "A",
          "param/state/KV leaf does not resolve through PartitionRules "
          "into a spec the mesh can realize")
_register("conflicting-partition-spec", "A",
          "live leaf sharding contradicts the rule-resolved base spec")
_register("dead-rule-row", "B",
          "family-table regex row matches no leaf in the family corpus")
_register("shadowed-rule-row", "B",
          "family-table row never wins first-match (an earlier row "
          "shadows it everywhere)")
_register("donation-layout-mismatch", "A",
          "donated input sharding differs from the output sharding at "
          "the same tree position (donation degrades to a copy)")
_register("replicated-blowup", "B",
          "unconstrained intermediate above the configured HBM "
          "fraction (replicated materialization risk)")
_register("unbudgeted-collective", "A",
          "compiled ICI collective not covered by a CommLayer decision "
          "or the byte model within tolerance")
_register("unbudgeted-dcn-collective", "A",
          "DCN-crossing collective unbudgeted or uncompressed at/above "
          "the DCN policy floor")


def all_shard_rules() -> Dict[str, Rule]:
    return dict(_SHARD_REGISTRY)


def make_shard_finding(rule_id: str, path: str, line: int,
                       message: str, col: int = 0) -> Finding:
    rule = _SHARD_REGISTRY[rule_id]
    return Finding(rule=rule_id, path=path, line=line, col=col,
                   message=message, severity=rule.tier)


# ---------------------------------------------------------------------------
# audit data model
# ---------------------------------------------------------------------------

@dataclass
class LeafSpec:
    """One param/state/KV leaf as Pass 1 sees it: tree path, abstract
    shape/dtype, and (when the engine placed it) the live PartitionSpec
    it actually carries."""

    path: str
    shape: Tuple[int, ...]
    dtype: Any = None
    actual: Optional[Any] = None  # live PartitionSpec (or None: unplaced)
    kind: str = "param"           # param | state | kv


@dataclass
class DonationPair:
    """A donated input leaf and the output leaf XLA should alias it to
    (same tree position of donated argnum vs out_shardings)."""

    path: str
    donor: Optional[Any]   # PartitionSpec of the donated input leaf
    target: Optional[Any]  # PartitionSpec declared for the output leaf


@dataclass
class SiteContext:
    """Everything ds_shard knows about one engine compile site.

    Engines build these through ``hooks`` at their existing AOT-compile
    sites; test fixtures build them by hand.  ``origin`` anchors
    findings that have no better source attribution (and is the line a
    ``# ds-shard: disable=...`` pragma suppresses them on).
    """

    site: str
    mesh: Any = None                    # jax Mesh (None: spec-only ctx)
    topology: Any = None                # sharding.mesh.MeshTopology
    rules: Any = None                   # sharding.rules.PartitionRules
    origin: Tuple[str, int] = ("<unknown>", 1)
    leaves: List[LeafSpec] = field(default_factory=list)
    donations: List[DonationPair] = field(default_factory=list)
    budget: Dict[str, int] = field(default_factory=dict)
    decisions: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    jaxpr_thunk: Optional[Callable[[], Any]] = None
    hlo_thunk: Optional[Callable[[], Optional[str]]] = None

    def hlo_text(self) -> Optional[str]:
        if self.hlo_thunk is None:
            return None
        return self.hlo_thunk()


def mesh_axis_sizes(mesh) -> Dict[str, int]:
    """{axis: size} for a jax Mesh (empty when mesh is None)."""
    if mesh is None:
        return {}
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def spec_dim_axes(entry) -> Tuple[str, ...]:
    """Normalize one PartitionSpec entry to a tuple of axis names."""
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)
