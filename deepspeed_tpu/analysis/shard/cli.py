"""``ds_shard`` command-line interface.

Unlike ds_lint/ds_race (AST-only, never import the linted code),
ds_shard IMPORTS the runtime: Pass 1 eval-shapes the engine trees and
Pass 2 compiles the engines at their dryrun configs.  The CLI therefore
forces the 8-device CPU mesh before jax loads (the same environment
tests/conftest.py sets) unless devices are already configured.

Exit codes mirror ds_lint: 0 clean (or only findings below the failing
tier), 1 new findings at/above the failing tier (default: tier A),
2 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional


def _ensure_devices() -> None:
    """Give jax an 8-device CPU mesh if nothing configured one yet.
    Must run before the first jax import — a no-op when the caller
    (pytest, a TPU launcher) already owns the platform env."""
    if "jax" in sys.modules:
        return
    n = os.environ.get("DS_SHARD_DEVICES", "8")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ds_shard",
        description="Partition-spec dataflow analysis + compiled-collective "
        "audit: certifies every engine executable's comm against the byte "
        "model (docs/ds_shard.md).",
    )
    p.add_argument("--baseline", metavar="PATH",
                   help="baseline file (default: nearest .ds_shard_baseline.json)")
    p.add_argument("--no-baseline", action="store_true", help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="record all current findings as the new baseline and exit 0")
    p.add_argument("--select", metavar="RULES", help="comma-separated rule ids to run (default: all)")
    p.add_argument("--disable", metavar="RULES", help="comma-separated rule ids to skip")
    p.add_argument("--engines", metavar="NAMES",
                   help="comma-separated dryrun engines (default: train,offload,"
                   "pipe,inference,serving)")
    p.add_argument("--tables-only", action="store_true",
                   help="audit only the built-in family rule tables (no jax, sub-second)")
    p.add_argument("--inject", metavar="MODE", choices=["dcn-allgather"],
                   help="add a synthetic guilty site (CI RED-gate self-test)")
    p.add_argument("--fail-on", default="A", choices=["A", "B", "C"],
                   help="lowest tier that fails the run (default: A)")
    p.add_argument("--format", default="text", choices=["text", "json"], dest="fmt")
    p.add_argument("--json", action="store_const", const="json", dest="fmt",
                   help="shorthand for --format json")
    p.add_argument("--list-rules", action="store_true", help="print the rule catalog and exit")
    p.add_argument("-q", "--quiet", action="store_true", help="findings only, no summary")
    return p


def _split(raw: Optional[str]) -> Optional[List[str]]:
    if not raw:
        return None
    return [part.strip() for part in raw.split(",") if part.strip()]


def cli_main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    args = _build_parser().parse_args(argv)

    from deepspeed_tpu.analysis.shard.rules import all_shard_rules

    if args.list_rules:
        rules = all_shard_rules()
        width = max(len(r) for r in rules)
        for rid in sorted(rules, key=lambda r: (-rules[r].tier, r)):
            rule = rules[rid]
            print(f"[{rule.tier.name}] {rid.ljust(width)}  {rule.description}")
        return 0

    if not args.tables_only:
        _ensure_devices()

    from deepspeed_tpu.analysis import baseline as baseline_mod
    from deepspeed_tpu.analysis.core import Severity
    from deepspeed_tpu.analysis.shard.runner import (
        SHARD_BASELINE_NAME,
        _REPO_ROOT,
        shard_run,
    )

    fail_on = Severity.parse(args.fail_on)
    baseline_path = args.baseline
    if args.write_baseline and baseline_path is None:
        # resolve BEFORE the run so fingerprints root at its directory
        baseline_path = baseline_mod.discover([_REPO_ROOT], name=SHARD_BASELINE_NAME) \
            or os.path.join(_REPO_ROOT, SHARD_BASELINE_NAME)

    start = time.monotonic()
    try:
        result = shard_run(
            select=_split(args.select),
            disable=_split(args.disable),
            baseline_path=baseline_path,
            use_baseline=not args.no_baseline,
            engines=_split(args.engines),
            tables_only=args.tables_only,
            inject=args.inject,
        )
    except (FileNotFoundError, KeyError, ValueError) as e:
        print(f"ds_shard: error: {e}", file=sys.stderr)
        return 2
    elapsed = time.monotonic() - start

    if args.write_baseline:
        baseline_mod.save(baseline_path, result.all_current, tool="ds_shard")
        print(f"ds_shard: wrote {len(result.all_current)} finding(s) to {baseline_path}")
        return 0

    if args.fmt == "json":
        print(json.dumps(
            {
                "findings": [
                    {
                        "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
                        "severity": f.severity.name, "message": f.message,
                        "fingerprint": f.fingerprint,
                    }
                    for f in result.findings
                ],
                "baselined": len(result.baselined),
                "suppressed": result.suppressed,
                "files": result.files,
            },
            indent=1,
        ))
    else:
        for f in result.findings:
            print(f.format())
        if not args.quiet:
            tiers = ", ".join(
                f"{result.count(t)} tier-{t.name}"
                for t in (Severity.A, Severity.B, Severity.C))
            bits = [f"{len(result.findings)} finding(s) ({tiers})"]
            if result.baselined:
                bits.append(f"{len(result.baselined)} baselined")
            if result.suppressed:
                bits.append(f"{result.suppressed} suppressed")
            print(f"ds_shard: {', '.join(bits)} in {elapsed:.2f}s "
                  f"(failing tier: {fail_on.name}+)")

    return 1 if result.failing(fail_on) else 0


def main() -> None:
    sys.exit(cli_main())


if __name__ == "__main__":
    main()
