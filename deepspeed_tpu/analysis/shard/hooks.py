"""ds_shard collector: how engines feed Pass 1/Pass 2 contexts from
their existing AOT-compile sites.

Disarmed (the default) every ``note_*`` call is a None-check and
return — the ds_san pattern, nothing on the hot path.  The ds_shard
runner arms a collector, builds the dryrun engines (compiling exactly
what production compiles), then audits every collected
:class:`~deepspeed_tpu.analysis.shard.rules.SiteContext`.

Heavy work is deferred: notes store abstract shapes (ShapeDtypeStructs)
and thunks; AOT lowering of plain-jit sites happens only when the audit
actually reads the site's HLO.
"""
from __future__ import annotations

import sys
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.analysis.shard.rules import (
    DonationPair,
    LeafSpec,
    SiteContext,
)

_COLLECTOR: Optional["ShardCollector"] = None


class ShardCollector:
    """Accumulates one SiteContext per engine compile site."""

    def __init__(self) -> None:
        self.sites: Dict[str, SiteContext] = {}
        self.notes: List[str] = []

    def add(self, ctx: SiteContext) -> None:
        self.sites[ctx.site] = ctx

    def skip(self, site: str, reason: str) -> None:
        self.notes.append(f"{site}: {reason}")


def armed() -> bool:
    return _COLLECTOR is not None


def arm() -> ShardCollector:
    global _COLLECTOR
    _COLLECTOR = ShardCollector()
    return _COLLECTOR


def disarm() -> None:
    global _COLLECTOR
    _COLLECTOR = None


def current() -> Optional[ShardCollector]:
    return _COLLECTOR


def _origin(depth: int = 2) -> Tuple[str, int]:
    """(file, line) of the engine-side note call — the anchor findings
    without HLO source metadata attach to (and the line a
    ``# ds-shard: disable=...`` pragma suppresses them on)."""
    try:
        fr = sys._getframe(depth)
        return fr.f_code.co_filename, fr.f_lineno
    except ValueError:
        return "<unknown>", 1


def _abstract(tree: Any) -> Any:
    import jax
    import numpy as np

    def conv(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(tuple(np.shape(x)), x.dtype)
        return x

    return jax.tree_util.tree_map(conv, tree)


def _live_leaves(tree: Any, kind: str, prefix: str = "") -> List[LeafSpec]:
    """LeafSpecs from a live (placed) tree: shapes plus the
    PartitionSpec each array actually carries."""
    import jax
    import numpy as np

    from deepspeed_tpu.sharding.rules import _path_str

    out: List[LeafSpec] = []
    for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        shape = tuple(np.shape(leaf))
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        path = (prefix + "/" if prefix else "") + _path_str(kp)
        out.append(LeafSpec(path=path, shape=shape,
                            dtype=getattr(leaf, "dtype", None),
                            actual=spec, kind=kind))
    return out


def _donations_from(tree: Any, donor_sh: Any, target_sh: Any) -> List[DonationPair]:
    import jax

    from deepspeed_tpu.sharding.rules import _path_str

    donor_leaves = jax.tree_util.tree_flatten_with_path(donor_sh)[0]
    target_leaves = jax.tree_util.tree_leaves(target_sh)
    out: List[DonationPair] = []
    if len(donor_leaves) != len(target_leaves):
        return out
    for (kp, d), t in zip(donor_leaves, target_leaves):
        out.append(DonationPair(
            path=_path_str(kp),
            donor=getattr(d, "spec", d),
            target=getattr(t, "spec", t)))
    return out


def _jit_hlo_thunk(jit_fn: Any, args: Tuple[Any, ...],
                   collector: ShardCollector, site: str) -> Callable[[], Optional[str]]:
    """Deferred AOT lower+compile of a plain-jit site against the
    abstract shapes of its first real invocation (the
    serving.attribute_decode pattern).  Compile failures are recorded
    as skips, not findings — pipe SPMD doesn't compile on every
    backend (tests/capabilities.py)."""
    abstract = _abstract(args)

    def thunk() -> Optional[str]:
        try:
            return jit_fn.lower(*abstract).compile().as_text()
        except Exception as e:  # noqa: BLE001 — backend capability, not a finding
            collector.skip(site, f"AOT compile unavailable: {type(e).__name__}: {e}")
            return None

    return thunk


def train_budget(engine) -> Tuple[Dict[str, int], Dict[str, Tuple[str, str]]]:
    """(byte-model budget, CommLayer decision table) for a train engine —
    the comparison baseline Pass 2 certifies compiled collectives against."""
    try:
        summary = engine.comm_summary()
    except Exception:  # noqa: BLE001 — a partially-built engine still audits specs
        return {}, {}
    budget = dict(summary.get("model") or {})
    comm_cfg = getattr(getattr(engine, "comm", None), "cfg", None)
    dcn_floor = getattr(comm_cfg, "dcn_threshold_bytes", None)
    if dcn_floor:
        budget["dcn-threshold-bytes"] = int(dcn_floor)
    return budget, dict(summary.get("table") or {})


# ---------------------------------------------------------------------------
# engine-side notes (one line at each compile site)
# ---------------------------------------------------------------------------

def note_train(engine, site: str, executable, fn=None, args=None,
               out_state_shardings=None) -> None:
    """Train engine AOT sites (train_batch / train_batches): the
    executable exists, so Pass 2 reads its HLO directly; Pass 1 gets
    the live param leaves, the state donation map (donated state vs the
    declared out_shardings), and a jaxpr thunk."""
    if _COLLECTOR is None:
        return
    budget, decisions = train_budget(engine)
    donor_sh = getattr(engine, "_state_shardings", None)
    target_sh = out_state_shardings if out_state_shardings is not None else donor_sh
    jaxpr_thunk = None
    if fn is not None and args is not None:
        abstract = _abstract(args)

        def jaxpr_thunk() -> Any:  # noqa: F811 — the closure IS the thunk
            import jax

            return jax.make_jaxpr(fn)(*abstract)

    _COLLECTOR.add(SiteContext(
        site=site,
        mesh=engine.mesh,
        topology=getattr(engine, "topology", None),
        rules=getattr(engine, "partition_rules", None),
        origin=_origin(),
        leaves=_live_leaves(engine.state.get("params", {}), "param", prefix=""),
        donations=_donations_from(donor_sh, donor_sh, target_sh) if donor_sh else [],
        budget=budget,
        decisions=decisions,
        jaxpr_thunk=jaxpr_thunk,
        hlo_thunk=lambda: executable.as_text(),
    ))


def note_jit(engine, site: str, jit_fn, args, *, mesh=None, rules=None,
             leaves=None, budget=None, decisions=None, origin=None) -> None:
    """Plain-jit compile sites (pipe train, offload drain, inference
    generate): Pass 2 AOT-lowers lazily against the call's abstract
    shapes; Pass 1 audits whatever live leaves the caller names."""
    if _COLLECTOR is None:
        return
    mesh = mesh if mesh is not None else getattr(engine, "mesh", None)
    topology = getattr(engine, "topology", None)
    if topology is None and mesh is not None:
        from deepspeed_tpu.sharding.mesh import derive_topology

        topology = derive_topology(mesh)
    _COLLECTOR.add(SiteContext(
        site=site,
        mesh=mesh,
        topology=topology,
        rules=rules if rules is not None else getattr(
            engine, "partition_rules", getattr(engine, "_rules", None)),
        origin=origin if origin is not None else _origin(),
        leaves=leaves or [],
        budget=dict(budget or {}),
        decisions=dict(decisions or {}),
        hlo_thunk=_jit_hlo_thunk(jit_fn, args, _COLLECTOR, site),
    ))


def note_serving(srv, site: str, jit_fn, args) -> None:
    """Serving prefill/decode: params + the KV pool are the leaf set
    (the pool is the tree ROADMAP item 1 will shard — every leaf must
    already resolve)."""
    if _COLLECTOR is None:
        return
    engine = srv.engine
    leaves = _live_leaves(engine.params, "param")
    pool = getattr(srv, "pool", None)
    if pool is not None:
        leaves += _live_leaves(getattr(pool, "k", {}), "kv", prefix="kv_pool/k")
        leaves += _live_leaves(getattr(pool, "v", {}), "kv", prefix="kv_pool/v")
    note_jit(engine, site, jit_fn, args, leaves=leaves, origin=_origin())


def live_param_leaves(tree: Any, kind: str = "param") -> List[LeafSpec]:
    """Public helper for engine hook sites."""
    return _live_leaves(tree, kind)
