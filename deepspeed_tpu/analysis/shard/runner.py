"""ds_shard runner: dryrun engine builds -> Pass 1 (spec dataflow) +
Pass 2 (compiled-collective audit) -> suppression + baseline filtering.

``shard_run`` mirrors ``lint_paths``/``race_paths`` — same LintResult
shape, same fingerprint/baseline semantics — so the CLI, CI gate,
ds_report, and tests treat all four analysis tools interchangeably.
The baseline lives next to ds_lint's as ``.ds_shard_baseline.json``;
the last self-run verdict is persisted to ``.ds_shard_status.json``
(the ds_report row).

The dryrun builds compile exactly what production compiles: each engine
is constructed at its tiny dryrun config on the 8-device CPU mesh and
driven through the ONE call that hits its AOT-compile site, with the
hook collector armed.  A builder that cannot run on the current backend
(pipe SPMD on some CPU jaxlibs) records a skip note, never a finding.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterable, List, Optional, Sequence, Set

from deepspeed_tpu.analysis import baseline as baseline_mod
from deepspeed_tpu.analysis.context import parse_suppressions
from deepspeed_tpu.analysis.core import Finding
from deepspeed_tpu.analysis.runner import LintResult
from deepspeed_tpu.analysis.shard import hooks
from deepspeed_tpu.analysis.shard.hloaudit import audit_hlo
from deepspeed_tpu.analysis.shard.rules import all_shard_rules
from deepspeed_tpu.analysis.shard.speccheck import (
    audit_builtin_tables,
    audit_site_specs,
)

SHARD_BASELINE_NAME = ".ds_shard_baseline.json"
SHARD_STATUS_NAME = ".ds_shard_status.json"

#: engine dryruns in build order; ``--engines`` selects a subset
ENGINE_DRYRUNS = ("train", "offload", "pipe", "inference", "serving")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def _select_ids(select: Optional[Iterable[str]],
                disable: Optional[Iterable[str]]) -> Set[str]:
    rules = all_shard_rules()
    keep = set(rules)
    if select:
        unknown = set(select) - set(rules)
        if unknown:
            raise KeyError(f"unknown rule(s): {sorted(unknown)}")
        keep = set(select)
    if disable:
        unknown = set(disable) - set(rules)
        if unknown:
            raise KeyError(f"unknown rule(s): {sorted(unknown)}")
        keep -= set(disable)
    return keep


# ---------------------------------------------------------------------------
# dryrun engine builders (each drives exactly one AOT-compile site)
# ---------------------------------------------------------------------------

def _gpt2_tiny_cfg():
    import dataclasses

    from deepspeed_tpu.models import gpt2

    return dataclasses.replace(
        gpt2.GPT2_TINY, remat=False, scan_unroll=gpt2.GPT2_TINY.n_layer)


def _train_config(**extra):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 10_000,
    }
    cfg.update(extra)
    return cfg


def _tiny_batch(cfg, global_bs=16, seq=16):
    import numpy as np

    rng = np.random.default_rng(0)
    return {"input_ids": rng.integers(0, cfg.vocab_size, (global_bs, seq),
                                      dtype=np.int32)}


def _dryrun_train() -> None:
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    cfg = _gpt2_tiny_cfg()
    model_fn, init_fn, tp_fn = gpt2.make_model(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(),
        config=_train_config(), tp_spec_fn=tp_fn)
    engine.train_batch(_tiny_batch(cfg))


def _dryrun_offload() -> None:
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    cfg = _gpt2_tiny_cfg()
    model_fn, init_fn, tp_fn = gpt2.make_model(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(),
        config=_train_config(
            zero_optimization={"stage": 2,
                               "offload_optimizer": {"device": "cpu"}}),
        tp_spec_fn=tp_fn)
    engine.train_batch(_tiny_batch(cfg))


class _PipeLinear:
    """Minimal pipe layer (the tests/test_pipe.py fixture shape)."""

    def __init__(self, dim, act=True):
        self.dim, self.act = dim, act

    def init(self, rng):
        import jax
        import jax.numpy as jnp
        import numpy as np

        w = jax.random.normal(rng, (self.dim, self.dim), jnp.float32)
        return {"w": w / np.sqrt(self.dim), "b": jnp.zeros((self.dim,), jnp.float32)}

    def apply(self, params, x, rng=None):
        import jax

        h = x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype)
        return jax.nn.gelu(h) if self.act else h


def _dryrun_pipe() -> None:
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.runtime.pipe import LayerSpec, PipelineModule

    def mse(outputs, labels):
        return jnp.mean((outputs.astype(jnp.float32) - labels.astype(jnp.float32)) ** 2)

    dim, gas, micro_bs = 16, 4, 2
    module = PipelineModule(
        layers=[LayerSpec(_PipeLinear, dim) for _ in range(4)]
        + [LayerSpec(_PipeLinear, dim, act=False)],
        loss_fn=mse)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module,
        config=_train_config(
            gradient_accumulation_steps=gas,
            mesh={"pipe": 2, "data": -1}))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((gas * micro_bs, dim)).astype(np.float32)
    y = np.tanh(x * 0.3)
    engine.train_batch(batch=(x, y))


def _dryrun_inference() -> None:
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    inf = deepspeed_tpu.init_inference(
        model_config=gpt2.GPT2_TINY, params=gpt2.init_params(gpt2.GPT2_TINY),
        dtype=jnp.float32, max_out_tokens=gpt2.GPT2_TINY.n_positions)
    inf.generate(np.ones((2, 8), np.int32), max_new_tokens=4)


def _dryrun_serving() -> None:
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.serving import ServingEngine

    inf = deepspeed_tpu.init_inference(
        model_config=gpt2.GPT2_TINY, params=gpt2.init_params(gpt2.GPT2_TINY),
        dtype=jnp.float32, max_out_tokens=gpt2.GPT2_TINY.n_positions)
    srv = ServingEngine(inf, num_slots=2, prefill_chunk=8, max_len=32)
    # building the jits is enough — the notes fire at construction and
    # Pass 2 AOT-lowers lazily; nothing needs to execute
    srv._get_prefill()
    srv._get_decode()


_BUILDERS = {
    "train": _dryrun_train,
    "offload": _dryrun_offload,
    "pipe": _dryrun_pipe,
    "inference": _dryrun_inference,
    "serving": _dryrun_serving,
}


def _inject_dcn_allgather(collector: hooks.ShardCollector) -> None:
    """RED-gate fixture: a hand-injected ``with_sharding_constraint``
    that forces GSPMD to materialize a >=1 MiB uncompressed all-gather
    across the full device set — with ``DS_DCN_SLICES=2`` its replica
    groups cross the DCN seam, which the audit must flag as tier-A
    ``unbudgeted-dcn-collective`` no matter what any budget says."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.analysis.shard.rules import SiteContext
    from deepspeed_tpu.sharding.mesh import derive_topology

    devices = np.asarray(jax.devices())
    mesh = Mesh(devices.reshape((devices.size,)), ("data",))

    def fn(x):
        y = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))
        return y * 2.0

    jit_fn = jax.jit(
        fn,
        # deliberately guilty: the RED-gate spec bypasses the rule engine
        in_shardings=NamedSharding(mesh, P("data")),  # ds-lint: disable=hand-built-partition-spec
        out_shardings=NamedSharding(mesh, P()),
    )
    arg = jax.ShapeDtypeStruct((1 << 19,), jnp.float32)  # 2 MiB f32

    def hlo_thunk():
        try:
            return jit_fn.lower(arg).compile().as_text()
        except Exception as e:  # noqa: BLE001
            collector.skip("inject.dcn-allgather",
                           f"AOT compile unavailable: {type(e).__name__}: {e}")
            return None

    collector.add(SiteContext(
        site="inject.dcn-allgather",
        mesh=mesh,
        topology=derive_topology(mesh),
        origin=(os.path.abspath(__file__), 1),
        hlo_thunk=hlo_thunk,
    ))


def collect_sites(engines: Optional[Sequence[str]] = None,
                  inject: Optional[str] = None) -> hooks.ShardCollector:
    """Arm the hook collector, run the selected dryrun builders, and
    return the collected SiteContexts (collector stays usable after
    disarm — only the global note switch is reset)."""
    wanted = tuple(engines) if engines else ENGINE_DRYRUNS
    unknown = set(wanted) - set(_BUILDERS)
    if unknown:
        raise KeyError(f"unknown engine(s): {sorted(unknown)}")
    collector = hooks.arm()
    try:
        for name in wanted:
            try:
                _BUILDERS[name]()
            except Exception as e:  # noqa: BLE001 — capability, not finding
                collector.skip(name, f"dryrun failed: {type(e).__name__}: {e}")
        if inject == "dcn-allgather":
            _inject_dcn_allgather(collector)
        elif inject:
            raise KeyError(f"unknown inject mode: {inject}")
    finally:
        hooks.disarm()
    return collector


# ---------------------------------------------------------------------------
# shard_run — the library entry point (CLI and tests go through it)
# ---------------------------------------------------------------------------

def _normalize_path(path: str, root: str) -> str:
    """Repo-relative display paths for anything under the root (stable
    fingerprints across checkouts); absolute paths stay as-is."""
    ap = os.path.abspath(path) if os.path.isabs(path) else os.path.abspath(
        os.path.join(root, path))
    try:
        rel = os.path.relpath(ap, root)
    except ValueError:
        return path
    return path if rel.startswith("..") else rel


def _read_sources(findings: List[Finding], root: str) -> Dict[str, str]:
    sources: Dict[str, str] = {}
    for f in findings:
        if f.path in sources:
            continue
        ap = f.path if os.path.isabs(f.path) else os.path.join(root, f.path)
        try:
            with open(ap, "r", encoding="utf-8") as fh:
                sources[f.path] = fh.read()
        except (OSError, UnicodeDecodeError):
            sources[f.path] = ""
    return sources


def shard_run(
    select: Optional[Iterable[str]] = None,
    disable: Optional[Iterable[str]] = None,
    baseline_path: Optional[str] = None,
    use_baseline: bool = True,
    engines: Optional[Sequence[str]] = None,
    tables_only: bool = False,
    inject: Optional[str] = None,
    root: Optional[str] = None,
    write_status: bool = True,
    sites: Optional[Sequence] = None,
) -> LintResult:
    """Run both passes and return a LintResult.

    ``sites`` bypasses the dryrun builders with prebuilt SiteContexts
    (test fixtures); ``tables_only`` audits just the built-in family
    rule tables (no jax work at all); ``inject`` adds a synthetic
    guilty site (the CI RED-gate).
    """
    root = os.path.abspath(root or _REPO_ROOT)
    keep = _select_ids(select, disable)
    result = LintResult()

    raw: List[Finding] = []
    notes: List[str] = []
    site_names: List[str] = []

    raw.extend(audit_builtin_tables())

    if sites is not None:
        for ctx in sites:
            site_names.append(ctx.site)
            raw.extend(audit_site_specs(ctx))
            raw.extend(audit_hlo(ctx))
    elif not tables_only:
        collector = collect_sites(engines=engines, inject=inject)
        for name in sorted(collector.sites):
            ctx = collector.sites[name]
            site_names.append(name)
            raw.extend(audit_site_specs(ctx))
            raw.extend(audit_hlo(ctx))
        # after the audit loop: lazy HLO thunks record their skips during it
        notes = list(collector.notes)

    raw = [f for f in raw if f.rule in keep]
    for f in raw:
        f.path = _normalize_path(f.path, root)

    sources = _read_sources(raw, root)
    live: List[Finding] = []
    suppressions = {p: parse_suppressions(src) for p, src in sources.items()}
    for f in raw:
        sup = suppressions.get(f.path)
        if sup is not None and sup.is_suppressed(f.rule, f.line):
            result.suppressed += 1
        else:
            live.append(f)

    if baseline_path is None and use_baseline:
        baseline_path = baseline_mod.discover([root], name=SHARD_BASELINE_NAME)
    result.baseline_path = baseline_path
    fp_root = os.path.dirname(os.path.abspath(baseline_path)) if baseline_path else root
    baseline_mod.assign_fingerprints(live, fp_root, sources)

    known: Set[str] = set()
    if use_baseline and baseline_path and os.path.isfile(baseline_path):
        known = baseline_mod.load(baseline_path)
    for f in live:
        (result.baselined if f.fingerprint in known else result.findings).append(f)

    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    result.baselined.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    result.files = len(sources)

    # an --inject run is deliberately guilty (the RED-gate): its verdict
    # must not clobber the persisted status ds_report shows
    if write_status and sites is None and not tables_only and inject is None:
        write_run_status(result, root=root, sites=site_names, notes=notes)
    return result


def status_path(root: Optional[str] = None) -> str:
    return os.path.join(os.path.abspath(root or _REPO_ROOT), SHARD_STATUS_NAME)


def write_run_status(result: LintResult, root: Optional[str] = None,
                     sites: Optional[Sequence[str]] = None,
                     notes: Optional[Sequence[str]] = None) -> str:
    """Persist the self-run verdict for ds_report (best-effort: a
    read-only checkout must not make the audit itself fail)."""
    from deepspeed_tpu.analysis.core import Severity

    path = status_path(root)
    payload = {
        "version": 1,
        "tool": "ds_shard",
        "verdict": "RED" if result.failing(Severity.A) else "GREEN",
        "new": len(result.findings),
        "new_tier_a": len(result.failing(Severity.A)),
        "baselined": len(result.baselined),
        "suppressed": result.suppressed,
        "sites": list(sites or []),
        "skips": list(notes or []),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    try:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=1)
            fh.write("\n")
    except OSError:
        pass
    return path


def read_run_status(root: Optional[str] = None) -> Optional[Dict]:
    try:
        with open(status_path(root), "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None
