"""ds_shard Pass 2 — compiled-collective audit (post-compile).

Walks an AOT-compiled executable's optimized HLO (the PR 11
attribution parser's regexes) and classifies every collective as
*budgeted* or *unbudgeted* against the PR 6/PR 8 comm model:

* each instruction's replica groups are mapped back to mesh axes (both
  explicit ``{{0,1},{2,3}}`` and iota ``[G,N]<=[dims]`` group formats)
  and to the DCN seam via the granule split
  (:func:`deepspeed_tpu.sharding.mesh._granules` — the same contiguous
  blocks ``DS_DCN_SLICES`` simulates);
* payloads below the control floor (loss scalars, overflow flags,
  grad-norm psums) are budgeted as control plane;
* remaining traffic is charged to a per-opcode ledger funded by the
  site's byte-model rows (``step_comm_bytes``: all-gather /
  reduce-scatter / all-reduce / grad-exchange) with the documented
  tolerance ``actual <= budget * (1 + rel) + abs``; ring-weighted
  bytes use :data:`deepspeed_tpu.utils.hlo.COLLECTIVE_WEIGHTS`
  (all-reduce counts 2x its payload) so actuals and model speak the
  same unit;
* instructions that do not fit the ledger are tier A
  ``unbudgeted-collective`` findings naming the inferred
  producer/consumer specs;
* any DCN-crossing collective is additionally held to the PR 8 policy
  floor: uncompressed (>= 2-byte element) payloads at/above
  ``dcn_floor`` are **always** tier A ``unbudgeted-dcn-collective``,
  budgeted or not — the policy table requires a compressed strategy on
  that link.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.analysis.core import Finding
from deepspeed_tpu.analysis.shard.rules import (
    SiteContext,
    make_shard_finding,
)
from deepspeed_tpu.telemetry.attribution import (
    _COLLECTIVES,
    _INSTR_RE,
    _META_RE,
    _shape_elems_bytes,
)

# budget-matching tolerance: actual <= budget * (1 + REL) + ABS.
# REL covers GSPMD's extra partial-sum reductions riding the same link
# (measured 1.18x on the dryrun train step); ABS absorbs per-step
# scalar chatter that never graduates past a few control payloads.
DEFAULT_TOLERANCE_REL = 0.30
DEFAULT_TOLERANCE_ABS = 64 * 1024
# payloads at/below this are control plane (loss means, grad norms,
# overflow flags) — always budgeted, never worth a policy row
DEFAULT_CONTROL_FLOOR = 4 * 1024
# DCN policy floor: uncompressed payloads at/above this on a
# DCN-crossing group are tier A regardless of ledger room (PR 8's
# dcn_threshold_bytes default)
DEFAULT_DCN_FLOOR = 1 * 1024 * 1024

# ring-weighted byte accounting, same convention as
# utils/hlo.collective_bytes_by_op: all-reduce moves ~2x its payload
_OP_WEIGHT = {"all-reduce": 2.0}

_GROUPS_RE = re.compile(r"replica_groups=(\{\{[^}]*(?:\},\{[^}]*)*\}\}|\[[^\]]*\]<=\[[^\]]*\](?:T\([\d,]*\))?)")
_SRC_LINE_RE = re.compile(r'source_line=(\d+)')
_SRC_FILE_RE = re.compile(r'source_file="([^"]*)"')
_DIM_RE = re.compile(r"dimensions=\{(\d+)\}")


@dataclass
class CollectiveInstr:
    """One parsed collective instruction."""

    name: str
    opcode: str
    payload_bytes: int
    dtype_bytes: int
    groups: List[List[int]] = field(default_factory=list)
    op_name: str = ""
    source_file: Optional[str] = None
    source_line: int = 1
    operand_shapes: List[Tuple[int, ...]] = field(default_factory=list)
    result_shape: Tuple[int, ...] = ()
    raw: str = ""

    @property
    def weighted_bytes(self) -> float:
        return self.payload_bytes * _OP_WEIGHT.get(self.opcode, 1.0)


def _parse_groups(raw: str) -> List[List[int]]:
    """Both replica-group encodings XLA emits: explicit
    ``{{0,1},{2,3}}`` lists and iota ``[G,N]<=[d0,d1,...]T(perm)``."""
    raw = raw.strip()
    if raw.startswith("{{"):
        return [
            [int(x) for x in grp.split(",") if x.strip()]
            for grp in re.findall(r"\{([\d,\s]*)\}", raw[1:-1])
        ]
    m = re.match(r"\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?", raw)
    if not m:
        return []
    out_dims = [int(x) for x in m.group(1).split(",")]
    src_dims = [int(x) for x in m.group(2).split(",")]
    import numpy as np

    ids = np.arange(int(np.prod(src_dims))).reshape(src_dims)
    if m.group(3):
        ids = ids.transpose([int(x) for x in m.group(3).split(",")])
    ids = ids.reshape(out_dims)
    if ids.ndim == 1:
        ids = ids.reshape(1, -1)
    return [list(map(int, row)) for row in ids]


def _result_shapes(type_str: str) -> List[Tuple[int, ...]]:
    shapes = []
    for _dt, dims in re.findall(r"(\w+)\[([\d,]*)\]", type_str):
        shapes.append(tuple(int(d) for d in dims.split(",") if d))
    return shapes


def parse_collectives(hlo_text: str) -> List[CollectiveInstr]:
    out: List[CollectiveInstr] = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m or m.group("opcode") not in _COLLECTIVES:
            continue
        if "-start" in m.group("opcode") or "-done" in m.group("opcode"):
            continue
        _elems, nbytes = _shape_elems_bytes(m.group("type"))
        dtype_bytes = 4
        dt = re.match(r"\(?\s*(\w+)\[", m.group("type"))
        if dt:
            from deepspeed_tpu.telemetry.attribution import _DTYPE_BYTES

            dtype_bytes = _DTYPE_BYTES.get(dt.group(1), 4)
        gm = _GROUPS_RE.search(line)
        meta = _META_RE.search(line)
        fm = _SRC_FILE_RE.search(line)
        lm = _SRC_LINE_RE.search(line)
        rest = m.group("rest")
        operand_shapes = _result_shapes(rest.split("metadata=")[0])
        shapes = _result_shapes(m.group("type"))
        out.append(CollectiveInstr(
            name=m.group("name"),
            opcode=m.group("opcode"),
            payload_bytes=nbytes,
            dtype_bytes=dtype_bytes,
            groups=_parse_groups(gm.group(1)) if gm else [],
            op_name=meta.group("op") if meta else "",
            source_file=fm.group(1) if fm else None,
            source_line=int(lm.group(1)) if lm else 1,
            operand_shapes=operand_shapes,
            result_shape=shapes[0] if shapes else (),
            raw=line.strip(),
        ))
    return out


# ---------------------------------------------------------------------------
# group -> mesh axes / DCN seam
# ---------------------------------------------------------------------------

def group_axes(mesh, groups: Sequence[Sequence[int]]) -> Tuple[str, ...]:
    """Which mesh axes a collective's groups span: partition ids map to
    mesh coordinates row-major over ``mesh.devices`` (GSPMD numbers
    partitions in mesh device order); an axis is spanned when its
    coordinate varies within any group."""
    import numpy as np

    if mesh is None or not groups:
        return ()
    shape = mesh.devices.shape
    spanned = set()
    n = int(np.prod(shape))
    for grp in groups:
        coords = [np.unravel_index(p, shape) for p in grp if p < n]
        if len(coords) < 2:
            continue
        for d, axis in enumerate(mesh.axis_names):
            if len({c[d] for c in coords}) > 1:
                spanned.add(axis)
    return tuple(a for a in mesh.axis_names if a in spanned)


def crosses_dcn(mesh, groups: Sequence[Sequence[int]]) -> bool:
    """True when any replica group spans more than one DCN granule
    (the contiguous device blocks ``_granules`` defines — real slices
    on multi-slice topologies, simulated ones under DS_DCN_SLICES)."""
    from deepspeed_tpu.sharding.mesh import _granules

    if mesh is None or not groups:
        return False
    flat = list(mesh.devices.flat)
    granules = _granules(flat)
    if granules is None or len(granules) <= 1:
        return False
    granule_of = {}
    for gi, devs in enumerate(granules):
        for d in devs:
            granule_of[id(d)] = gi
    for grp in groups:
        gids = {granule_of.get(id(flat[p])) for p in grp if p < len(flat)}
        if len(gids - {None}) > 1:
            return True
    return False


def _describe_specs(instr: CollectiveInstr, axes: Tuple[str, ...]) -> str:
    """Name the producer/consumer layouts a reshard mediates, inferred
    from the per-device operand/result shapes: the dim that grows by
    the group size is the gathered one (producer sharded over ``axes``
    there, consumer replicated); shrink is the scatter direction."""
    grp = len(instr.groups[0]) if instr.groups else 0
    ax = "/".join(axes) or "?"
    opnd = instr.operand_shapes[0] if instr.operand_shapes else ()
    res = instr.result_shape
    if instr.opcode == "all-gather" and opnd and res and len(opnd) == len(res):
        for d, (a, b) in enumerate(zip(opnd, res)):
            if a != b and a and b % a == 0:
                return (f"producer=P(dim{d}:{ax!r}) {opnd} -> "
                        f"consumer=replicated {res}")
    if instr.opcode == "reduce-scatter" and opnd and res and len(opnd) == len(res):
        for d, (a, b) in enumerate(zip(opnd, res)):
            if a != b and b and a % b == 0:
                return (f"producer=replicated(partial) {opnd} -> "
                        f"consumer=P(dim{d}:{ax!r}) {res}")
    if instr.opcode == "all-reduce":
        return (f"producer=partial-sum over {ax!r} {opnd or res} -> "
                f"consumer=replicated {res}")
    if instr.opcode == "all-to-all":
        return f"producer/consumer resharded across {ax!r} (groups of {grp})"
    return f"producer/consumer specs differ across {ax!r} (groups of {grp})"


# which byte-model rows fund which opcode's ledger
_LEDGER_ROWS = {
    "all-gather": ("all-gather", "weight-update-all-gather"),
    "reduce-scatter": ("reduce-scatter",),
    "all-reduce": ("all-reduce", "grad-exchange"),
    "all-to-all": ("all-to-all", "grad-exchange"),
    "collective-broadcast": ("all-gather",),
}
# decision-record sites that arm an opcode without a byte row (bytes
# are data-dependent at the site, e.g. the pipe micro-batch handoff)
_DECISION_OPCODES = {
    "collective-permute": ("pipe-p2p", "kv-handoff"),
}


def audit_hlo(ctx: SiteContext,
              tolerance_rel: float = DEFAULT_TOLERANCE_REL,
              tolerance_abs: int = DEFAULT_TOLERANCE_ABS,
              control_floor: int = DEFAULT_CONTROL_FLOOR,
              dcn_floor: Optional[int] = None) -> List[Finding]:
    """Classify every collective in the site's optimized HLO."""
    text = ctx.hlo_text()
    if not text:
        return []
    if dcn_floor is None:
        dcn_floor = int(ctx.budget.get("dcn-threshold-bytes", 0) or DEFAULT_DCN_FLOOR)
    instrs = parse_collectives(text)
    findings: List[Finding] = []
    opath, oline = ctx.origin

    def anchor(instr: CollectiveInstr) -> Tuple[str, int]:
        if instr.source_file:
            return instr.source_file, instr.source_line
        return opath, oline

    # fund the per-opcode ledgers from the byte model (ring-weighted
    # units on both sides)
    ledger: Dict[str, float] = {}
    for opcode, rows in _LEDGER_ROWS.items():
        ledger[opcode] = float(sum(int(ctx.budget.get(r, 0) or 0) for r in rows))
    strategy = str(ctx.budget.get("strategy", "dense"))

    # DCN policy first: an uncompressed dense payload at/above the
    # floor on a DCN-crossing group is tier A no matter the ledger
    dcn_flagged = set()
    for instr in instrs:
        if not crosses_dcn(ctx.mesh, instr.groups):
            continue
        if instr.payload_bytes >= dcn_floor and instr.dtype_bytes >= 2:
            axes = group_axes(ctx.mesh, instr.groups)
            p, ln = anchor(instr)
            findings.append(make_shard_finding(
                "unbudgeted-dcn-collective", p, ln,
                f"[{ctx.site}] {instr.opcode} {instr.name!r} moves "
                f"{instr.payload_bytes / 2**20:.2f} MiB of "
                f"{instr.dtype_bytes}-byte elements across the DCN seam "
                f"(axes {axes or ('?',)}, strategy={strategy}) — the "
                f"policy floor ({dcn_floor} B) requires a compressed "
                f"strategy on this link; {_describe_specs(instr, axes)}"))
            dcn_flagged.add(instr.name)

    # control plane + ledger for the rest, largest payloads first so a
    # blowup is what overflows the cap, not the legitimate tail behind it
    charged = [i for i in instrs if i.name not in dcn_flagged]
    charged.sort(key=lambda i: -i.weighted_bytes)
    spent: Dict[str, float] = {}
    over: Dict[str, List[CollectiveInstr]] = {}
    for instr in charged:
        if instr.payload_bytes <= control_floor:
            continue  # control plane: budgeted by definition
        if instr.opcode in _DECISION_OPCODES:
            sites = _DECISION_OPCODES[instr.opcode]
            if any(s in ctx.decisions for s in sites):
                continue  # a decision record priced this path
            over.setdefault(instr.opcode, []).append(instr)
            continue
        cap = ledger.get(instr.opcode, 0.0) * (1.0 + tolerance_rel) + tolerance_abs
        used = spent.get(instr.opcode, 0.0)
        if used + instr.weighted_bytes <= cap:
            spent[instr.opcode] = used + instr.weighted_bytes
            continue
        over.setdefault(instr.opcode, []).append(instr)

    for opcode, bad in over.items():
        for instr in bad:
            axes = group_axes(ctx.mesh, instr.groups)
            budget = sum(int(ctx.budget.get(r, 0) or 0)
                         for r in _LEDGER_ROWS.get(opcode, ()))
            p, ln = anchor(instr)
            findings.append(make_shard_finding(
                "unbudgeted-collective", p, ln,
                f"[{ctx.site}] {opcode} {instr.name!r} moves "
                f"{instr.weighted_bytes / 2**20:.2f} MiB (ring-weighted) "
                f"over axes {axes or ('?',)} but the byte model budgets "
                f"{budget} B for {opcode} here (strategy={strategy}) — "
                f"GSPMD inserted a reshard nobody priced; "
                f"{_describe_specs(instr, axes)}"))
    return findings
