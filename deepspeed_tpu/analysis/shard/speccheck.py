"""ds_shard Pass 1 — partition-spec dataflow (pre-compile).

Four checks, all over abstract shapes (eval_shape trees / jaxprs —
nothing executes):

* rule-table hygiene: dead and shadowed regex rows per model family,
  decided against the family's *model corpus* (every param tree the
  family's builders can produce, eval-shaped);
* leaf resolution: every param/state/KV leaf of a compile site must
  resolve through PartitionRules into a spec the site's mesh can
  realize (tier A otherwise), and the live sharding must agree with
  the resolved base spec (tier A on conflict);
* donation layout: each donated input leaf must match the declared
  output sharding at the same tree position (tier A — XLA demotes the
  alias to a copy silently);
* replicated blowup: jaxpr walk flagging unconstrained intermediates
  above ``hbm_fraction`` of per-device HBM, attributed to the op's
  source line.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.analysis.core import Finding
from deepspeed_tpu.analysis.shard.rules import (
    LeafSpec,
    SiteContext,
    make_shard_finding,
    mesh_axis_sizes,
    spec_dim_axes,
)

# Default HBM capacity the replicated-blowup threshold is a fraction
# of.  v4/v5 chips carry 16-32 GiB; override with DS_SHARD_HBM_BYTES.
DEFAULT_HBM_BYTES = 16 * 1024 ** 3
DEFAULT_HBM_FRACTION = 0.05


# ---------------------------------------------------------------------------
# rule-table hygiene: dead / shadowed rows
# ---------------------------------------------------------------------------

def _rules_source_location(pattern: str) -> Tuple[str, int]:
    """Best-effort source attribution for a family-table row: the line
    in sharding/rules.py whose text contains the regex literal (the
    tables are built from literals in that file)."""
    from deepspeed_tpu.sharding import rules as rules_mod

    path = rules_mod.__file__
    needle = pattern.replace("\\", "\\\\")
    try:
        with open(path) as f:
            for i, line in enumerate(f, start=1):
                if pattern in line or needle in line:
                    return path, i
    except OSError:
        pass
    return path, 1


def audit_rule_table(family: str, rules, corpus: Dict[str, Sequence[str]]) -> List[Finding]:
    """Dead/shadowed detection for one family table.

    ``corpus`` maps a corpus label (e.g. ``gpt2-tiny``) to the leaf
    paths of one model tree the family supports.  A row is *dead* when
    no corpus path matches its regex at all, *shadowed* when paths
    match it but an earlier row wins first-match on every one of them.
    Exact-duplicate patterns are shadowed even with an empty corpus.
    """
    findings: List[Finding] = []
    table = getattr(rules, "rules", ())
    if not table:
        return findings
    all_paths = sorted({p for paths in corpus.values() for p in paths})
    seen_patterns: Dict[str, int] = {}
    for i, (rx, _spec) in enumerate(table):
        first_hits = []
        any_hits = []
        for p in all_paths:
            if rx.search(p) is None:
                continue
            any_hits.append(p)
            winner = next(j for j, (rj, _s) in enumerate(table) if rj.search(p) is not None)
            if winner == i:
                first_hits.append(p)
        path, line = _rules_source_location(rx.pattern)
        dup_of = seen_patterns.get(rx.pattern)
        if dup_of is not None:
            findings.append(make_shard_finding(
                "shadowed-rule-row", path, line,
                f"family {family!r} row {i} ({rx.pattern!r}) duplicates "
                f"row {dup_of}; first-match-wins makes it unreachable"))
        elif all_paths and not any_hits:
            findings.append(make_shard_finding(
                "dead-rule-row", path, line,
                f"family {family!r} row {i} ({rx.pattern!r}) matches no "
                f"leaf in corpus {sorted(corpus)} — remove it or extend "
                f"the corpus"))
        elif any_hits and not first_hits:
            winners = sorted({
                next(j for j, (rj, _s) in enumerate(table) if rj.search(p) is not None)
                for p in any_hits
            })
            findings.append(make_shard_finding(
                "shadowed-rule-row", path, line,
                f"family {family!r} row {i} ({rx.pattern!r}) never wins "
                f"first-match: row(s) {winners} shadow it on "
                f"{len(any_hits)} matching leaf/leaves (e.g. {any_hits[0]!r})"))
        seen_patterns.setdefault(rx.pattern, i)
    return findings


def _leaf_paths(tree: Any) -> List[str]:
    import jax

    from deepspeed_tpu.sharding.rules import _path_str

    paths: List[str] = []
    for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        paths.append(_path_str(kp))
    return paths


def family_corpora() -> Dict[str, Dict[str, List[str]]]:
    """{family: {corpus label: leaf paths}} — one eval-shaped model
    tree per supported layout variant, so row liveness is decided
    against real trees, not guesses.  gpt2 hosts both the dense and
    the MoE block layout; neo shares gpt2's dense schema (GPT-Neo has
    no MoE variant); moe is the MoE layout alone; bert is bert."""
    import dataclasses

    import jax

    from deepspeed_tpu.models import bert, gpt2

    tiny = dataclasses.replace(gpt2.GPT2_TINY)
    tiny_moe = dataclasses.replace(gpt2.GPT2_TINY, n_experts=4)
    bert_tiny = bert.BERT_TINY

    def shaped(init_fn, *args):
        return _leaf_paths(jax.eval_shape(init_fn, *args))

    gpt2_dense = shaped(lambda: gpt2.init_params(tiny))
    gpt2_moe = shaped(lambda: gpt2.init_params(tiny_moe))
    bert_tree = shaped(lambda: bert.init_params(bert_tiny))
    return {
        "gpt2": {"gpt2-tiny": gpt2_dense, "gpt2-tiny-moe": gpt2_moe},
        "neo": {"gpt-neo (gpt2 dense schema)": gpt2_dense},
        "moe": {"gpt2-tiny-moe": gpt2_moe},
        "bert": {"bert-tiny": bert_tree},
    }


def audit_builtin_tables() -> List[Finding]:
    """Dead/shadowed audit over every registered family table."""
    from deepspeed_tpu.sharding.rules import _FAMILIES, rules_for_family

    corpora = family_corpora()
    findings: List[Finding] = []
    for family in sorted(_FAMILIES):
        findings.extend(audit_rule_table(
            family, rules_for_family(family), corpora.get(family, {})))
    return findings


# ---------------------------------------------------------------------------
# leaf resolution + conflicts
# ---------------------------------------------------------------------------

def _resolve(rules, leaf: LeafSpec):
    """(spec, error) — rule resolution with failures captured."""
    try:
        spec = rules.spec(leaf.path, leaf.shape) if rules is not None else None
    except Exception as e:  # noqa: BLE001 — a raising table IS the finding
        return None, f"resolution raised {type(e).__name__}: {e}"
    return spec, None


def audit_leaves(ctx: SiteContext) -> List[Finding]:
    findings: List[Finding] = []
    sizes = mesh_axis_sizes(ctx.mesh)
    opath, oline = ctx.origin
    for leaf in ctx.leaves:
        spec, err = _resolve(ctx.rules, leaf)
        if err is not None:
            findings.append(make_shard_finding(
                "unresolved-partition-spec", opath, oline,
                f"[{ctx.site}] {leaf.path}: {err}"))
            continue
        dims = tuple(spec) if spec is not None else ()
        if len(dims) > len(leaf.shape):
            findings.append(make_shard_finding(
                "unresolved-partition-spec", opath, oline,
                f"[{ctx.site}] {leaf.path}: spec {spec} has {len(dims)} "
                f"dims but the leaf has rank {len(leaf.shape)} "
                f"(shape {leaf.shape})"))
            continue
        bad = False
        for d, entry in enumerate(dims):
            for axis in spec_dim_axes(entry):
                size = sizes.get(axis)
                if size is None and sizes:
                    findings.append(make_shard_finding(
                        "unresolved-partition-spec", opath, oline,
                        f"[{ctx.site}] {leaf.path}: spec {spec} names "
                        f"axis {axis!r} but the mesh has "
                        f"{sorted(sizes)}"))
                    bad = True
                elif size and leaf.shape[d] % size != 0:
                    findings.append(make_shard_finding(
                        "unresolved-partition-spec", opath, oline,
                        f"[{ctx.site}] {leaf.path}: dim {d} "
                        f"(size {leaf.shape[d]}) is not divisible by "
                        f"axis {axis!r} (size {size})"))
                    bad = True
        if bad or leaf.actual is None:
            continue
        # conflict: a dim the table shards over a >1 axis must carry
        # that axis in the live sharding (composition may ADD axes —
        # ZeRO stacks fsdp on top — but must not drop the base one).
        actual_dims = tuple(leaf.actual)
        for d, entry in enumerate(dims):
            for axis in spec_dim_axes(entry):
                if sizes.get(axis, 1) <= 1:
                    continue
                live = spec_dim_axes(actual_dims[d]) if d < len(actual_dims) else ()
                if axis not in live:
                    findings.append(make_shard_finding(
                        "conflicting-partition-spec", opath, oline,
                        f"[{ctx.site}] {leaf.path}: table shards dim "
                        f"{d} over {axis!r} (spec {spec}) but the live "
                        f"sharding is {leaf.actual} — rule engine and "
                        f"executable disagree"))
    return findings


# ---------------------------------------------------------------------------
# donation layout
# ---------------------------------------------------------------------------

def audit_donations(ctx: SiteContext) -> List[Finding]:
    findings: List[Finding] = []
    opath, oline = ctx.origin
    for pair in ctx.donations:
        donor = tuple(pair.donor) if pair.donor is not None else ()
        target = tuple(pair.target) if pair.target is not None else ()
        if donor != target:
            findings.append(make_shard_finding(
                "donation-layout-mismatch", opath, oline,
                f"[{ctx.site}] {pair.path}: donated input is laid out "
                f"P{donor} but the output at the same position is "
                f"P{target} — XLA drops the alias and copies"))
    return findings


# ---------------------------------------------------------------------------
# replicated blowup (jaxpr walk)
# ---------------------------------------------------------------------------

_CONSTRAINT_PRIMS = ("sharding_constraint", "with_sharding_constraint")


def _eqn_source_line(eqn) -> Tuple[Optional[str], int]:
    try:
        from jax._src import source_info_util as siu

        frame = siu.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name, int(frame.start_line)
    except (ImportError, AttributeError, TypeError):
        pass
    return None, 1


def audit_jaxpr(ctx: SiteContext, hbm_bytes: Optional[int] = None,
                hbm_fraction: float = DEFAULT_HBM_FRACTION) -> List[Finding]:
    """Flag intermediates whose unsharded materialization exceeds
    ``hbm_fraction`` of per-device HBM and that no sharding constraint
    pins down.  Pre-compile heuristic — GSPMD may still shard the
    value — so tier B: above the threshold the layout bet must be
    explicit, not implicit."""
    if ctx.jaxpr_thunk is None:
        return []
    if hbm_bytes is None:
        hbm_bytes = int(os.environ.get("DS_SHARD_HBM_BYTES", DEFAULT_HBM_BYTES))
    threshold = int(hbm_bytes * hbm_fraction)
    try:
        jaxpr = ctx.jaxpr_thunk()
    except Exception:  # noqa: BLE001 — a site that can't trace is skipped, not fatal
        return []
    findings: List[Finding] = []
    opath, oline = ctx.origin
    constrained = set()

    # first pass marks every constrained var (constraints may appear
    # AFTER the producing eqn in program order), second pass flags
    def mark(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name in _CONSTRAINT_PRIMS:
                for v in eqn.outvars:
                    constrained.add(id(v))
                for v in eqn.invars:
                    constrained.add(id(v))
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    mark(sub.jaxpr)

    def walk(jx):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name in _CONSTRAINT_PRIMS:
                continue
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    walk(sub.jaxpr)
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                if aval is None or not hasattr(aval, "shape"):
                    continue
                nbytes = int(getattr(aval, "size", 0)) * getattr(
                    getattr(aval, "dtype", None), "itemsize", 4)
                if nbytes > threshold and id(v) not in constrained:
                    fpath, fline = _eqn_source_line(eqn)
                    findings.append(make_shard_finding(
                        "replicated-blowup", fpath or opath,
                        fline if fpath else oline,
                        f"[{ctx.site}] {name} materializes "
                        f"{aval.shape} ({nbytes / 2**20:.1f} MiB) with "
                        f"no sharding constraint — above "
                        f"{hbm_fraction:.0%} of {hbm_bytes / 2**30:.0f} "
                        f"GiB HBM, pin its layout explicitly"))

    top = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    mark(top)
    walk(top)
    return findings


def audit_site_specs(ctx: SiteContext, hbm_bytes: Optional[int] = None,
                     hbm_fraction: float = DEFAULT_HBM_FRACTION) -> List[Finding]:
    """All Pass 1 checks for one compile site."""
    out = audit_leaves(ctx)
    out += audit_donations(ctx)
    out += audit_jaxpr(ctx, hbm_bytes=hbm_bytes, hbm_fraction=hbm_fraction)
    return out
