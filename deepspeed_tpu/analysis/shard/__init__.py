"""ds_shard: partition-spec dataflow analysis + compiled-collective
audit — the fourth analysis surface next to ds_lint (AST hygiene),
ds_san (runtime numerics), and ds_race (lock discipline).

Two cooperating passes share ds_lint's Finding/severity/baseline/
suppression machinery (docs/ds_shard.md):

* **Pass 1 — spec dataflow (pre-compile, ``speccheck``):** abstract
  interpretation over the PR 8 rule engine and each engine's
  eval-shaped step trees.  Every param/state/KV leaf must resolve
  through :class:`~deepspeed_tpu.sharding.rules.PartitionRules`
  (tier A on unresolved or conflicting specs), dead/shadowed regex
  rows in the family tables are flagged, donation targets must
  layout-match their donors, and replicated intermediates above a
  configurable HBM fraction are reported with the offending op's
  source line.

* **Pass 2 — collective audit (post-compile, ``hloaudit``):** walk
  each AOT-compiled executable's optimized HLO (the PR 11 attribution
  parser) and classify every all-gather / all-reduce / reduce-scatter
  / all-to-all / collective-permute as *budgeted* (a CommLayer
  decision record or the PR 8 byte model covers it within tolerance)
  or *unbudgeted* (tier A: GSPMD inserted a reshard nobody priced —
  the finding names the mismatched producer/consumer specs), with ICI
  vs DCN rows split via
  :class:`~deepspeed_tpu.sharding.mesh.MeshTopology` so an
  uncompressed DCN-crossing collective is always tier A.

Engines feed Pass 2 through the ``hooks`` collector at their existing
AOT-compile sites; ``bin/ds_shard`` / ``python -m
deepspeed_tpu.analysis shard`` run the self-audit over the 8-device
dryrun configs.  The baseline lives next to ds_lint's as
``.ds_shard_baseline.json``.
"""
from deepspeed_tpu.analysis.shard.rules import all_shard_rules
from deepspeed_tpu.analysis.shard.runner import (
    SHARD_BASELINE_NAME,
    SHARD_STATUS_NAME,
    shard_run,
)

__all__ = [
    "all_shard_rules",
    "shard_run",
    "SHARD_BASELINE_NAME",
    "SHARD_STATUS_NAME",
]
