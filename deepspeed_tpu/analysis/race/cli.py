"""``ds_race`` command-line interface.

Two modes, mirroring the ds_lint UX (same flags, same exit codes:
0 clean, 1 failing findings / failed scenarios, 2 usage error):

* static (default): the lockset pass over the given paths, filtered by
  ``# ds-race: disable=`` suppressions and ``.ds_race_baseline.json``;
* ``--stress``: the schedule-perturbing scenario sweep (no paths
  needed); ``--seeds`` controls how many schedules each scenario
  explores and ``--scenario`` narrows the set.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from deepspeed_tpu.analysis import baseline as baseline_mod
from deepspeed_tpu.analysis.core import Severity
from deepspeed_tpu.analysis.race.rules import all_race_rules
from deepspeed_tpu.analysis.race.runner import RACE_BASELINE_NAME, race_paths
from deepspeed_tpu.analysis.runner import LintResult


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ds_race",
        description="Lock-discipline static analysis + schedule-perturbing "
        "race harness for deepspeed_tpu's threaded runtime "
        "(static mode is AST-based and never imports the analyzed code).",
    )
    p.add_argument("paths", nargs="*", help="files or directories to analyze")
    p.add_argument("--baseline", metavar="PATH",
                   help=f"baseline file (default: nearest {RACE_BASELINE_NAME})")
    p.add_argument("--no-baseline", action="store_true", help="ignore any baseline file")
    p.add_argument(
        "--write-baseline", action="store_true",
        help="record all current findings as the new baseline and exit 0",
    )
    p.add_argument("--select", metavar="RULES", help="comma-separated rule ids to run (default: all)")
    p.add_argument("--disable", metavar="RULES", help="comma-separated rule ids to skip")
    p.add_argument(
        "--fail-on", default="A", choices=["A", "B", "C"],
        help="lowest tier that fails the run (default: A)",
    )
    p.add_argument("--format", default="text", choices=["text", "json"], dest="fmt")
    p.add_argument("--list-rules", action="store_true", help="print the rule catalog and exit")
    p.add_argument("-q", "--quiet", action="store_true", help="findings only, no summary")
    # -- stress mode ----------------------------------------------------
    p.add_argument("--stress", action="store_true",
                   help="run the seeded schedule-perturbation scenarios instead "
                   "of the static pass")
    p.add_argument("--seeds", type=int, default=50, metavar="N",
                   help="schedules per scenario in --stress (default: 50)")
    p.add_argument("--scenario", metavar="NAMES",
                   help="comma-separated scenario names to run (default: all)")
    p.add_argument("--plan", metavar="PATH",
                   help="DS_FAULT_PLAN-format JSON file overriding the default "
                   "race.yield/race.stall perturbation plan")
    p.add_argument("--list-scenarios", action="store_true",
                   help="print the stress scenario catalog and exit")
    return p


def _split(raw: Optional[str]) -> Optional[List[str]]:
    if not raw:
        return None
    return [part.strip() for part in raw.split(",") if part.strip()]


def _print_catalog() -> None:
    rules = all_race_rules()
    width = max(len(r) for r in rules)
    for rid in sorted(rules, key=lambda r: (-rules[r].tier, r)):
        rule = rules[rid]
        print(f"[{rule.tier.name}] {rid.ljust(width)}  {rule.description}")


def _print_scenarios() -> None:
    from deepspeed_tpu.analysis.race.stress import all_scenarios

    scenarios = all_scenarios()
    width = max(len(n) for n in scenarios)
    for name in sorted(scenarios):
        sc = scenarios[name]
        tags = "".join(
            f" [{t}]" for t, on in (("must-fire", sc.must_fire),
                                    ("jax", sc.requires_jax)) if on
        )
        print(f"{name.ljust(width)}  {sc.description}{tags}")


def _summarize(result: LintResult, elapsed: float, fail_on: Severity, quiet: bool) -> None:
    if quiet:
        return
    tiers = ", ".join(f"{result.count(t)} tier-{t.name}" for t in (Severity.A, Severity.B, Severity.C))
    bits = [f"{len(result.findings)} finding(s) ({tiers})", f"{result.files} file(s)"]
    if result.baselined:
        bits.append(f"{len(result.baselined)} baselined")
    if result.suppressed:
        bits.append(f"{result.suppressed} suppressed")
    if result.parse_errors:
        bits.append(f"{len(result.parse_errors)} unparsable")
    print(f"ds_race: {', '.join(bits)} in {elapsed:.2f}s (failing tier: {fail_on.name}+)")


def _stress_main(args) -> int:
    from deepspeed_tpu.analysis.race.stress import run_stress

    plan_spec = None
    if args.plan:
        try:
            with open(args.plan) as f:
                plan_spec = f.read()
            json.loads(plan_spec)
        except (OSError, ValueError) as e:
            print(f"ds_race: error: cannot read plan {args.plan!r}: {e}", file=sys.stderr)
            return 2
    try:
        report = run_stress(seeds=max(1, args.seeds),
                            names=_split(args.scenario),
                            plan_spec=plan_spec)
    except KeyError as e:
        print(f"ds_race: error: {e}", file=sys.stderr)
        return 2
    if args.fmt == "json":
        print(json.dumps(report, indent=1))
    else:
        for e in report["scenarios"]:
            if e["skipped"]:
                line = f"SKIP {e['name']}: {e['skipped']}"
            else:
                n_fail = len(e["failures"])
                if e["must_fire"]:
                    verdict = "ok" if e["ok"] else "FAIL"
                    detail = (f"fired on {n_fail}/{report['seeds']} seed(s)"
                              if n_fail else "never fired")
                else:
                    verdict = "ok" if e["ok"] else "FAIL"
                    detail = (f"{report['seeds']} seed(s) clean" if e["ok"]
                              else f"{n_fail} seed(s) failed")
                line = f"{verdict:4s} {e['name']}: {detail} [{e['elapsed_s']}s]"
                if not e["ok"] and e["failures"] and not args.quiet:
                    first = e["failures"][0]
                    line += f"\n     seed {first['seed']}: {first['error']}"
            print(line)
        if not args.quiet:
            n_ok = sum(1 for e in report["scenarios"] if e["ok"])
            print(f"ds_race --stress: {n_ok}/{len(report['scenarios'])} "
                  f"scenario(s) ok over {report['seeds']} seed(s) each")
    return 0 if report["ok"] else 1


def cli_main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        _print_catalog()
        return 0
    if args.list_scenarios:
        _print_scenarios()
        return 0
    if args.stress:
        return _stress_main(args)
    if not args.paths:
        print("ds_race: no paths given (try `ds_race deepspeed_tpu/` or "
              "`ds_race --stress`)", file=sys.stderr)
        return 2
    fail_on = Severity.parse(args.fail_on)
    baseline_path = args.baseline
    if args.write_baseline and baseline_path is None:
        # resolve BEFORE analyzing so fingerprints root at its directory
        # (same first-write subtlety as ds_lint)
        baseline_path = baseline_mod.discover(
            args.paths, name=RACE_BASELINE_NAME
        ) or os.path.join(os.getcwd(), RACE_BASELINE_NAME)
    start = time.monotonic()
    try:
        result = race_paths(
            args.paths,
            select=_split(args.select),
            disable=_split(args.disable),
            baseline_path=baseline_path,
            use_baseline=not args.no_baseline,
        )
    except (FileNotFoundError, KeyError, ValueError) as e:
        print(f"ds_race: error: {e}", file=sys.stderr)
        return 2
    elapsed = time.monotonic() - start

    if args.write_baseline:
        baseline_mod.save(baseline_path, result.all_current, tool="ds_race")
        print(f"ds_race: wrote {len(result.all_current)} finding(s) to {baseline_path}")
        return 0

    if args.fmt == "json":
        print(
            json.dumps(
                {
                    "findings": [
                        {
                            "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
                            "severity": f.severity.name, "message": f.message,
                            "fingerprint": f.fingerprint,
                        }
                        for f in result.findings + result.parse_errors
                    ],
                    "baselined": len(result.baselined),
                    "suppressed": result.suppressed,
                    "files": result.files,
                },
                indent=1,
            )
        )
    else:
        for f in result.parse_errors + result.findings:
            print(f.format())
        _summarize(result, elapsed, fail_on, args.quiet)

    return 1 if result.failing(fail_on) else 0


def main() -> None:
    sys.exit(cli_main())


if __name__ == "__main__":
    main()
