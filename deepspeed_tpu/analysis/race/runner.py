"""ds_race runner: parse (shared with ds_lint) -> lockset model ->
race rules -> suppression + baseline filtering.

``race_paths`` mirrors ``lint_paths`` exactly — same LintResult shape,
same fingerprint/baseline semantics — so the CLI, CI gate, and tests
can treat the two tools interchangeably.  The baseline lives next to
ds_lint's as ``.ds_race_baseline.json``.
"""
from __future__ import annotations

import os
from typing import Iterable, List, Optional, Sequence, Set

from deepspeed_tpu.analysis import baseline as baseline_mod
from deepspeed_tpu.analysis.context import ProjectContext
from deepspeed_tpu.analysis.core import Finding
from deepspeed_tpu.analysis.runner import LintResult, parse_files
from deepspeed_tpu.analysis.race.rules import RaceModel, all_race_rules

RACE_BASELINE_NAME = ".ds_race_baseline.json"


def _select_rules(select: Optional[Iterable[str]], disable: Optional[Iterable[str]]):
    rules = all_race_rules()
    if select:
        unknown = set(select) - set(rules)
        if unknown:
            raise KeyError(f"unknown rule(s): {sorted(unknown)}")
        rules = {rid: r for rid, r in rules.items() if rid in set(select)}
    if disable:
        unknown = set(disable) - set(all_race_rules())
        if unknown:
            raise KeyError(f"unknown rule(s): {sorted(unknown)}")
        rules = {rid: r for rid, r in rules.items() if rid not in set(disable)}
    return rules


def race_paths(
    paths: Sequence[str],
    select: Optional[Iterable[str]] = None,
    disable: Optional[Iterable[str]] = None,
    baseline_path: Optional[str] = None,
    use_baseline: bool = True,
) -> LintResult:
    result = LintResult()

    contexts, sources = parse_files(paths, result)
    by_path = {fc.path: fc for fc in contexts}

    root = os.path.commonpath([os.path.abspath(p) for p in paths]) if paths else os.getcwd()
    if os.path.isfile(root):
        root = os.path.dirname(root)
    # ProjectContext kept for parity/debugging even though race rules
    # consume the prebuilt lockset model instead of raw contexts.
    ProjectContext(root=root, files=contexts)

    model = RaceModel.build(contexts)
    raw: List[Finding] = []
    for rule in _select_rules(select, disable).values():
        raw.extend(rule.check(rule, model))

    live: List[Finding] = []
    for f in raw:
        fc = by_path.get(f.path)
        if fc is not None and fc.suppressions.is_suppressed(f.rule, f.line):
            result.suppressed += 1
        else:
            live.append(f)

    if baseline_path is None and use_baseline:
        baseline_path = baseline_mod.discover(paths, name=RACE_BASELINE_NAME)
    result.baseline_path = baseline_path
    fp_root = os.path.dirname(os.path.abspath(baseline_path)) if baseline_path else root
    baseline_mod.assign_fingerprints(live, fp_root, sources)

    known: Set[str] = set()
    if use_baseline and baseline_path and os.path.isfile(baseline_path):
        known = baseline_mod.load(baseline_path)
    for f in live:
        (result.baselined if f.fingerprint in known else result.findings).append(f)

    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    result.baselined.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return result
