"""Per-class lockset model for the ds_race static pass.

The analysis is class-granular because that is how the threaded runtime
is written: every thread-crossing object in this tree (prefetcher,
AsyncCheckpointWriter, supervision monitor, fleet supervisor, metrics
registry, autotuner, serving scheduler) is a class holding its own
``threading.Lock``/``RLock``/``Condition`` next to the state it guards.
For each class we build:

* **lock attributes** — ``self.X = threading.Lock()`` (or RLock /
  Condition / a name matching ``lock|mutex|cond``) assigned anywhere in
  the class;
* **per-method accesses** — every ``self.attr`` read/write with the set
  of locks held at that point.  ``with self._lock:`` scopes a lock over
  its body; bare ``self._lock.acquire()`` / ``.release()`` pairs are
  tracked linearly within a block.  Writes include plain/augmented
  assignment, subscript stores (``self.d[k] = v``), and mutating method
  calls on the attribute (``self.q.append``, ``self.d.pop``, ...);
* **thread entry points** — methods passed as ``threading.Thread(
  target=self.m)`` (or in ``args=``) plus methods annotated with a
  ``# ds-race: entry`` comment on/above their ``def`` line (for
  cross-module callers the AST cannot see: an exporter thread calling
  ``registry.snapshot()``, the preemption watchdog calling
  ``writer.drain()``);
* **reachability closures** — the self-call graph, walked from the
  entry points (thread side) and from the public surface (main-thread
  side).  An attribute written outside ``__init__`` and touched on both
  sides is *shared state*, the unit the rules reason about.

The model is deliberately intra-class with two cross-class seams:
``self.attr = ClassName(...)`` records a sub-object edge (used by the
lock-order rule to chain acquisitions across e.g. router -> supervisor),
and the entry annotation imports thread-ness from other modules.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from deepspeed_tpu.analysis.context import FileContext

# Factories whose result is a lock-like object when assigned to self.X.
_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "multiprocessing.Lock", "multiprocessing.RLock",
}
# Fallback: attribute NAMES that read as locks even when the factory is
# indirect (e.g. `self._cv = threading.Condition(self._lock)` via alias).
_LOCKY_NAME = re.compile(r"(?:^|_)(?:lock|mutex|cond|cv)$", re.IGNORECASE)

_ENTRY_RE = re.compile(r"#\s*ds-race:\s*entry\b")

# Decorator names that mean "the body runs under self._lock" (the
# PagedKVPool idiom: @_locked wraps the method in `with self._lock:`).
_LOCKED_DECORATOR = re.compile(r"(?:^|_)(?:locked|synchronized)$", re.IGNORECASE)

# Method calls on an attribute that mutate the receiver in place.
_MUTATING_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "pop", "popleft", "popitem", "remove", "discard", "clear", "update",
    "setdefault", "sort", "reverse", "put", "put_nowait",
}

# Dunders that form part of a class's public (main-thread) surface.
_PUBLIC_DUNDERS = {
    "__call__", "__iter__", "__next__", "__enter__", "__exit__",
    "__len__", "__contains__", "__getitem__", "__setitem__",
}


@dataclass
class Access:
    """One ``self.<attr>`` touch with its held lockset."""

    attr: str
    write: bool
    method: str
    line: int
    col: int
    locks: FrozenSet[str]
    rmw: bool = False  # read-modify-write (augassign / x = f(x) shape)


@dataclass
class Acquisition:
    """A lock acquired at a site, with the locks already held there —
    the edge source for the lock-order graph."""

    lock: str  # dotted path relative to self ("_lock", "sup._lock")
    held: FrozenSet[str]
    line: int
    col: int
    method: str


@dataclass
class MethodInfo:
    name: str
    line: int
    accesses: List[Access] = field(default_factory=list)
    calls: Set[str] = field(default_factory=set)  # self.m() targets
    # (callee, held locks, line, col); callee is "m" for self.m() or
    # "attr.m" for self.attr.m() — the cross-class seam.
    calls_held: List[Tuple[str, FrozenSet[str], int, int]] = field(default_factory=list)
    acquisitions: List[Acquisition] = field(default_factory=list)
    entry: bool = False
    daemon_threads: List[Tuple[int, int]] = field(default_factory=list)
    has_join: bool = False


@dataclass
class ClassInfo:
    name: str
    path: str
    line: int
    lock_attrs: Set[str] = field(default_factory=set)
    lock_kinds: Dict[str, str] = field(default_factory=dict)  # attr -> Lock/RLock/...
    methods: Dict[str, MethodInfo] = field(default_factory=dict)
    subobjects: Dict[str, str] = field(default_factory=dict)  # attr -> Class

    # -- reachability ---------------------------------------------------
    def closure(self, roots: Sequence[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.methods]
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            stack.extend(c for c in self.methods[m].calls if c in self.methods and c not in seen)
        return seen

    def entry_methods(self) -> List[str]:
        return sorted(m for m, info in self.methods.items() if info.entry)

    def thread_reachable(self) -> Set[str]:
        return self.closure(self.entry_methods())

    def public_reachable(self) -> Set[str]:
        roots = [
            m for m in self.methods
            if (not m.startswith("_")) or m in _PUBLIC_DUNDERS
        ]
        return self.closure(roots)

    def is_lock(self, attr: str) -> bool:
        return attr in self.lock_attrs or bool(_LOCKY_NAME.search(attr))

    def inherited_locks(self) -> Dict[str, FrozenSet[str]]:
        """Locks a PRIVATE method can assume held because every in-class
        call site holds them (``_page_decref`` only ever runs under the
        pool lock).  Public methods and entries inherit nothing — an
        external caller arrives lock-free.  Fixed point over the call
        graph so the guarantee chains through private helpers."""
        inh: Dict[str, FrozenSet[str]] = {m: frozenset() for m in self.methods}
        for _ in range(len(self.methods)):
            changed = False
            for m, info in self.methods.items():
                if not m.startswith("_") or m == "__init__" or info.entry:
                    continue
                sites = [
                    held | inh[caller]
                    for caller, cinfo in self.methods.items() if caller != m
                    for callee, held, _ln, _col in cinfo.calls_held
                    if callee == m
                ]
                if not sites:
                    continue
                new = frozenset.intersection(*sites)
                if new != inh[m]:
                    inh[m] = new
                    changed = True
            if not changed:
                break
        return inh


def _self_attr_path(node: ast.AST) -> Optional[str]:
    """Dotted attribute path rooted at ``self`` ("x", "sup._lock"), or
    None if the chain is not self-rooted."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and parts:
        return ".".join(reversed(parts))
    return None


def _is_lock_path(cls: ClassInfo, path: str) -> bool:
    """Is this self-rooted path a lock?  Depth-1 paths check the class's
    known lock attrs; any depth falls back to the name heuristic on the
    last component (so ``self.sup._lock`` still counts)."""
    leaf = path.split(".")[-1]
    if "." not in path and path in cls.lock_attrs:
        return True
    return bool(_LOCKY_NAME.search(leaf))


class _MethodWalker:
    """One pass over a method body, tracking the held lockset per
    statement block.  ``with`` scoping is exact; ``acquire()``/
    ``release()`` are tracked linearly within each block (a release in a
    nested branch does not leak out — the common try/finally idiom is
    modelled by the ``with`` path anyway)."""

    def __init__(self, ctx: FileContext, cls: ClassInfo, info: MethodInfo):
        self.ctx = ctx
        self.cls = cls
        self.info = info

    # -- expression-level collection ------------------------------------
    def _record_access(self, attr: str, write: bool, node: ast.AST,
                       held: FrozenSet[str], rmw: bool = False) -> None:
        head = attr.split(".")[0]
        if self.cls.is_lock(head) or _is_lock_path(self.cls, attr):
            return
        if head in self.cls.methods:  # bound-method reference, not state
            return
        self.info.accesses.append(Access(
            attr=head, write=write, method=self.info.name,
            line=node.lineno, col=node.col_offset, locks=held, rmw=rmw,
        ))

    def _thread_call(self, call: ast.Call, held: FrozenSet[str]) -> None:
        """threading.Thread(target=self.m, args=(...)) — mark entry
        methods and daemon-ness."""
        resolved = self.ctx.resolve(call.func) or ""
        if not (resolved in ("threading.Thread", "threading.Timer")
                or resolved.endswith(".Thread") or resolved.endswith(".Timer")):
            return
        daemon = False
        targets: List[str] = []
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
            if kw.arg in ("target", "function"):
                p = _self_attr_path(kw.value)
                if p and "." not in p:
                    targets.append(p)
            if kw.arg == "args" and isinstance(kw.value, (ast.Tuple, ast.List)):
                for elt in kw.value.elts:
                    p = _self_attr_path(elt)
                    if p and "." not in p and p in self.cls.methods:
                        targets.append(p)
        for t in targets:
            if t in self.cls.methods:
                self.cls.methods[t].entry = True
        if daemon:
            self.info.daemon_threads.append((call.lineno, call.col_offset))

    def _visit_expr(self, node: ast.AST, held: FrozenSet[str]) -> None:
        """Collect reads/calls from an expression tree (no stores)."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._thread_call(sub, held)
                if isinstance(sub.func, ast.Attribute):
                    if sub.func.attr == "join":
                        self.info.has_join = True
                    path = _self_attr_path(sub.func)
                    if path is not None:
                        parts = path.split(".")
                        meth = parts[-1]
                        if len(parts) == 1:
                            # self.m() — self-call (or callback attr)
                            if meth in self.cls.methods:
                                self.info.calls.add(meth)
                                self.info.calls_held.append(
                                    (meth, held, sub.lineno, sub.col_offset))
                                continue
                        elif len(parts) == 2:
                            head = parts[0]
                            if meth in _MUTATING_METHODS:
                                self._record_access(head, True, sub.func, held)
                                continue
                            # self.attr.m() — cross-object call seam
                            self.info.calls_held.append(
                                (path, held, sub.lineno, sub.col_offset))
            elif isinstance(sub, ast.Attribute) and isinstance(sub.ctx, ast.Load):
                path = _self_attr_path(sub)
                if path is not None and "." not in path:
                    # only the innermost self.x of a chain reaches here
                    # with a one-component path
                    self._record_access(path, False, sub, held)

    def _store_targets(self, target: ast.AST, held: FrozenSet[str],
                       rmw: bool = False) -> None:
        for sub in ast.walk(target):
            if isinstance(sub, ast.Attribute) and isinstance(sub.ctx, ast.Store):
                path = _self_attr_path(sub)
                if path is not None:
                    self._record_access(path.split(".")[0], True, sub, held, rmw=rmw)
            elif isinstance(sub, ast.Subscript):
                path = _self_attr_path(sub.value)
                if path is not None:
                    self._record_access(path.split(".")[0], True, sub, held)

    # -- statement-level walk -------------------------------------------
    def _with_locks(self, stmt: ast.With) -> List[str]:
        out = []
        for item in stmt.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):  # e.g. self._cv (called? rare) / contextlib
                expr = expr.func
            path = _self_attr_path(expr)
            if path is not None and _is_lock_path(self.cls, path):
                out.append(path)
        return out

    def _acquire_release(self, stmt: ast.stmt) -> Optional[Tuple[str, bool]]:
        """(lock_path, acquired?) for a bare self.X.acquire()/release()
        expression statement."""
        if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
            return None
        func = stmt.value.func
        if not (isinstance(func, ast.Attribute) and func.attr in ("acquire", "release")):
            return None
        path = _self_attr_path(func.value)
        if path is None or not _is_lock_path(self.cls, path):
            return None
        return path, func.attr == "acquire"

    def walk_block(self, stmts: Sequence[ast.stmt], held: FrozenSet[str]) -> None:
        running = set(held)
        for stmt in stmts:
            ar = self._acquire_release(stmt)
            if ar is not None:
                lock, acquired = ar
                if acquired:
                    self.info.acquisitions.append(Acquisition(
                        lock, frozenset(running), stmt.lineno, stmt.col_offset,
                        self.info.name))
                    running.add(lock)
                else:
                    running.discard(lock)
                continue
            self._walk_stmt(stmt, frozenset(running))

    def _walk_stmt(self, stmt: ast.stmt, held: FrozenSet[str]) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            locks = self._with_locks(stmt)
            for item in stmt.items:  # evaluate context exprs outside
                self._visit_expr(item.context_expr, held)
            inner = set(held)
            for lk in locks:
                if lk not in inner:
                    self.info.acquisitions.append(Acquisition(
                        lk, frozenset(inner), stmt.lineno, stmt.col_offset,
                        self.info.name))
                inner.add(lk)
            self.walk_block(stmt.body, frozenset(inner))
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested function: body runs later (often on another thread);
            # analyze with an EMPTY lockset — the enclosing with-block's
            # lock is not held when a worker thread executes it.
            self.walk_block(stmt.body, frozenset())
        elif isinstance(stmt, ast.Assign):
            self._visit_expr(stmt.value, held)
            rmw = self._is_rmw(stmt.targets, stmt.value)
            for t in stmt.targets:
                self._store_targets(t, held, rmw=rmw)
        elif isinstance(stmt, ast.AugAssign):
            self._visit_expr(stmt.value, held)
            self._store_targets(stmt.target, held, rmw=True)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._visit_expr(stmt.value, held)
                self._store_targets(stmt.target, held)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._store_targets(t, held)
        elif isinstance(stmt, (ast.If, ast.While)):
            self._visit_expr(stmt.test, held)
            self.walk_block(stmt.body, held)
            self.walk_block(stmt.orelse, held)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_expr(stmt.iter, held)
            self._store_targets(stmt.target, held)
            self.walk_block(stmt.body, held)
            self.walk_block(stmt.orelse, held)
        elif isinstance(stmt, ast.Try):
            self.walk_block(stmt.body, held)
            for h in stmt.handlers:
                self.walk_block(h.body, held)
            self.walk_block(stmt.orelse, held)
            self.walk_block(stmt.finalbody, held)
        elif isinstance(stmt, (ast.Return, ast.Expr)):
            if getattr(stmt, "value", None) is not None:
                self._visit_expr(stmt.value, held)
        elif isinstance(stmt, (ast.Raise, ast.Assert)):
            for v in (getattr(stmt, "exc", None), getattr(stmt, "test", None),
                      getattr(stmt, "msg", None), getattr(stmt, "cause", None)):
                if v is not None:
                    self._visit_expr(v, held)
        # Pass/Break/Continue/Import/Global/ClassDef: nothing shared.

    @staticmethod
    def _is_rmw(targets: Sequence[ast.AST], value: ast.AST) -> bool:
        """``self.x = <expr mentioning self.x>`` — a read-modify-write
        even without AugAssign (e.g. ``self.x = self.x + [item]``)."""
        names = set()
        for t in targets:
            p = _self_attr_path(t) if isinstance(t, ast.Attribute) else None
            if p:
                names.add(p.split(".")[0])
        if not names:
            return False
        for sub in ast.walk(value):
            if isinstance(sub, ast.Attribute) and isinstance(sub.ctx, ast.Load):
                p = _self_attr_path(sub)
                if p and p.split(".")[0] in names:
                    return True
        return False


def _method_defs(cls_node: ast.ClassDef) -> List[ast.FunctionDef]:
    return [n for n in cls_node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _collect_lock_attrs(cls: ClassInfo, ctx: FileContext,
                        cls_node: ast.ClassDef) -> None:
    for fn in _method_defs(cls_node):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                path = _self_attr_path(t) if isinstance(t, ast.Attribute) else None
                if path is None or "." in path:
                    continue
                if isinstance(node.value, ast.Call):
                    resolved = ctx.resolve(node.value.func) or ""
                    if resolved in _LOCK_FACTORIES:
                        cls.lock_attrs.add(path)
                        cls.lock_kinds[path] = resolved.split(".")[-1]
                    else:
                        # self.attr = ClassName(...) sub-object seam
                        leaf = resolved.split(".")[-1] if resolved else ""
                        if leaf and leaf[0].isupper():
                            cls.subobjects.setdefault(path, leaf)


def _entry_annotated(ctx: FileContext, fn: ast.FunctionDef) -> bool:
    """``# ds-race: entry`` on the ``def`` line or the line above it
    (above-decorator placement also honoured)."""
    lines = ctx.source.splitlines()
    first = fn.decorator_list[0].lineno if fn.decorator_list else fn.lineno
    for ln in (fn.lineno, first - 1, fn.lineno - 1):
        if 0 < ln <= len(lines) and _ENTRY_RE.search(lines[ln - 1]):
            return True
    return False


def collect_classes(ctx: FileContext) -> List[ClassInfo]:
    """Build the lockset model for every top-level class in a file."""
    out: List[ClassInfo] = []
    for node in ctx.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        cls = ClassInfo(name=node.name, path=ctx.path, line=node.lineno)
        defs = _method_defs(node)
        for fn in defs:  # register names first so self-calls resolve
            cls.methods[fn.name] = MethodInfo(name=fn.name, line=fn.lineno)
        _collect_lock_attrs(cls, ctx, node)
        for fn in defs:
            info = cls.methods[fn.name]
            if _entry_annotated(ctx, fn):
                info.entry = True
            held: FrozenSet[str] = frozenset()
            for deco in fn.decorator_list:
                name = deco.func if isinstance(deco, ast.Call) else deco
                leaf = name.attr if isinstance(name, ast.Attribute) else (
                    name.id if isinstance(name, ast.Name) else "")
                if leaf and _LOCKED_DECORATOR.search(leaf):
                    held = frozenset({"_lock"})
                    info.acquisitions.append(Acquisition(
                        "_lock", frozenset(), fn.lineno, fn.col_offset, fn.name))
            _MethodWalker(ctx, cls, info).walk_block(fn.body, held)
        out.append(cls)
    return out


@dataclass
class SharedAttr:
    """One shared attribute and every access to it from the two
    closures — the input to the unguarded-write / inconsistent-lockset
    rules."""

    attr: str
    cls: ClassInfo
    accesses: List[Access]
    entry_methods: List[str]

    @property
    def guarded_accesses(self) -> List[Access]:
        return [a for a in self.accesses if a.locks]


def shared_attrs(cls: ClassInfo) -> List[SharedAttr]:
    entries = cls.entry_methods()
    if not entries:
        return []
    thread_side = cls.thread_reachable()
    public_side = cls.public_reachable()

    inherited = cls.inherited_locks()
    by_attr: Dict[str, List[Access]] = {}
    touched_thread: Dict[str, bool] = {}
    touched_public: Dict[str, bool] = {}
    written: Dict[str, bool] = {}
    for m, info in cls.methods.items():
        if m == "__init__" or (m not in thread_side and m not in public_side):
            continue
        for raw in info.accesses:
            a = raw
            if inherited.get(m):
                a = Access(attr=raw.attr, write=raw.write, method=raw.method,
                           line=raw.line, col=raw.col,
                           locks=raw.locks | inherited[m], rmw=raw.rmw)
            by_attr.setdefault(a.attr, []).append(a)
            if m in thread_side:
                touched_thread[a.attr] = True
            if m in public_side:
                touched_public[a.attr] = True
            if a.write:
                written[a.attr] = True

    out: List[SharedAttr] = []
    for attr, accesses in sorted(by_attr.items()):
        if written.get(attr) and touched_thread.get(attr) and touched_public.get(attr):
            out.append(SharedAttr(attr=attr, cls=cls, accesses=accesses,
                                  entry_methods=entries))
    return out
