"""The four ds_race rules, evaluated over the project-wide lockset
model (every rule is project-scope: the lock-order graph crosses files,
and keeping one scope keeps the runner trivial).

Rule catalog (docs/ds_race.md has the long-form version):

* ``race-unguarded-shared-write`` (A) — a shared attribute (written
  from a thread-entry closure AND from the public surface) is written
  with no lock held, and the write is either a read-modify-write
  (``self.n += 1`` — the classic lost update) or the attribute is
  guarded at *other* sites (so the unguarded site defeats them).  A
  plain rebind of an attribute that is never guarded anywhere is NOT
  flagged: single-word rebinds are atomic under the GIL and the tree
  uses that idiom deliberately (e.g. ``registry.step``).
* ``race-inconsistent-lockset`` (B) — writes are consistently guarded
  but some write site uses a disjoint lockset, or a read runs without
  any lock that the writers hold (a torn read across multi-field
  updates — the registry snapshot bug).
* ``race-lock-order-inversion`` (B) — cycle in the project-wide lock
  acquisition graph: node = (class, lock), edge A->B when B is acquired
  (directly, via a self-call, or via a ``self.sub.method()``
  cross-object call) while A is held.  A self-edge on a plain ``Lock``
  is reported too (self-deadlock); on an ``RLock``/``Condition`` it is
  the intended re-entrancy pattern and skipped.
* ``race-daemon-thread-no-join`` (C) — a class spawns
  ``Thread(daemon=True)`` and no method in the class ever joins: the
  thread's work can be vaporized at interpreter exit mid-critical-
  section.  Often acceptable (grandfathered in the baseline) but worth
  an explicit decision per site.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from deepspeed_tpu.analysis.context import FileContext
from deepspeed_tpu.analysis.core import Finding, Rule, Severity

from deepspeed_tpu.analysis.race.lockset import (
    Acquisition,
    ClassInfo,
    SharedAttr,
    collect_classes,
    shared_attrs,
)

_RACE_REGISTRY: Dict[str, Rule] = {}


def race_register(rule_id: str, tier: str, description: str):
    def deco(fn):
        _RACE_REGISTRY[rule_id] = Rule(
            id=rule_id, tier=Severity.parse(tier), description=description,
            check=fn, scope="project")
        return fn
    return deco


def all_race_rules() -> Dict[str, Rule]:
    return dict(_RACE_REGISTRY)


@dataclass
class RaceModel:
    """Project-wide input to the rules: every class's lockset model plus
    a name index for cross-class (sub-object) resolution."""

    classes: List[ClassInfo] = field(default_factory=list)
    by_name: Dict[str, List[ClassInfo]] = field(default_factory=dict)

    @classmethod
    def build(cls, contexts: List[FileContext]) -> "RaceModel":
        model = cls()
        for ctx in contexts:
            for ci in collect_classes(ctx):
                model.classes.append(ci)
                model.by_name.setdefault(ci.name, []).append(ci)
        return model

    def resolve_subobject(self, owner: ClassInfo, attr: str) -> Optional[ClassInfo]:
        name = owner.subobjects.get(attr)
        if name:
            cands = self.by_name.get(name, [])
            return cands[0] if cands else None
        # fallback: an attribute named after a known class ("self.router"
        # -> Router) — covers handles handed in via __init__ params,
        # where no ClassName(...) construction is visible to the model
        key = attr.lstrip("_").replace("_", "").lower()
        for cname, cands in self.by_name.items():
            if cands and cname.lower() == key:
                return cands[0]
        return None


def _finding(rule: Rule, cls: ClassInfo, line: int, col: int, message: str) -> Finding:
    return Finding(rule=rule.id, path=cls.path, line=line, col=col + 1,
                   message=message, severity=rule.tier)


def _fmt_locks(locks: FrozenSet[str]) -> str:
    return "{" + ", ".join(sorted(locks)) + "}" if locks else "{}"


# ---------------------------------------------------------------------------
# race-unguarded-shared-write (A)
# ---------------------------------------------------------------------------
@race_register(
    "race-unguarded-shared-write", "A",
    "shared attribute written without a lock (lost update / defeats other "
    "guarded sites)")
def check_unguarded_shared_write(rule: Rule, model: RaceModel) -> List[Finding]:
    out: List[Finding] = []
    for cls in model.classes:
        for sa in shared_attrs(cls):
            guarded_elsewhere = bool(sa.guarded_accesses)
            entries = ", ".join(f"{m}()" for m in sa.entry_methods)
            for a in sa.accesses:
                if not a.write or a.locks:
                    continue
                if a.rmw:
                    why = "a read-modify-write (lost update under a context switch)"
                elif guarded_elsewhere:
                    why = ("unguarded while other sites hold "
                           + _fmt_locks(next(iter(sa.guarded_accesses)).locks))
                else:
                    continue  # plain rebind, never guarded anywhere: GIL-atomic idiom
                out.append(_finding(
                    rule, cls, a.line, a.col,
                    f"'{cls.name}.{sa.attr}' is shared with thread entry "
                    f"point(s) {entries} but written lock-free in "
                    f"{a.method}(): {why}"))
    return out


# ---------------------------------------------------------------------------
# race-inconsistent-lockset (B)
# ---------------------------------------------------------------------------
@race_register(
    "race-inconsistent-lockset", "B",
    "accesses to a shared attribute disagree on which lock guards it "
    "(torn read or split-brain locking)")
def check_inconsistent_lockset(rule: Rule, model: RaceModel) -> List[Finding]:
    out: List[Finding] = []
    for cls in model.classes:
        for sa in shared_attrs(cls):
            writes = [a for a in sa.accesses if a.write]
            if not writes or any(not a.locks for a in writes):
                continue  # unguarded writes are rule-A territory
            common: Optional[FrozenSet[str]] = None
            for a in writes:
                common = a.locks if common is None else common & a.locks
            if not common:
                # writers disagree among themselves: flag the minority
                counts: Dict[FrozenSet[str], int] = {}
                for a in writes:
                    counts[a.locks] = counts.get(a.locks, 0) + 1
                majority = max(counts, key=lambda k: (counts[k], sorted(k)))
                seen: Set[Tuple[str, str]] = set()
                for a in writes:
                    if a.locks != majority and (sa.attr, a.method) not in seen:
                        seen.add((sa.attr, a.method))
                        out.append(_finding(
                            rule, cls, a.line, a.col,
                            f"'{cls.name}.{sa.attr}': write in {a.method}() "
                            f"holds {_fmt_locks(a.locks)} but the majority of "
                            f"writes hold {_fmt_locks(majority)} — the two "
                            f"locksets do not exclude each other"))
                continue
            # consistent writers; flag reads that skip the guarding lock
            seen_rm: Set[Tuple[str, str]] = set()
            for a in sa.accesses:
                if a.write or (a.locks & common) or (sa.attr, a.method) in seen_rm:
                    continue
                seen_rm.add((sa.attr, a.method))
                out.append(_finding(
                    rule, cls, a.line, a.col,
                    f"'{cls.name}.{sa.attr}' is read in {a.method}() without "
                    f"{_fmt_locks(common)}, which every write site holds — a "
                    f"concurrent writer can expose a torn/mid-update value"))
    return out


# ---------------------------------------------------------------------------
# race-lock-order-inversion (B)
# ---------------------------------------------------------------------------
def _may_acquire(cls: ClassInfo, method: str) -> List[Acquisition]:
    """Direct acquisitions of ``method`` plus those of every same-class
    callee (transitively)."""
    out: List[Acquisition] = []
    for m in sorted(cls.closure([method])):
        out.extend(cls.methods[m].acquisitions)
    return out


def _lock_node(model: RaceModel, cls: ClassInfo, lock_path: str) -> Tuple[str, str]:
    """(owner class, lock leaf) for a self-rooted lock path; a dotted
    path like ``sup._lock`` maps to the sub-object's class when known."""
    parts = lock_path.split(".")
    if len(parts) > 1:
        owner = model.resolve_subobject(cls, parts[0])
        return ((owner.name if owner else f"{cls.name}.{parts[0]}"), parts[-1])
    return (cls.name, lock_path)


@race_register(
    "race-lock-order-inversion", "B",
    "cycle in the lock acquisition graph (potential ABBA deadlock)")
def check_lock_order_inversion(rule: Rule, model: RaceModel) -> List[Finding]:
    Node = Tuple[str, str]
    edges: Dict[Node, Dict[Node, Tuple[ClassInfo, int, int]]] = {}

    def add_edge(src: Node, dst: Node, cls: ClassInfo, line: int, col: int) -> None:
        edges.setdefault(src, {}).setdefault(dst, (cls, line, col))

    for cls in model.classes:
        for info in cls.methods.values():
            # direct nested acquisitions
            for acq in info.acquisitions:
                dst = _lock_node(model, cls, acq.lock)
                for h in acq.held:
                    add_edge(_lock_node(model, cls, h), dst, cls, acq.line, acq.col)
            # calls made while holding a lock: the callee's acquisitions
            # (same class, or a sub-object's class) happen under it
            for callee, held, line, col in info.calls_held:
                if not held:
                    continue
                if "." in callee:
                    attr, meth = callee.split(".", 1)
                    target = model.resolve_subobject(cls, attr)
                else:
                    meth = callee
                    target = cls
                if target is None or meth not in target.methods:
                    continue
                for acq in _may_acquire(target, meth):
                    dst = _lock_node(model, target, acq.lock)
                    for h in held:
                        add_edge(_lock_node(model, cls, h), dst, cls, line, col)

    # drop self-edges on re-entrant primitives (the intended pattern)
    for src in list(edges):
        if src in edges[src]:
            owner_cls, leaf = src
            kinds = {
                ci.lock_kinds.get(leaf)
                for ci in model.by_name.get(owner_cls, [])
            }
            if kinds & {"RLock", "Condition", "Semaphore", "BoundedSemaphore"}:
                del edges[src][src]

    # Tarjan SCC: any SCC of size > 1, or a surviving self-edge, is a cycle
    index: Dict[Node, int] = {}
    low: Dict[Node, int] = {}
    onstack: Set[Node] = set()
    stack: List[Node] = []
    sccs: List[List[Node]] = []
    counter = [0]

    def strongconnect(v: Node) -> None:
        work = [(v, iter(sorted(edges.get(v, {}))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(sorted(edges.get(w, {})))))
                    advanced = True
                    break
                elif w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(edges):
        if v not in index:
            strongconnect(v)

    out: List[Finding] = []
    for comp in sccs:
        comp_set = set(comp)
        cyclic = len(comp) > 1 or (comp[0] in edges.get(comp[0], {}))
        if not cyclic:
            continue
        # anchor the finding at the smallest edge site inside the SCC
        sites = [
            edges[a][b] for a in comp for b in edges.get(a, {})
            if b in comp_set
        ]
        cls, line, col = min(sites, key=lambda s: (s[0].path, s[1], s[2]))
        path = " -> ".join(f"{c}.{l}" for c, l in sorted(comp_set)) or "?"
        out.append(_finding(
            rule, cls, line, col,
            f"lock acquisition cycle {path} -> (back): two threads taking "
            f"these locks in opposing order can deadlock"))
    return out


# ---------------------------------------------------------------------------
# race-daemon-thread-no-join (C)
# ---------------------------------------------------------------------------
@race_register(
    "race-daemon-thread-no-join", "C",
    "daemon thread spawned by a class that never joins it")
def check_daemon_no_join(rule: Rule, model: RaceModel) -> List[Finding]:
    out: List[Finding] = []
    for cls in model.classes:
        if any(info.has_join for info in cls.methods.values()):
            continue
        spawns = [
            (line, col, info.name)
            for info in cls.methods.values()
            for line, col in info.daemon_threads
        ]
        if not spawns:
            continue
        line, col, method = min(spawns)
        out.append(_finding(
            rule, cls, line, col,
            f"{cls.name}.{method}() spawns Thread(daemon=True) and no "
            f"method of the class joins it — interpreter exit can kill it "
            f"mid-critical-section (join in close()/stop(), or suppress "
            f"with a comment explaining the ownership)"))
    return out
