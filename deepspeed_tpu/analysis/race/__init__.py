"""ds_race: lock-discipline static analysis + schedule-perturbing race
harness for deepspeed_tpu's threaded runtime.

Third analysis surface next to ds_lint (AST hygiene) and ds_san
(numerics): shares their Finding/severity/baseline/suppression
machinery, adds a per-class lockset model (``lockset``), four race
rules (``rules``), and a seeded stress harness (``stress``) built on
the resilience FaultInjector's ``race.yield``/``race.stall`` actions.
"""
from deepspeed_tpu.analysis.race.rules import all_race_rules
from deepspeed_tpu.analysis.race.runner import RACE_BASELINE_NAME, race_paths

__all__ = ["all_race_rules", "race_paths", "RACE_BASELINE_NAME"]
