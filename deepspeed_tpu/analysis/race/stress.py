"""ds_race --stress: schedule-perturbing race scenarios.

Static lockset analysis proves discipline; this module tries to break
it.  Each scenario drives a real threaded subsystem (metrics registry,
async checkpoint writer, fleet supervisor, paged KV pool) from multiple
threads while a seeded :class:`FaultInjector` injects ``race.yield``
(drop the GIL) and ``race.stall`` (hold a window open ~0.2ms) at
instrumented lock sites — then asserts the subsystem's invariants.  A
single seed is one schedule; the harness sweeps 50+ seeds so the
interleaving space actually gets explored (CPython's ~5ms switch
interval would otherwise hide almost every window).

Instrumentation is :func:`instrument`: replace an object's ``_lock``
with a :class:`TracedLock` that funnels every acquire/release through
``faults.check_race`` under a scenario-chosen site name.  Plans target
``<site>.acquire`` (before the lock — widens lock-contention windows)
and ``<site>.held`` (just after acquiring and just before releasing —
stretches critical sections), or the ``race.*`` catch-all.

``must_fire`` scenarios invert the verdict: they drive a DELIBERATELY
unguarded fixture and pass only when the harness detects the lost
update — the seeded RED test proving the perturbation machinery can
actually catch a race (CI gates on it).
"""
from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.resilience.faults import FaultInjector, InjectedFault


class TracedLock:
    """Wraps a ``Lock``/``RLock`` so every acquire/release crosses a
    ``check_race`` perturbation point.  Re-entrancy, ``with``, and any
    extra methods delegate to the wrapped primitive."""

    def __init__(self, inner: Any, site: str):
        self._inner = inner
        self.site = site

    def acquire(self, *args, **kwargs):
        faults.check_race(self.site + ".acquire")
        got = self._inner.acquire(*args, **kwargs)
        faults.check_race(self.site + ".held")
        return got

    def release(self):
        faults.check_race(self.site + ".held")
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc_info):
        self.release()
        return False

    def __getattr__(self, name):
        return getattr(self._inner, name)


def instrument(obj: Any, attr: str = "_lock", site: str = "race.lock") -> TracedLock:
    """Swap ``obj.<attr>`` for a TracedLock (idempotent)."""
    inner = getattr(obj, attr)
    if isinstance(inner, TracedLock):
        return inner
    traced = TracedLock(inner, site)
    setattr(obj, attr, traced)
    return traced


def default_injector(seed: int) -> FaultInjector:
    """The standard perturbation plan: yield at every race site with
    p=0.25.  Scenarios layer exact-site stalls on top."""
    inj = FaultInjector(seed=seed)
    inj.race_yield("race.*", probability=0.25)
    return inj


def _run_threads(fns: Sequence[Callable[[], None]], timeout: float = 30.0) -> None:
    """Run each fn on its own thread; re-raise the first failure."""
    errors: List[BaseException] = []

    def guarded(fn):
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=guarded, args=(fn,), daemon=True)
               for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    if any(t.is_alive() for t in threads):
        raise AssertionError("scenario wedged: worker thread did not finish")
    if errors:
        raise errors[0]


# ---------------------------------------------------------------------------
# scenario registry
# ---------------------------------------------------------------------------
@dataclass
class Scenario:
    name: str
    fn: Callable[[int, FaultInjector], None]
    description: str
    must_fire: bool = False  # passes only if >= 1 seed BREAKS the invariant
    requires_jax: bool = False


_SCENARIOS: Dict[str, Scenario] = {}


def scenario(name: str, description: str, must_fire: bool = False,
             requires_jax: bool = False):
    def deco(fn):
        _SCENARIOS[name] = Scenario(name=name, fn=fn, description=description,
                                    must_fire=must_fire, requires_jax=requires_jax)
        return fn
    return deco


def all_scenarios() -> Dict[str, Scenario]:
    return dict(_SCENARIOS)


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------
@scenario(
    "registry-snapshot-under-publish",
    "export thread snapshots while two threads publish + get-or-create; "
    "asserts untorn histogram snapshots, one handle per key, exact counts")
def _registry_snapshot_under_publish(seed: int, inj: FaultInjector) -> None:
    from deepspeed_tpu.telemetry.registry import MetricsRegistry

    inj.race_stall("race.metric.h.lock.held", seconds=2e-4, probability=0.15)
    reg = MetricsRegistry(enabled=True, ring=64)
    c = reg.counter("stress/events")
    g = reg.gauge("stress/depth")
    h = reg.histogram("stress/lat")
    instrument(reg, "_lock", "race.registry.lock")
    instrument(c, "_lock", "race.metric.c.lock")
    instrument(g, "_lock", "race.metric.g.lock")
    instrument(h, "_lock", "race.metric.h.lock")

    N = 120
    stop = threading.Event()

    def publish_a():
        for i in range(N):
            c.inc()
            h.observe((i % 7) + 0.5)
            g.set(float(i))

    def publish_b():
        for i in range(N):
            c.inc(2.0)
            # get-or-create under churn: the same key must yield the
            # SAME object (two handles would silently split the count)
            assert reg.counter("stress/events") is c, "get-or-create split"
            reg.histogram("stress/other").observe(1.0)

    def export():
        while not stop.is_set():
            snap = reg.snapshot()
            for m in snap["metrics"]:
                if m["kind"] == "histogram" and m["count"]:
                    assert m["min"] is not None and m["max"] is not None, (
                        f"torn histogram snapshot: {m}")
                    lo = m["count"] * m["min"] - 1e-6
                    hi = m["count"] * m["max"] + 1e-6
                    assert lo <= m["sum"] <= hi, f"torn histogram snapshot: {m}"
            reg.snapshot_compact()

    exporter_errors: List[BaseException] = []

    def export_guarded():
        try:
            export()
        except BaseException as e:  # noqa: BLE001
            exporter_errors.append(e)

    exporter = threading.Thread(target=export_guarded, daemon=True)
    exporter.start()
    try:
        _run_threads([publish_a, publish_b])
    finally:
        stop.set()
        exporter.join(10)
    if exporter_errors:
        raise exporter_errors[0]
    assert c.value == N * 1.0 + N * 2.0, f"lost counter increments: {c.value}"
    assert h.count == N, f"lost histogram observations: {h.count}"


@scenario(
    "async-save-while-preemption",
    "preemption watchdog drains concurrently with the trainer's "
    "submit/drain loop; asserts each save is accounted exactly once")
def _async_save_while_preemption(seed: int, inj: FaultInjector) -> None:
    from deepspeed_tpu.runtime.overlap.async_writer import AsyncCheckpointWriter

    inj.race_stall("race.ckpt.commit", seconds=3e-4, probability=0.3)
    writer = AsyncCheckpointWriter(drain_timeout_seconds=10.0)
    instrument(writer, "_lock", "race.ckpt.lock")
    rng = random.Random(seed)

    K, fail_every = 10, 4

    def commit_ok():
        faults.check_race("race.ckpt.commit")

    def commit_bad():
        faults.check_race("race.ckpt.commit")
        raise InjectedFault("injected commit failure")

    stop = threading.Event()

    def watchdog():
        while not stop.is_set():
            writer.drain()

    wd_errors: List[BaseException] = []

    def watchdog_guarded():
        try:
            watchdog()
        except BaseException as e:  # noqa: BLE001
            wd_errors.append(e)

    wd = threading.Thread(target=watchdog_guarded, daemon=True)
    wd.start()
    submitted = expected_failed = 0
    try:
        for i in range(K):
            bad = i % fail_every == fail_every - 1
            while True:
                try:
                    writer.submit(f"tag-{i}", f"/nonexistent/tag-{i}",
                                  commit_bad if bad else commit_ok)
                    submitted += 1
                    expected_failed += 1 if bad else 0
                    break
                except RuntimeError:  # still in flight: trainer drains
                    writer.drain()
            if rng.random() < 0.5:
                writer.drain()
        writer.drain()
    finally:
        stop.set()
        wd.join(10)
    if wd_errors:
        raise wd_errors[0]
    writer.drain()  # final sweep in case the watchdog lost the last transition
    total = writer.completed + writer.failed
    assert total == submitted, (
        f"save accounting raced: completed({writer.completed}) + "
        f"failed({writer.failed}) != submitted({submitted})")
    assert writer.failed == expected_failed, (
        f"failed={writer.failed}, expected {expected_failed}")


@scenario(
    "fleet-route-while-background-restart",
    "router keeps handling deaths while N background restart threads "
    "complete; asserts every restart is delivered exactly once")
def _fleet_route_while_restart(seed: int, inj: FaultInjector) -> None:
    from deepspeed_tpu.serving.fleet.supervisor import (
        RESTART_PENDING,
        ReplicaSupervisor,
    )

    inj.race_stall("race.fleet.restart", seconds=2e-4, probability=0.3)

    class _Replica:
        def __init__(self, name: str, fail: bool):
            self.name = name
            self.fail = fail

        def restart(self):
            faults.check_race("race.fleet.restart")
            if self.fail:
                raise InjectedFault("injected restart failure")
            return []

    sup = ReplicaSupervisor(max_restarts=3, seed=seed,
                            sleep=lambda s: None, background=True)
    instrument(sup, "_lock", "race.fleet.lock")
    K = 20
    replicas = [_Replica(f"r{i}", fail=(i % 5 == 4)) for i in range(K)]

    def router():
        for r in replicas:
            assert sup.handle_death(r, "injected death") is RESTART_PENDING

    rt = threading.Thread(target=router, daemon=True)
    rt.start()
    done: List[Any] = []
    deadline = time.monotonic() + 20
    while len(done) < K and time.monotonic() < deadline:
        done.extend(sup.drain_completed())
        time.sleep(0)
    rt.join(10)
    done.extend(sup.drain_completed())
    assert not rt.is_alive(), "router wedged"
    assert len(done) == K and not sup.pending(), (
        f"lost restart completions: {len(done)}/{K}")
    names = sorted(r.name for r, _ in done)
    assert names == sorted(r.name for r in replicas), "duplicate/missing delivery"
    ok = sum(1 for _, replayed in done if replayed is not None)
    expected_ok = sum(1 for r in replicas if not r.fail)
    assert sup.restarts == ok == expected_ok, (
        f"restart counter raced: counter={sup.restarts} delivered={ok} "
        f"expected={expected_ok}")


@scenario(
    "scale-down-while-route",
    "one thread routes + steps a FleetRouter while another adds, drains, "
    "and removes replicas (the elastic autoscaler's membership churn); "
    "asserts no crash, exact delivery, and no handle left stranded")
def _scale_down_while_route(seed: int, inj: FaultInjector) -> None:
    from deepspeed_tpu.serving.fleet.router import FleetOverloaded, FleetRouter

    inj.race_stall("race.fleet.membership.acquire", seconds=2e-4,
                   probability=0.2)

    class _Result:
        def __init__(self, rid, now):
            self.request_id = rid
            self.submit_time = now
            self.first_token_time = now
            self.finish_time = now + 1e-3
            self.finish_reason = "eos"
            self.tokens = [rid]

    class _Replica:
        """Minimal routing surface: a request finishes after 2 steps."""

        def __init__(self, name):
            self.name = name
            self._next = 0
            self._live: Dict[int, int] = {}  # rid -> steps remaining
            self._done: Dict[int, _Result] = {}

        def alive(self):
            return True

        def submit(self, prompt, **kw):
            faults.check_race("race.fleet.submit")
            rid = self._next
            self._next += 1
            self._live[rid] = 2
            return rid

        def cancel(self, rid):
            return self._live.pop(rid, None) is not None

        def step(self):
            for rid in list(self._live):
                self._live[rid] -= 1
                if self._live[rid] <= 0:
                    del self._live[rid]
                    self._done[rid] = _Result(rid, time.monotonic())
            return bool(self._live)

        def has_work(self):
            return bool(self._live)

        def pop_results(self):
            out, self._done = self._done, {}
            return out

        def result(self, rid):
            return self._done.get(rid)

        def first_token_seen(self, rid):
            return rid in self._done

        def client_request_id(self, key):
            return None

        def estimate_ttft(self, n):
            return float(len(self._live)) * 1e-3

        def queue_depth(self):
            return len(self._live)

        def degrade_level(self):
            return 0

        def draining(self):
            return False

    router = FleetRouter([_Replica("r0")])
    instrument(router, "_mlock", "race.fleet.membership")
    N = 60
    submitted: List[int] = []
    stop = threading.Event()

    def route_and_step():
        try:
            for i in range(N):
                try:
                    submitted.append(router.submit([1, 2, 3]))
                except FleetOverloaded:
                    pass
                router.step()
            deadline = time.monotonic() + 10
            while router.has_work() and time.monotonic() < deadline:
                router.step()
        finally:
            stop.set()

    def churn():
        rng = random.Random(seed * 31 + 7)
        k = 0
        while not stop.is_set():
            faults.check_race("race.fleet.churn")
            k += 1
            name = f"e{k}"
            router.add_replica(_Replica(name))
            time.sleep(rng.random() * 1e-3)
            router.begin_drain(name, "stress scale-down")
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if router.inflight_on(name) == 0:
                    try:
                        router.remove_replica(name)
                        break
                    except ValueError:
                        pass  # a handle landed between check and remove
                time.sleep(1e-4)
            else:
                raise AssertionError(f"drained replica {name} never idled")

    _run_threads([route_and_step, churn], timeout=30.0)
    assert not router.has_work(), "handles stranded after membership churn"
    results = router.pop_results()
    assert len(results) == len(submitted), (
        f"delivery raced: {len(results)} results for {len(submitted)} "
        f"submits")
    assert "r0" in router._replicas, "the permanent replica vanished"


@scenario(
    "prefix-index-insert-under-evict",
    "two threads alloc/learn/retire against a small paged pool so prefix "
    "inserts race TTL eviction pressure; asserts no refcount underflow "
    "or double free",
    requires_jax=True)
def _prefix_insert_under_evict(seed: int, inj: FaultInjector) -> None:
    import numpy as np

    from deepspeed_tpu.serving.kvcache.pages import PagedKVPool

    inj.race_stall("race.kvpool.lock.acquire", seconds=2e-4, probability=0.1)

    class _Req:
        def __init__(self, rid, prompt, max_new=4):
            self.request_id = rid
            self.prompt = prompt
            self.max_new_tokens = max_new
            self.prefill_pos = 0
            self.prefix_hint = 0
            self.slot = None

    pool = PagedKVPool(n_layer=1, num_slots=4, heads=1, max_len=16,
                       head_dim=4, kv_dtype=np.float32, page_len=4,
                       num_pages=24)
    instrument(pool, "_lock", "race.kvpool.lock")
    base = list(range(1, 12))

    def worker(wid: int) -> None:
        rng = random.Random(seed * 100 + wid)
        now = float(wid)
        for i in range(30):
            now += 1.0
            plen = 4 + rng.randrange(5)
            req = _Req((wid, i), np.asarray(base[:plen], np.int32))
            slot = pool.alloc_request(req, now=now)
            if slot is None:
                continue  # page churn; the scheduler would requeue
            req.slot = slot
            pool.consume_cow(slot)
            pool.learn_prefix(req, now=now)
            pool.prefix_hint_tokens(np.asarray(base[:plen], np.int32))
            # a SlotPoolError here IS the bug (double free / underflow)
            pool.retire(slot, None, now=now)

    _run_threads([partial(worker, 0), partial(worker, 1)])
    assert pool.free_slots == pool.num_slots, "slot leaked across retire"
    for entry in pool.index.evict_candidates():
        for p in entry.pages:
            assert pool.refcount(p) >= 1, (
                f"page {p} held by the prefix index has refcount "
                f"{pool.refcount(p)}")


@scenario(
    "demote-while-prefix-hit",
    "multi-turn session workers race the tier manager's demotion tick "
    "and affinity pricing probes over a paged pool with a host tier; "
    "asserts no refcount underflow, exact page accounting, and no "
    "promotion left in flight",
    requires_jax=True)
def _demote_while_prefix_hit(seed: int, inj: FaultInjector) -> None:
    import numpy as np

    from deepspeed_tpu.serving.kvcache.pages import PagedKVPool
    from deepspeed_tpu.serving.kvcache.tiers import PageTierManager

    inj.race_stall("race.kvpool.lock.acquire", seconds=2e-4, probability=0.1)
    inj.race_stall("race.kvtiers.lock.acquire", seconds=2e-4, probability=0.1)

    class _Req:
        def __init__(self, rid, prompt, sid, max_new=2):
            self.request_id = rid
            self.prompt = prompt
            self.session_id = sid
            self.max_new_tokens = max_new
            self.prefill_pos = 0
            self.prefix_hint = 0
            self.slot = None
            # retire() parks prompt + generated[:-1] under the session
            self.generated = [7, 8]
            self.finish_reason = "length"

    pool = PagedKVPool(n_layer=1, num_slots=4, heads=1, max_len=16,
                       head_dim=4, kv_dtype=np.float32, page_len=4,
                       num_pages=20)
    # host-only tier (no T2): every demotion/promotion is a synchronous
    # gather/scatter under the two instrumented locks, which is exactly
    # the window a stale schedule would free a mid-promotion page in
    mgr = PageTierManager(pool, host_pages=6, residency_window=4,
                          demote_watermark=0.3, demote_batch=4)
    pool.attach_tiers(mgr)
    instrument(pool, "_lock", "race.kvpool.lock")
    instrument(mgr, "_lock", "race.kvtiers.lock")
    hist: Dict[str, Any] = {}
    finished: List[int] = []

    def turns(wid: int) -> None:
        rng = random.Random(seed * 100 + wid)
        now = float(wid)
        try:
            for i in range(25):
                now += 1.0
                sid = f"s{wid}-{i % 2}"
                prev = hist.get(sid)
                if prev is None or prev.shape[0] > 10:
                    prompt = np.asarray(
                        [wid * 50 + 1 + t for t in range(4 + rng.randrange(3))],
                        np.int32)
                else:  # extend the parked turn so promotion gets a hit
                    prompt = np.concatenate(
                        [prev, np.asarray([rng.randrange(1, 99)], np.int32)])
                req = _Req((wid, i), prompt, sid)
                slot = pool.alloc_request(req, now=now)
                if slot is None:
                    continue  # page churn; the scheduler would requeue
                req.slot = slot
                pool.consume_cow(slot)
                pool.learn_prefix(req, now=now)
                pool.affinity_tokens(prompt, session_id=sid)
                # a SlotPoolError anywhere here IS the bug (a demotion
                # freed a page the live slot or a promotion still holds)
                pool.retire(slot, req, now=now)
                hist[sid] = np.concatenate(
                    [prompt, np.asarray(req.generated[:-1], np.int32)])
        finally:
            finished.append(wid)

    def ticker() -> None:
        # the migration pump an idle engine runs from stats(): demotes
        # past the (deliberately low) watermark while turns promote
        now = 1000.0
        while len(finished) < 2:
            now += 1.0
            mgr.tick(now)
            time.sleep(1e-4)

    _run_threads([partial(turns, 0), partial(turns, 1), ticker])
    assert pool.free_slots == pool.num_slots, "slot leaked across retire"
    assert not mgr._promoting, f"promotion left in flight: {mgr._promoting}"
    # exact page accounting: every live page is held by the prefix index
    # and/or a warm session, with a refcount equal to its holder count
    held: Dict[int, int] = {}
    for entry in pool.index.entries():
        for p in entry.pages:
            held[p] = held.get(p, 0) + 1
    for sess in pool.sessions.warm():
        for p in sess.pages:
            held[p] = held.get(p, 0) + 1
    for p, n in held.items():
        assert pool.refcount(p) == n, (
            f"page {p} refcount {pool.refcount(p)} != {n} holders "
            "(underflow or leaked reference)")
    assert pool.pages_live == len(held), (
        f"{pool.pages_live} live pages but only {len(held)} accounted for")


@scenario(
    "tenant-refill-under-admit",
    "two tenants' admit loops race the token-bucket refill path, a WFQ "
    "tag/pick loop, and snapshot readers; asserts exact bucket "
    "accounting (burst + refilled - consumed == tokens), an exact "
    "throttle count, and monotone per-tenant WFQ clocks")
def _tenant_refill_under_admit(seed: int, inj: FaultInjector) -> None:
    from deepspeed_tpu.serving.frontdoor.tenants import (
        TenantRegistry,
        TenantThrottled,
    )

    inj.race_stall("race.tenant.lock.acquire", seconds=2e-4, probability=0.2)
    inj.race_stall("race.tenant.refill", seconds=2e-4, probability=0.3)

    reg = TenantRegistry()
    reg._overrides = {
        "a": {"refill_tokens_per_second": 400.0, "burst_tokens": 40.0},
        "b": {"refill_tokens_per_second": 250.0, "burst_tokens": 25.0,
              "weight": 2.0},
    }
    instrument(reg, "_lock", "race.tenant.lock")

    N = 80
    throttled = {"a": 0, "b": 0}  # each key written by ONE thread

    def admits(tenant: str) -> None:
        rng = random.Random(seed * 100 + (0 if tenant == "a" else 1))
        now = 0.0
        last_tag = -1.0
        for _ in range(N):
            now += rng.random() * 0.02  # per-bucket clocks stay monotone
            cost = 1.0 + rng.randrange(10)
            try:
                reg.admit(tenant, cost, now)
            except TenantThrottled as e:
                assert e.retry_after is not None and e.retry_after > 0, (
                    f"throttle without retry_after: {e!r}")
                throttled[tenant] += 1
            tag = reg.tag(tenant, cost)
            assert tag > last_tag, (
                f"tenant {tenant} WFQ clock went backwards: "
                f"{tag} after {last_tag}")
            last_tag = tag

    class _Queued:
        def __init__(self, tenant, tag, priority):
            self.tenant = tenant
            self.wfq_tag = tag
            self.priority = priority

    stop = threading.Event()

    def pick_and_snapshot():
        rng = random.Random(seed * 7 + 3)
        while not stop.is_set():
            q = [_Queued(t, reg.tag(t, 0.5), rng.randrange(3))
                 for t in ("a", "b", "bg") for _ in range(2)]
            i = reg.pick(q)
            assert 0 <= i < len(q), f"pick index {i} out of range"
            reg.snapshot()

    picker_errors: List[BaseException] = []

    def picker_guarded():
        try:
            pick_and_snapshot()
        except BaseException as e:  # noqa: BLE001
            picker_errors.append(e)

    picker = threading.Thread(target=picker_guarded, daemon=True)
    picker.start()
    try:
        _run_threads([partial(admits, "a"), partial(admits, "b")])
    finally:
        stop.set()
        picker.join(10)
    if picker_errors:
        raise picker_errors[0]
    for t in ("a", "b"):
        st = reg.state(t)
        b = st.bucket
        assert abs(b.burst + b.refilled - b.consumed - b.tokens) < 1e-6, (
            f"tenant {t} bucket accounting tore: burst={b.burst} "
            f"refilled={b.refilled} consumed={b.consumed} tokens={b.tokens}")
        assert -1e-9 <= b.tokens <= b.burst + 1e-9, (
            f"tenant {t} bucket over/underflow: {b.tokens} of {b.burst}")
        assert st.counters["submitted"] == N, (
            f"tenant {t} lost submits: {st.counters['submitted']}/{N}")
        assert st.counters["throttled"] == throttled[t], (
            f"tenant {t} throttle count raced: counter="
            f"{st.counters['throttled']} observed={throttled[t]}")


@scenario(
    "fixture-torn-counter",
    "DELIBERATELY unguarded read-modify-write; the harness must observe "
    "a lost update under at least one seed (the dynamic RED gate)",
    must_fire=True)
def _fixture_torn_counter(seed: int, inj: FaultInjector) -> None:
    class _TornCounter:
        """The racy fixture: the yield between read and write-back is
        exactly the window ``race.yield`` schedules another bump into."""

        def __init__(self):
            self.value = 0

        def bump(self):
            v = self.value
            faults.check_race("race.fixture.torn")
            self.value = v + 1

    torn = _TornCounter()
    N = 200

    def bumper():
        for _ in range(N):
            torn.bump()

    _run_threads([bumper, bumper])
    assert torn.value == 2 * N, f"lost {2 * N - torn.value} update(s)"


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------
def _plan_with_seed(plan_spec: str, seed: int) -> FaultInjector:
    import json

    doc = json.loads(plan_spec)
    doc["seed"] = seed
    return FaultInjector.from_plan(json.dumps(doc))


def run_stress(
    seeds: int = 50,
    names: Optional[Sequence[str]] = None,
    plan_spec: Optional[str] = None,
    include_must_fire: bool = True,
) -> Dict[str, Any]:
    """Sweep every (selected) scenario across ``seeds`` schedules.
    Returns the report dict the CLI renders/JSON-dumps.  A normal
    scenario is ok when NO seed fails; a must_fire scenario is ok when
    at least one seed fails (detection works)."""
    picked = all_scenarios()
    if names is not None:
        unknown = set(names) - set(picked)
        if unknown:
            raise KeyError(f"unknown scenario(s): {sorted(unknown)}")
        picked = {n: s for n, s in picked.items() if n in set(names)}
    report: Dict[str, Any] = {"seeds": seeds, "scenarios": []}
    # scenarios inject faults on purpose; the runtime's WARNING/ERROR
    # lines about them would print seeds × scenarios times
    ds_logger = logging.getLogger("deepspeed_tpu")
    saved_level = ds_logger.level
    ds_logger.setLevel(logging.CRITICAL)
    try:
        _run_scenarios(picked, seeds, plan_spec, include_must_fire, report)
    finally:
        ds_logger.setLevel(saved_level)
    report["ok"] = all(e["ok"] for e in report["scenarios"])
    return report


def _run_scenarios(picked, seeds, plan_spec, include_must_fire, report) -> None:
    for name in sorted(picked):
        sc = picked[name]
        entry: Dict[str, Any] = {
            "name": name, "must_fire": sc.must_fire, "failures": [],
            "skipped": None,
        }
        if sc.must_fire and not include_must_fire:
            entry["skipped"] = "must-fire fixture excluded"
            entry["ok"] = True
            report["scenarios"].append(entry)
            continue
        if sc.requires_jax:
            try:
                import jax  # noqa: F401
            except Exception:  # pragma: no cover - jax-less environment
                entry["skipped"] = "jax unavailable"
                entry["ok"] = True
                report["scenarios"].append(entry)
                continue
        t0 = time.monotonic()
        for seed in range(seeds):
            inj = (_plan_with_seed(plan_spec, seed) if plan_spec
                   else default_injector(seed))
            try:
                with inj:
                    sc.fn(seed, inj)
            except AssertionError as e:
                entry["failures"].append({"seed": seed, "error": str(e)})
            except Exception as e:  # noqa: BLE001 — a crash is a failure too
                entry["failures"].append({"seed": seed, "error": repr(e)})
        entry["elapsed_s"] = round(time.monotonic() - t0, 3)
        entry["ok"] = (bool(entry["failures"]) if sc.must_fire
                       else not entry["failures"])
        report["scenarios"].append(entry)
