"""Baseline ("grandfather") file support.

A baseline records the findings that existed when a rule was introduced
so the gate only trips on *new* findings.  Fingerprints are
line-number-free: ``sha1(rule | relative path | stripped source line |
occurrence index)`` — editing an unrelated part of a file doesn't churn
the baseline, while changing the offending line itself (hopefully to fix
it) retires the entry.

Format (checked in at the repo root as ``.ds_lint_baseline.json``):

    {"version": 1, "findings": [
        {"rule": "...", "path": "...", "line": 12, "fingerprint": "..."},
        ...
    ]}
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Iterable, List, Optional, Set

from deepspeed_tpu.analysis.core import Finding

BASELINE_NAME = ".ds_lint_baseline.json"


def fingerprint(rule: str, rel_path: str, line_text: str, occurrence: int) -> str:
    key = "|".join((rule, rel_path.replace(os.sep, "/"), line_text.strip(), str(occurrence)))
    return hashlib.sha1(key.encode("utf-8")).hexdigest()[:20]


def assign_fingerprints(findings: List[Finding], root: str, sources: Dict[str, str]) -> None:
    """Fill ``finding.fingerprint`` in place.  ``sources`` maps display
    path -> file source.  Occurrence indices disambiguate identical
    lines (e.g. two ``float(x)`` calls on copy-pasted lines)."""
    counters: Dict[tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        src = sources.get(f.path, "")
        lines = src.splitlines()
        line_text = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        rel = os.path.relpath(os.path.abspath(f.path), root)
        key = (f.rule, rel, line_text.strip())
        occ = counters.get(key, 0)
        counters[key] = occ + 1
        f.fingerprint = fingerprint(f.rule, rel, line_text, occ)


def discover(paths: Iterable[str], name: str = BASELINE_NAME) -> Optional[str]:
    """Find the nearest baseline file (``name``, default ds_lint's):
    cwd first, then walking up from the first linted path.  ds_race and
    ds_san pass their own baseline filenames through ``name``."""
    cand = os.path.join(os.getcwd(), name)
    if os.path.isfile(cand):
        return cand
    for p in paths:
        d = os.path.abspath(p)
        if os.path.isfile(d):
            d = os.path.dirname(d)
        while True:
            cand = os.path.join(d, name)
            if os.path.isfile(cand):
                return cand
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
        break  # only the first path anchors discovery
    return None


def load(path: str) -> Set[str]:
    with open(path, "r") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"{path} is not a ds_lint baseline file")
    return {entry["fingerprint"] for entry in data["findings"]}


def save(path: str, findings: List[Finding], tool: str = "ds_lint") -> None:
    entries = [
        {
            "rule": f.rule,
            "path": f.path.replace(os.sep, "/"),
            "line": f.line,
            "severity": f.severity.name,
            "fingerprint": f.fingerprint,
        }
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    ]
    with open(path, "w") as f:
        json.dump({"version": 1, "tool": tool, "findings": entries}, f, indent=1)
        f.write("\n")
