"""Rule: unhashable-static-arg — static jit arguments must be hashable.

``static_argnums`` values key the jit cache by ``hash(arg)``: passing a
list/dict/set raises at call time, and a mutable default on a static
parameter is a latent version of the same bug.  Caught lexically at the
jit wrap site and at resolvable call sites.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from deepspeed_tpu.analysis.core import Severity, make_finding, register

_MUTABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)


def _is_jit_call(ctx, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    resolved = ctx.resolve(node.func)
    if not resolved:
        return False
    parts = resolved.split(".")
    return parts[-1] in ("jit", "pjit") and (parts[0] == "jax" or len(parts) == 1)


def _static_positions(jit_call: ast.Call) -> Optional[List[int]]:
    """Literal static_argnums positions, or None if not statically known."""
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return [v.value]
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for elt in v.elts:
                    if not (isinstance(elt, ast.Constant) and isinstance(elt.value, int)):
                        return None
                    out.append(elt.value)
                return out
            return None
    return None


@register(
    "unhashable-static-arg",
    Severity.A,
    "static_argnums positions fed a list/dict/set (unhashable → TypeError, or silently "
    "wrong cache keys via mutable defaults)",
)
def check(rule, ctx):
    # Local defs, to cross-check static positions against parameter defaults.
    local_defs = {
        n.name: n
        for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    # name -> static positions, for `f = jax.jit(g, static_argnums=...)`.
    wrapped_names = {}

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and _is_jit_call(ctx, node.value):
            pos = _static_positions(node.value)
            if pos is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        wrapped_names[tgt.id] = pos

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        # `jax.jit(f, static_argnums=(1,))(a, [2])` — direct invocation.
        if _is_jit_call(ctx, node.func):
            pos = _static_positions(node.func)
            if pos is not None:
                for p in pos:
                    if p < len(node.args) and isinstance(node.args[p], _MUTABLE):
                        yield make_finding(
                            rule, ctx, node.args[p],
                            f"argument {p} is marked static but a "
                            f"{type(node.args[p]).__name__.lower()} literal is passed "
                            "(unhashable); pass a tuple or hashable config object",
                        )
        # `f(a, [2])` where f = jax.jit(g, static_argnums=(1,)).
        elif isinstance(node.func, ast.Name) and node.func.id in wrapped_names:
            for p in wrapped_names[node.func.id]:
                if p < len(node.args) and isinstance(node.args[p], _MUTABLE):
                    yield make_finding(
                        rule, ctx, node.args[p],
                        f"argument {p} of '{node.func.id}' is static but a "
                        f"{type(node.args[p]).__name__.lower()} literal is passed (unhashable)",
                    )
        # `jax.jit(g, static_argnums=...)` where g's static param has a
        # mutable default — hashability bug waiting for the default path.
        if _is_jit_call(ctx, node):
            pos = _static_positions(node)
            target = node.args[0] if node.args else None
            if pos is not None and isinstance(target, ast.Name) and target.id in local_defs:
                fn = local_defs[target.id]
                params = fn.args.args
                defaults = fn.args.defaults
                offset = len(params) - len(defaults)
                for p in pos:
                    if offset <= p < len(params) and isinstance(defaults[p - offset], _MUTABLE):
                        yield make_finding(
                            rule, ctx, defaults[p - offset],
                            f"static parameter '{params[p].arg}' of '{fn.name}' has a "
                            "mutable (unhashable) default",
                        )
