"""Rule: checkpoint metadata must go through the atomic-write helper.

A bare ``open(path, "w")`` of ``latest`` / ``meta.json`` /
``manifest.json`` can tear: a crash between ``open`` and ``close``
leaves a truncated pointer or metadata file, which is exactly the
failure mode the resilience subsystem exists to remove.  The sanctioned
path is :func:`deepspeed_tpu.resilience.atomic.atomic_write_text`
(tmp file + fsync + ``os.replace``), so this rule flags any write-mode
``open`` whose path expression mentions one of the checkpoint metadata
names — outside the helper module itself.
"""
from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import Severity, make_finding, register

_META_NAMES = {"latest", "meta.json", "manifest.json"}
_META_NAME_VARS = {"LATEST_FILE", "META_FILE", "MANIFEST_FILE"}
_WRITE_CHARS = set("wax+")


def _open_mode(node: ast.Call):
    """The mode literal of an ``open()`` call, or None if absent/dynamic."""
    if len(node.args) >= 2:
        mode = node.args[1]
    else:
        mode = next((kw.value for kw in node.keywords if kw.arg == "mode"), None)
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _meta_target(node: ast.AST):
    """A checkpoint-metadata name mentioned anywhere in the path
    expression (string constant or one of the conventional constants)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            base = sub.value.replace("\\", "/").rsplit("/", 1)[-1]
            if base in _META_NAMES:
                return sub.value
        if isinstance(sub, ast.Name) and sub.id in _META_NAME_VARS:
            return sub.id
    return None


@register(
    "non-atomic-checkpoint-write",
    Severity.B,
    "checkpoint metadata written with bare open(..., 'w'); use resilience.atomic.atomic_write_text",
)
def check_atomic_write(rule, ctx):
    if ctx.path.replace("\\", "/").endswith("resilience/atomic.py"):
        return  # the helper's own implementation
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "open"
            and ctx.aliases.get("open", "open") == "open"
        ):
            continue
        mode = _open_mode(node)
        if mode is None or not (_WRITE_CHARS & set(mode)):
            continue
        if not node.args:
            continue
        hit = _meta_target(node.args[0])
        if hit is not None:
            yield make_finding(
                rule, ctx, node,
                f"checkpoint metadata ('{hit}') written with bare open(..., {mode!r}) — a "
                "crash mid-write tears the file; use "
                "deepspeed_tpu.resilience.atomic.atomic_write_text (tmp + fsync + os.replace)",
            )
