"""Rule: raw ``pl.pallas_call`` sites belong in the kernel seam.

Every Pallas kernel is a block-size decision (the autotuner's domain,
``ops/kernels/autotune.py``), a version-compat surface
(``CompilerParams`` vs ``TPUCompilerParams`` — the exact drift that held
11 tier-1 tests red on this container's jaxlib), and an attribution
contract (docs/kernels.md: every kernel lands with a bucket pin and a
bench rung).  A bare ``pl.pallas_call`` outside
``deepspeed_tpu/ops/kernels/`` and ``deepspeed_tpu/ops/attention/``
gets none of that: hardcoded tiles, per-call compat guards, and cost
invisible to the roofline table.  New kernels go in ``ops/kernels/``
(or the attention package, whose flash/splash kernels predate the
seam) and route compiler params through
:func:`deepspeed_tpu.ops.kernels.compat.tpu_compiler_params`.
"""
from __future__ import annotations

import ast
import os

from deepspeed_tpu.analysis.core import Severity, make_finding, register

# the two sanctioned kernel homes (attention/ predates the seam and
# already carries autotune defaults + attribution pins)
_EXEMPT = ("deepspeed_tpu/ops/kernels/", "deepspeed_tpu/ops/attention/")


def _is_pallas_call(node: ast.Call):
    """Match ``pl.pallas_call(...)`` / ``pallas.pallas_call(...)`` /
    bare ``pallas_call(...)`` (however the module was imported)."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "pallas_call":
        return True
    if isinstance(f, ast.Name) and f.id == "pallas_call":
        return True
    return False


@register(
    "raw-pallas-call-outside-kernels",
    Severity.B,
    "direct pl.pallas_call site outside deepspeed_tpu/ops/kernels/ and "
    "ops/attention/ — new kernels go through the kernel seam (autotuned "
    "blocks, tpu_compiler_params version shim, attribution pin + bench "
    "rung per docs/kernels.md)",
)
def check_raw_pallas_call(rule, ctx):
    path = os.path.normpath(ctx.path).replace(os.sep, "/")
    if any(marker in path for marker in _EXEMPT):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _is_pallas_call(node):
            yield make_finding(
                rule, ctx, node,
                "raw 'pallas_call' outside the kernel seam — this kernel gets "
                "no autotuned blocks, no CompilerParams version shim, and no "
                "attribution/bench coverage; put it in ops/kernels/ (see "
                "docs/kernels.md)",
            )
