"""Rules: Python side effects under trace.

A traced function's Python body runs once per compilation, not once per
step — prints vanish after the first call, ``np.random`` draws are baked
in as compile-time constants (every step reuses the same "random"
numbers), and writes to ``self``/globals leak tracers out of the trace.
"""
from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import Severity, make_finding, register
from deepspeed_tpu.analysis.traced import iter_own_nodes, traced_defs


@register(
    "print-under-trace",
    Severity.B,
    "print()/breakpoint() in a traced function only fires at trace time; use jax.debug.print",
)
def check_print(rule, ctx):
    for fn in traced_defs(ctx):
        for node in iter_own_nodes(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("print", "breakpoint")
                and node.func.id == ctx.aliases.get(node.func.id, node.func.id)
            ):
                yield make_finding(
                    rule, ctx, node,
                    f"{node.func.id}() in traced function '{fn.name}' runs at trace time "
                    "only (once per compile); use jax.debug.print for per-step output",
                )


@register(
    "np-random-under-trace",
    Severity.A,
    "np.random draws in a traced function are baked in as constants; use jax.random with a key",
)
def check_np_random(rule, ctx):
    for fn in traced_defs(ctx):
        for node in iter_own_nodes(fn):
            if isinstance(node, ast.Call):
                resolved = ctx.resolve(node.func)
                if resolved and resolved.startswith("numpy.random."):
                    yield make_finding(
                        rule, ctx, node,
                        f"{resolved} in traced function '{fn.name}' is evaluated once at "
                        "trace time and constant-folded — every step reuses the same draw; "
                        "thread a jax.random key instead",
                    )


@register(
    "global-mutation-under-trace",
    Severity.A,
    "global/self mutation in a traced function leaks tracers and skips cached executions",
)
def check_global_mutation(rule, ctx):
    for fn in traced_defs(ctx):
        for node in iter_own_nodes(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                kw = "global" if isinstance(node, ast.Global) else "nonlocal"
                yield make_finding(
                    rule, ctx, node,
                    f"{kw} {', '.join(node.names)} in traced function '{fn.name}': the "
                    "mutation happens at trace time only and can leak tracers",
                )
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        yield make_finding(
                            rule, ctx, node,
                            f"assignment to self.{tgt.attr} in traced function '{fn.name}' "
                            "is a trace-time side effect (leaked tracer; not re-run on "
                            "cached executions); return the value instead",
                        )
