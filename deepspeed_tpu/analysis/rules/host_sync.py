"""Rule: host-sync-in-jit — device→host synchronization under trace.

The exact failure mode of runtime/engine.py's host offload path
(``np.array(jax.device_get(...))``, ``float(...)``) is *correct* there
because that code runs on the host between jitted calls — but the same
calls inside a traced step function either fail at trace time or, worse,
silently fall out of the compiled computation and force a blocking
transfer every step.  This is the repo's number-one "silent 10x
slowdown" pattern.
"""
from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import Severity, make_finding, register
from deepspeed_tpu.analysis.traced import iter_own_nodes, traced_defs

_NP_MATERIALIZE = {"array", "asarray", "asanyarray", "ascontiguousarray"}
_SYNC_METHODS = {
    "item": "`.item()` forces a device→host sync under trace",
    "tolist": "`.tolist()` forces a device→host sync under trace",
    "block_until_ready": "`.block_until_ready()` blocks the host inside a traced function",
}
_CASTS = {"float", "int", "bool", "complex"}


@register(
    "host-sync-in-jit",
    Severity.A,
    "host synchronization (float()/.item()/np.array()/jax.device_get/"
    "block_until_ready) inside a jit/trace context",
)
def check(rule, ctx):
    for fn in traced_defs(ctx):
        for node in iter_own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if resolved == "jax.device_get":
                yield make_finding(
                    rule, ctx, node,
                    f"jax.device_get inside traced function '{fn.name}' pulls the value "
                    "to host every step; return it from the jitted function instead",
                )
            elif resolved and resolved.startswith("numpy.") and resolved.split(".")[-1] in _NP_MATERIALIZE:
                yield make_finding(
                    rule, ctx, node,
                    f"{resolved} inside traced function '{fn.name}' materializes a host "
                    "array (sync + constant-folds the tracer); use jnp equivalents",
                )
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in _CASTS
                and node.func.id == ctx.aliases.get(node.func.id, node.func.id)
                and len(node.args) == 1
                and not isinstance(node.args[0], ast.Constant)
            ):
                yield make_finding(
                    rule, ctx, node,
                    f"{node.func.id}() on a traced value in '{fn.name}' is a concretization "
                    "(host sync / TracerConversionError); keep it a jnp scalar",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_METHODS
                and not node.args
                and not node.keywords
            ):
                yield make_finding(
                    rule, ctx, node, f"{_SYNC_METHODS[node.func.attr]} (in '{fn.name}')"
                )
