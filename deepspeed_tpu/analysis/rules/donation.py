"""Rule: donated-buffer-reuse — reading a buffer after donating it.

``donate_argnums`` hands the input buffer to XLA for reuse; touching the
Python reference afterwards returns garbage on TPU (and only *sometimes*
errors on CPU, which is why tests don't catch it).  The engine's idiom
``state = step(state)`` is safe — the donated name is rebound by the
same statement — and is recognized as such.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from deepspeed_tpu.analysis.core import Severity, make_finding, register
from deepspeed_tpu.analysis.rules.static_args import _is_jit_call

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)


def _donated_positions(jit_call: ast.Call) -> List[int]:
    for kw in jit_call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return [v.value]
            if isinstance(v, (ast.Tuple, ast.List)):
                return [e.value for e in v.elts if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def _name_events(scope: ast.AST, name: str) -> List[Tuple[int, int, str]]:
    """(line, col, 'load'|'store') events for ``name`` in a scope."""
    events = []
    for node in ast.walk(scope):
        if isinstance(node, ast.Name) and node.id == name:
            kind = "store" if isinstance(node.ctx, (ast.Store, ast.Del)) else "load"
            events.append((node.lineno, node.col_offset, kind, node))
    return sorted(events, key=lambda e: (e[0], e[1]))


def _check_scope(rule, ctx, scope):
    # donating callables bound to names in this scope
    donating: Dict[str, List[int]] = {}
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and _is_jit_call(ctx, node.value):
            pos = _donated_positions(node.value)
            if pos:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        donating[tgt.id] = pos
        elif (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Subscript)
        ):
            # `f = self._compiled["x"]` — opaque; can't track, skip.
            pass

    for node in ast.walk(scope):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name) and node.func.id in donating:
            pos = donating[node.func.id]
        elif _is_jit_call(ctx, node.func):
            pos = _donated_positions(node.func)
        else:
            continue
        for p in pos:
            if p >= len(node.args) or not isinstance(node.args[p], ast.Name):
                continue
            donated = node.args[p].id
            end = node.end_lineno or node.lineno
            events = _name_events(scope, donated)
            # `state = step(state)` — a store on the call's own statement
            # lines is the engine's rebind idiom: the donated name is
            # immediately rebound to the result, so later reads are fine.
            if any(kind == "store" and node.lineno <= line <= end for line, col, kind, ref in events):
                continue
            for line, col, kind, ref in events:
                if line <= end:
                    continue
                if kind == "store":
                    break  # rebound before any read: safe
                yield make_finding(
                    rule, ctx, ref,
                    f"'{donated}' is read after being donated (donate_argnums={p}) at "
                    f"line {node.lineno}; the buffer was handed to XLA and its contents "
                    "are undefined — rebind the result or drop donation",
                )
                break  # one finding per donation site is enough


@register(
    "donated-buffer-reuse",
    Severity.A,
    "a Python reference is read after its buffer was donated to a jit call",
)
def check(rule, ctx):
    scopes = [n for n in ast.walk(ctx.tree) if isinstance(n, FunctionNode)]
    seen_lines = set()
    for scope in scopes:
        for f in _check_scope(rule, ctx, scope):
            key = (f.line, f.col)
            if key not in seen_lines:  # nested scopes re-walk inner code
                seen_lines.add(key)
                yield f
