"""Rule: raw metric emission belongs in the telemetry plane.

Every metric is an aggregation/export/cross-rank decision
(docs/telemetry.md): a direct ``.add_scalar(...)`` /
``.write_events(...)`` call — or a hand-built ``SummaryWriter`` —
outside ``deepspeed_tpu/telemetry/`` bypasses the registry, so the
value never reaches the JSONL/Prometheus exporters, the cross-rank
aggregate stream, or the bench-record digest, and its cadence/flush
behaviour is ad hoc.  Publish through the engine's
:class:`~deepspeed_tpu.telemetry.TelemetryManager` (or
``telemetry.get_registry()`` for out-of-engine events); the
TensorBoard monitor is a *sink* the manager forwards to.

Exempt: the telemetry package itself (where sinks legitimately call
the writer) and ``utils/monitor.py`` (the sink's own implementation).
Tier C: the value still lands somewhere; it just falls out of the
unified plane.
"""
from __future__ import annotations

import ast
import os

from deepspeed_tpu.analysis.core import Severity, make_finding, register

_EMIT_METHODS = {"add_scalar", "add_scalars", "write_events"}
_EXEMPT = ("deepspeed_tpu/telemetry/", "deepspeed_tpu/utils/monitor.py")


@register(
    "raw-metric-emit",
    Severity.C,
    "direct add_scalar/write_events call or hand-built SummaryWriter "
    "outside deepspeed_tpu/telemetry/ — publish through the metrics "
    "registry so exporters, cross-rank aggregation, and bench digests "
    "see the value",
)
def check_raw_metric(rule, ctx):
    path = os.path.normpath(ctx.path).replace(os.sep, "/")
    if any(marker in path for marker in _EXEMPT):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _EMIT_METHODS:
            yield make_finding(
                rule, ctx, node,
                f"direct '.{f.attr}()' metric emit outside the telemetry plane — "
                "route through TelemetryManager / telemetry.get_registry() so the "
                "registry, exporters, and cross-rank aggregation see it",
            )
        elif (
            isinstance(f, ast.Name) and f.id == "SummaryWriter"
        ) or (
            isinstance(f, ast.Attribute) and f.attr == "SummaryWriter"
        ):
            yield make_finding(
                rule, ctx, node,
                "hand-built SummaryWriter outside the telemetry plane — the "
                "TensorBoard monitor is a telemetry sink; attach it via the "
                "manager instead of writing events directly",
            )
