"""Rule: float64-promotion — accidental double precision.

TPUs have no fast f64 path and this repo runs with x64 disabled, so an
explicit float64 request either silently becomes f32 (misleading) or —
with x64 on — drags a 2x-memory, many-times-slower dtype through the
whole program via promotion.  ``dtype=float`` and ``.astype(float)``
are the sneaky spellings: Python's ``float`` *is* float64.
"""
from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import Severity, make_finding, register

_F64_ATTRS = {"jax.numpy.float64", "numpy.float64", "jax.numpy.complex128", "numpy.complex128"}


def _is_f64_node(ctx, node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value in ("float64", "double", "complex128"):
        return True
    if isinstance(node, ast.Name) and node.id == "float" and "float" not in ctx.aliases:
        return True
    resolved = ctx.resolve(node)
    return resolved in _F64_ATTRS


@register(
    "float64-promotion",
    Severity.B,
    "explicit float64 dtype in jax/jnp code: silently downcast with x64 off, slow with it on",
)
def check(rule, ctx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = ctx.resolve(node.func) or ""
        # jnp.<ctor>(..., dtype=float64-ish) and jnp.zeros(..., float) etc.
        if resolved.startswith("jax.numpy.") or resolved.startswith("jax."):
            for kw in node.keywords:
                if kw.arg == "dtype" and _is_f64_node(ctx, kw.value):
                    yield make_finding(
                        rule, ctx, kw.value,
                        f"dtype float64 passed to {resolved}; use jnp.float32/bfloat16 "
                        "(x64 is disabled on the TPU path)",
                    )
        # x.astype(float) / x.astype("float64") / x.astype(jnp.float64)
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and node.args
            and _is_f64_node(ctx, node.args[0])
        ):
            yield make_finding(
                rule, ctx, node.args[0],
                ".astype(float64) promotes to double precision; use float32/bfloat16",
            )
