"""Rule: raw ``lax`` collectives belong in the comm layer.

Every collective exchange is a wire-strategy decision (dense vs
int8-quantized vs error-feedback compressed; docs/comm.md) and a
comm-bytes accounting site.  A bare ``jax.lax.psum`` /
``psum_scatter`` / ``all_gather`` / ``all_to_all`` / ``ppermute`` call
outside ``deepspeed_tpu/comm/`` bypasses both: it hard-codes the dense
path and is invisible to the strategy table and the per-step byte
model.  Route through :mod:`deepspeed_tpu.comm.collectives` (same
primitives, one import away) or :class:`deepspeed_tpu.comm.strategy.CommLayer`.

Grandfathered call sites (the ring-attention internals in
``parallel/sequence.py``, whose ppermute schedule IS the algorithm)
live in the baseline; new sites are tier-B findings.
"""
from __future__ import annotations

import ast
import os

from deepspeed_tpu.analysis.core import Severity, make_finding, register

_RAW_COLLECTIVES = {"psum", "pmean", "psum_scatter", "all_gather", "all_to_all", "ppermute"}
# the comm package is the sanctioned home of raw collective call sites
_EXEMPT_DIR = "deepspeed_tpu/comm/"


def _is_lax_collective(node: ast.Call):
    """Match ``lax.X(...)`` / ``jax.lax.X(...)`` for X in the raw set."""
    f = node.func
    if not isinstance(f, ast.Attribute) or f.attr not in _RAW_COLLECTIVES:
        return None
    v = f.value
    if isinstance(v, ast.Name) and v.id == "lax":
        return f.attr
    if isinstance(v, ast.Attribute) and v.attr == "lax":
        return f.attr
    return None


@register(
    "raw-collective-outside-comm-layer",
    Severity.B,
    "direct lax.psum/psum_scatter/all_gather/all_to_all/ppermute call site "
    "outside deepspeed_tpu/comm/ — route through comm.collectives / "
    "comm.strategy.CommLayer for strategy selection and byte accounting",
)
def check_raw_collective(rule, ctx):
    path = os.path.normpath(ctx.path).replace(os.sep, "/")
    if _EXEMPT_DIR in path:
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = _is_lax_collective(node)
            if name is not None:
                yield make_finding(
                    rule, ctx, node,
                    f"raw 'lax.{name}' outside the comm layer — this exchange is "
                    "invisible to the strategy table and the comm-bytes model; use "
                    "deepspeed_tpu.comm.collectives (or CommLayer) instead",
                )
