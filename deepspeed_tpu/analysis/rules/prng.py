"""Rule: prng-key-reuse — the same PRNG key fed to multiple samplers.

``jax.random`` is splittable-by-contract: reusing one key in two draws
yields correlated (often identical) streams — the training-run
equivalent of seeding dropout and init with the same bits.  Flagged per
function: a key variable consumed by ≥2 sampler calls with no
``split``/``fold_in`` of that key anywhere in the function.
"""
from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import Severity, make_finding, register
from deepspeed_tpu.analysis.traced import FunctionNode

_NON_SAMPLERS = {"split", "fold_in", "PRNGKey", "key", "wrap_key_data", "key_data", "clone"}


@register(
    "prng-key-reuse",
    Severity.B,
    "one PRNG key consumed by multiple jax.random draws without split/fold_in",
)
def check(rule, ctx):
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, FunctionNode):
            continue
        key_vars = set()
        split_vars = set()
        uses = {}  # var -> [call nodes in source order]
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                resolved = ctx.resolve(node.value.func) or ""
                if resolved in ("jax.random.PRNGKey", "jax.random.key"):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            key_vars.add(tgt.id)
            if isinstance(node, ast.Call):
                resolved = ctx.resolve(node.func) or ""
                if not resolved.startswith("jax.random."):
                    continue
                tail = resolved.split(".")[-1]
                args = [a for a in node.args if isinstance(a, ast.Name)]
                if tail in ("split", "fold_in"):
                    for a in args:
                        split_vars.add(a.id)
                elif tail not in _NON_SAMPLERS:
                    if node.args and isinstance(node.args[0], ast.Name):
                        uses.setdefault(node.args[0].id, []).append(node)
        for var, calls in uses.items():
            if var in key_vars and var not in split_vars and len(calls) >= 2:
                for call in sorted(calls, key=lambda c: (c.lineno, c.col_offset))[1:]:
                    yield make_finding(
                        rule, ctx, call,
                        f"PRNG key '{var}' already consumed by an earlier draw in "
                        f"'{fn.name}'; jax.random.split it so the streams are independent",
                    )
