"""Sharding rules.

``missing-sharding-constraint`` — unpinned collective outputs.  In
``comm/`` and ``runtime/zero/``, a function that issues collectives
(psum / all_gather / ppermute ...) but never mentions a sharding
construct leaves the result layout to XLA's propagation pass; under
GSPMD that is exactly where weight-update sharding (arXiv:2004.13336)
silently degrades to replication.  Tier C: advice, not a gate — inside
``shard_map`` bodies the layout is pinned by the enclosing specs, which
the lexical check can only see when they share a file.  The
partition-rule engine's constructors (``dp_rows_spec`` & co.) count as
markers: resolving through the rule engine IS pinning the layout.

``hand-built-partition-spec`` — the partition-rule engine
(deepspeed_tpu/sharding/) is the single home of axis-name layout
decisions; a ``PartitionSpec`` / ``P`` construction naming a mesh axis
as a string literal anywhere else re-wires the layout by hand, invisible
to the rule tables, the ZeRO layer, and the sharding-drift checker.
Tier B.  Empty / all-``None`` specs (replicated) and specs built from
variables (spec plumbing) are fine.
"""
from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import Severity, make_finding, register
from deepspeed_tpu.analysis.traced import FunctionNode

_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "axis_index",
}
_SHARDING_MARKERS = {
    "with_sharding_constraint", "NamedSharding", "PartitionSpec", "shard_map",
    # partition-rule-engine constructors (deepspeed_tpu/sharding/): a
    # layout resolved through the rule engine is a pinned layout
    "dp_rows_spec", "batch_pspec", "replicated_pspec", "stacked_batch_pspec",
    "stacked_micro_batch_pspec", "fsdp_trailing_spec", "batch_sharding",
    "replicated_sharding", "SpecLayout", "PartitionRules", "match_partition_rules",
}
_PATH_SEGMENTS = ("comm/", "zero/")


def _applies(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(seg in p for seg in _PATH_SEGMENTS)


@register(
    "missing-sharding-constraint",
    Severity.C,
    "collective-issuing function in comm//zero/ with no sharding annotation in sight",
)
def check(rule, ctx):
    if not _applies(ctx.path):
        return
    # File-wide marker scan: a module whose jit entry points pin layouts
    # usually does so near the collectives; one marker clears the file's
    # helper functions too (lexical heuristic, tier C).
    file_has_marker = any(
        isinstance(n, (ast.Name, ast.Attribute))
        and (getattr(n, "id", None) or getattr(n, "attr", None)) in _SHARDING_MARKERS
        for n in ast.walk(ctx.tree)
    )
    if file_has_marker:
        return
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, FunctionNode):
            continue
        collectives = [
            node
            for node in ast.walk(fn)
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _COLLECTIVES
        ]
        if collectives:
            yield make_finding(
                rule, ctx, fn,
                f"'{fn.name}' issues {len(collectives)} collective(s) but the module "
                "never pins a layout (with_sharding_constraint / NamedSharding / "
                "shard_map); XLA propagation decides the output sharding",
            )


# ---------------------------------------------------------------------------
# hand-built-partition-spec
# ---------------------------------------------------------------------------

# the framework mesh axes (sharding/mesh.py MESH_AXES) — a spec literal
# naming one of these is a layout decision
_MESH_AXIS_NAMES = {"pipe", "data", "fsdp", "seq", "model", "expert"}
# the rule engine is the sanctioned home of axis-literal spec construction
_SPEC_EXEMPT_DIR = "deepspeed_tpu/sharding/"


def _is_pspec_ctor(node: ast.Call) -> bool:
    f = node.func
    name = getattr(f, "id", None) or getattr(f, "attr", None)
    return name in ("P", "PartitionSpec")


def _literal_axes(node: ast.Call):
    """Mesh-axis string literals passed (possibly inside tuples) to a
    PartitionSpec constructor."""
    found = []
    for arg in node.args:
        elts = arg.elts if isinstance(arg, ast.Tuple) else [arg]
        for e in elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str) and e.value in _MESH_AXIS_NAMES:
                found.append(e.value)
    return found


@register(
    "hand-built-partition-spec",
    Severity.B,
    "PartitionSpec built from mesh-axis string literals outside "
    "deepspeed_tpu/sharding/ — resolve layouts through the partition-rule "
    "engine (sharding.rules / sharding.layout) instead",
)
def check_hand_built_spec(rule, ctx):
    import os

    path = os.path.normpath(ctx.path).replace(os.sep, "/")
    if _SPEC_EXEMPT_DIR in path:
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _is_pspec_ctor(node):
            axes = _literal_axes(node)
            if axes:
                yield make_finding(
                    rule, ctx, node,
                    f"hand-built PartitionSpec names mesh axis literal(s) "
                    f"{sorted(set(axes))} outside the partition-rule engine — "
                    "every engine must resolve layouts through "
                    "deepspeed_tpu.sharding (rule tables / SpecLayout helpers)",
                )
