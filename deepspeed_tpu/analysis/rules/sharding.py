"""Rule: missing-sharding-constraint — unpinned collective outputs.

In ``comm/`` and ``runtime/zero/``, a function that issues collectives
(psum / all_gather / ppermute ...) but never mentions a sharding
construct leaves the result layout to XLA's propagation pass; under
GSPMD that is exactly where weight-update sharding (arXiv:2004.13336)
silently degrades to replication.  Tier C: advice, not a gate — inside
``shard_map`` bodies the layout is pinned by the enclosing specs, which
the lexical check can only see when they share a file.
"""
from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import Severity, make_finding, register
from deepspeed_tpu.analysis.traced import FunctionNode

_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "axis_index",
}
_SHARDING_MARKERS = {
    "with_sharding_constraint", "NamedSharding", "PartitionSpec", "shard_map",
}
_PATH_SEGMENTS = ("comm/", "zero/")


def _applies(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(seg in p for seg in _PATH_SEGMENTS)


@register(
    "missing-sharding-constraint",
    Severity.C,
    "collective-issuing function in comm//zero/ with no sharding annotation in sight",
)
def check(rule, ctx):
    if not _applies(ctx.path):
        return
    # File-wide marker scan: a module whose jit entry points pin layouts
    # usually does so near the collectives; one marker clears the file's
    # helper functions too (lexical heuristic, tier C).
    file_has_marker = any(
        isinstance(n, (ast.Name, ast.Attribute))
        and (getattr(n, "id", None) or getattr(n, "attr", None)) in _SHARDING_MARKERS
        for n in ast.walk(ctx.tree)
    )
    if file_has_marker:
        return
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, FunctionNode):
            continue
        collectives = [
            node
            for node in ast.walk(fn)
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _COLLECTIVES
        ]
        if collectives:
            yield make_finding(
                rule, ctx, fn,
                f"'{fn.name}' issues {len(collectives)} collective(s) but the module "
                "never pins a layout (with_sharding_constraint / NamedSharding / "
                "shard_map); XLA propagation decides the output sharding",
            )
