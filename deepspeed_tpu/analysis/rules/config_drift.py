"""Rule: config-key-drift — constants table vs accessor drift.

``config/constants.py`` is the single source of truth for the JSON key
surface; ``config/config.py`` is supposed to consume it via ``C.KEY``.
Two drift modes, mirroring how the reference repo rotted:

* tier A: ``C.SOMETHING`` referenced by an accessor but absent from the
  constants module — an AttributeError waiting for that config path;
* tier B: a string literal key in an accessor (``_pop(d, "stage")``)
  that duplicates an existing constant's value — the two copies will
  eventually disagree.

Project-scope: only fires when both files are inside the linted tree.
"""
from __future__ import annotations

import ast
from typing import Dict

from deepspeed_tpu.analysis.core import Finding, Severity, register

_ACCESSOR_FUNCS = {"_pop", "get", "pop"}


def _constants_table(fc):
    """(all module-level names, name -> string-value for str constants)."""
    names = set()
    strings: Dict[str, str] = {}
    for node in fc.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
                    if isinstance(node.value, ast.Constant) and isinstance(node.value.value, str):
                        strings[tgt.id] = node.value.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            names.add(node.target.id)
    return names, strings


@register(
    "config-key-drift",
    Severity.A,
    "config accessors drifting from config/constants.py (missing constant or duplicated literal)",
    scope="project",
)
def check(rule, project):
    constants_fc = project.find("config/constants.py")
    config_fc = project.find("config/config.py")
    if constants_fc is None or config_fc is None:
        return
    names, strings = _constants_table(constants_fc)
    # A literal is only "drift" when exactly one constant owns that value;
    # generic sub-keys like "enabled" (FP16_ENABLED == BF16_ENABLED == ...)
    # are ambiguous, not drifted.
    value_owners: Dict[str, list] = {}
    for name, value in strings.items():
        value_owners.setdefault(value, []).append(name)
    value_to_name = {v: owners[0] for v, owners in value_owners.items() if len(owners) == 1}

    # alias(es) under which the constants module is imported in config.py
    const_aliases = {
        alias
        for alias, target in config_fc.aliases.items()
        if target.split(".")[-1] == "constants" or target.endswith(".constants")
    }

    for node in ast.walk(config_fc.tree):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in const_aliases
            and node.attr not in names
            and node.attr.isupper()
        ):
            yield Finding(
                rule=rule.id, path=config_fc.path, line=node.lineno,
                col=node.col_offset + 1, severity=Severity.A,
                message=f"{node.value.id}.{node.attr} is not defined in "
                f"{constants_fc.path} (AttributeError on this config path)",
            )
        elif isinstance(node, ast.Call):
            fname = None
            if isinstance(node.func, ast.Name):
                fname = node.func.id
            elif isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            if fname not in _ACCESSOR_FUNCS:
                continue
            # _pop(d, "key", ...) / d.get("key", ...) — key is arg 1 or 0.
            key_idx = 1 if isinstance(node.func, ast.Name) else 0
            if key_idx < len(node.args):
                key = node.args[key_idx]
                if (
                    isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and key.value in value_to_name
                ):
                    yield Finding(
                        rule=rule.id, path=config_fc.path, line=key.lineno,
                        col=key.col_offset + 1, severity=Severity.B,
                        message=f"literal {key.value!r} duplicates constants."
                        f"{value_to_name[key.value]}; use the constant so the key "
                        "surface has one source of truth",
                    )
