"""Rule: unfenced-timing — wall-clock deltas around jitted calls with no
device fence between them.

XLA dispatch is asynchronous: a compiled call returns the moment the
work is *enqueued*.  ``t0 = time.perf_counter(); step(x); dt =
time.perf_counter() - t0`` therefore measures Python dispatch overhead,
not the step — numbers that look 10-100x too good and silently steer
optimization work at nothing.  Honest timing blocks on the result
(``jax.block_until_ready``, ``device_get``, ``float(loss)``, ...)
before reading the second clock; the engine's ``StepTimeline`` and
``SynchronizedWallClockTimer`` both fence this way.

Detection (lexical, per function): a clock read assigned to a name, a
later ``<clock>() - name`` delta, and — in the statement window between
the two — a call recognizably dispatching compiled work (a call to a
``jax.jit``/AOT-compiled callable bound in this module, a direct
``jax.jit(f)(...)``, a function this module passes to a trace
transform, or the engine's compiled-step entry points) with no fencing
call anywhere in the window.  Tier C: timings lie quietly; the code
still runs.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from deepspeed_tpu.analysis.core import Severity, make_finding, register
from deepspeed_tpu.analysis.traced import FunctionNode, collect_functions, iter_own_nodes

_CLOCKS = {"time.time", "time.perf_counter", "time.monotonic"}
# resolved suffixes that block on device work before the second clock read
_FENCE_SUFFIXES = ("block_until_ready", "device_get", "wait_until_finished")
_FENCE_METHODS = {"block_until_ready", "item", "tolist", "wait_until_finished"}
_FENCE_CASTS = {"float", "int", "bool"}
_FENCE_NP = {"numpy.asarray", "numpy.array"}
# engine entry points that run a compiled step (host-side API; the
# callee body lives in another module, out of lexical reach)
_DISPATCH_METHODS = {"train_batch", "train_batches", "eval_batch", "predict"}


def _is_clock_call(ctx, node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and not node.args
        and not node.keywords
        and ctx.resolve(node.func) in _CLOCKS
    )


def _jit_factory(ctx, value: ast.AST) -> bool:
    """Does this assigned value produce a compiled callable?  Covers
    ``jax.jit(...)``/``pjit(...)``, ``self._get_compiled(...)``, and AOT
    ``....lower(...).compile()`` chains."""
    if not isinstance(value, ast.Call):
        return False
    resolved = ctx.resolve(value.func) or ""
    last = resolved.split(".")[-1]
    if last in ("jit", "pjit", "_get_compiled"):
        return True
    # ``jax.jit(f).lower(args).compile()``
    return last == "compile" and isinstance(value.func, ast.Attribute)


@register(
    "unfenced-timing",
    Severity.C,
    "time.time()/perf_counter() delta around a jitted call with no "
    "block_until_ready (async dispatch makes the measurement a lie)",
)
def check(rule, ctx):
    traced_ids = ctx.traced_functions()
    # names this module binds to compiled callables or passes to a trace
    # transform — a call to one of these dispatches device work
    jitted_names: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = getattr(node, "value", None)
            if value is not None and _jit_factory(ctx, value):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        jitted_names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        jitted_names.add(t.attr)
    for fn in collect_functions(ctx.tree):
        if id(fn) in traced_ids:
            jitted_names.add(fn.name)

    def dispatches(call: ast.Call) -> bool:
        func = call.func
        if isinstance(func, ast.Name) and func.id in jitted_names:
            return True
        if isinstance(func, ast.Attribute) and func.attr in (jitted_names | _DISPATCH_METHODS):
            return True
        # direct jax.jit(f)(x) / compiled-dict lookups self._compiled[...](x)
        if isinstance(func, ast.Call) and _jit_factory(ctx, func):
            return True
        return isinstance(func, ast.Subscript) and ctx.resolve(func.value) is not None and (
            ctx.resolve(func.value) or ""
        ).endswith("_compiled")

    def fences(call: ast.Call) -> bool:
        resolved = ctx.resolve(call.func) or ""
        if resolved.endswith(_FENCE_SUFFIXES) or resolved in _FENCE_NP:
            return True
        if isinstance(call.func, ast.Attribute) and call.func.attr in _FENCE_METHODS:
            return True
        return (
            isinstance(call.func, ast.Name)
            and call.func.id in _FENCE_CASTS
            and call.func.id == ctx.aliases.get(call.func.id, call.func.id)
            and len(call.args) == 1
            and not isinstance(call.args[0], ast.Constant)
        )

    for fn in collect_functions(ctx.tree):
        if id(fn) in traced_ids:
            continue  # inside a trace this is host-sync-in-jit territory
        # two passes: iter_own_nodes walks a stack, not source order, so
        # starts must be fully known before deltas are matched
        starts: Dict[str, int] = {}
        calls: List[ast.Call] = []
        own = list(iter_own_nodes(fn))
        for node in own:
            if isinstance(node, ast.Assign) and _is_clock_call(ctx, node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        starts[t.id] = node.lineno
            elif isinstance(node, ast.Call):
                calls.append(node)
        deltas: List = []  # (delta_node, start_line)
        for node in own:
            if (
                isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Sub)
                and _is_clock_call(ctx, node.left)
                and isinstance(node.right, ast.Name)
                and node.right.id in starts
                and node.lineno > starts[node.right.id]
            ):
                deltas.append((node, starts[node.right.id]))
        for delta, start_line in deltas:
            window = [c for c in calls if start_line <= c.lineno <= delta.lineno]
            if any(dispatches(c) for c in window) and not any(fences(c) for c in window):
                yield make_finding(
                    rule, ctx, delta,
                    f"wall-clock delta in '{fn.name}' spans a jitted call with no "
                    "block_until_ready/device_get fence — async dispatch means this "
                    "measures Python overhead, not the compiled step",
                )
