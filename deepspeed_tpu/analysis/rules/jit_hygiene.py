"""Rules: jit call hygiene — mesh scoping and compile-cache discipline.

* bare-jit: library code should wrap step functions with the engine's
  mesh scoping (``self._scoped`` / ``scoped_to``) or pass explicit
  shardings, so the ambient mesh governs layout instead of whatever XLA
  guesses — a GSPMD prerequisite (arXiv:2004.13336 relies on every
  update being annotation-driven).
* jit-in-loop: ``jax.jit(...)`` in a loop body builds a fresh callable
  (and hashes a fresh cache key) per iteration; hoist it or cache it the
  way runtime/engine.py:_get_compiled does.
"""
from __future__ import annotations

import ast
from typing import List

from deepspeed_tpu.analysis.core import Severity, make_finding, register
from deepspeed_tpu.analysis.rules.static_args import _is_jit_call

_SCOPED_WRAPPERS = {"_scoped", "scoped_to"}
_SHARDING_KWARGS = {"in_shardings", "out_shardings", "in_axis_resources", "out_axis_resources"}


@register(
    "bare-jit",
    Severity.B,
    "jax.jit without mesh scoping (scoped wrapper or explicit in_/out_shardings)",
)
def check_bare_jit(rule, ctx):
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and _is_jit_call(ctx, node)):
            continue
        if any(kw.arg in _SHARDING_KWARGS for kw in node.keywords):
            continue
        target = node.args[0] if node.args else None
        if isinstance(target, ast.Call):
            fname = None
            if isinstance(target.func, ast.Name):
                fname = target.func.id
            elif isinstance(target.func, ast.Attribute):
                fname = target.func.attr
            if fname in _SCOPED_WRAPPERS:
                continue
        yield make_finding(
            rule, ctx, node,
            "bare jax.jit: wrap the function with the mesh-scoped helper "
            "(self._scoped / scoped_to) or pass explicit in_/out_shardings so "
            "GSPMD sees the intended layout",
        )


def _collect_loop_nodes(node: ast.AST, in_loop: bool, out: List[ast.AST]) -> None:
    """Collect nodes lexically inside a loop body.  Nested function defs
    reset the loop context (they may run outside the loop); loops set it;
    comprehensions don't count (building a list of jitted fns once is a
    legitimate pattern)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            _collect_loop_nodes(child, False, out)
        elif isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
            _collect_loop_nodes(child, True, out)
        else:
            if in_loop:
                out.append(child)
            _collect_loop_nodes(child, in_loop, out)


@register(
    "jit-in-loop",
    Severity.B,
    "jax.jit called inside a loop body: re-wraps (and can re-trace) every iteration",
)
def check_jit_in_loop(rule, ctx):
    nodes: List[ast.AST] = []
    _collect_loop_nodes(ctx.tree, False, nodes)
    for node in nodes:
        if isinstance(node, ast.Call) and _is_jit_call(ctx, node):
            yield make_finding(
                rule, ctx, node,
                "jax.jit inside a loop body builds a new wrapper every iteration; "
                "hoist it out of the loop or cache it (cf. engine._get_compiled)",
            )
