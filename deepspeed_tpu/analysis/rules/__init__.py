"""Built-in rule modules.  Importing this package registers every rule
with the core registry (deepspeed_tpu.analysis.core)."""
from deepspeed_tpu.analysis.rules import (  # noqa: F401
    atomic_write,
    barrier_guard,
    config_drift,
    donation,
    dtype_rules,
    host_sync,
    jit_hygiene,
    pallas_seam,
    prng,
    raw_collective,
    raw_metric,
    sharding,
    side_effects,
    static_args,
    timing,
)
