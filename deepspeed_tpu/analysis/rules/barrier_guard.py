"""Rule: blocking cross-process syncs must sit inside a watchdog-armed
region.

A bare ``sync_global_devices`` / ``process_allgather`` /
``broadcast_one_to_all`` at a checkpoint or step boundary is an eternal
hang the moment one peer dies — the exact failure the supervision
subsystem exists to bound (docs/resilience.md).  The sanctioned shapes:

* ``with supervisor.armed("site"): multihost_utils.sync_global_devices(...)``
  (any ``.armed(...)`` / ``._sup_region(...)`` context manager item);
* routing through :func:`deepspeed_tpu.resilience.supervision.supervised_sync`
  (the helper arms itself — its own body is exempt);
* a function whose name starts with ``supervised_`` (wrapper modules).

Everything else is a tier-B finding; pre-supervision sites live in the
baseline.
"""
from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import Severity, make_finding, register

_BLOCKING_SYNCS = {
    "sync_global_devices",
    "process_allgather",
    "broadcast_one_to_all",
    # the comm layer's host-side allgather wrapper blocks exactly like
    # the process_allgather it wraps — routing through comm/collectives
    # must not hide the site from this rule
    "host_allgather",
}
_GUARD_ATTRS = {"armed", "_sup_region"}
_EXEMPT_FUNC_PREFIX = "supervised_"


def _call_name(node: ast.Call):
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _with_is_guard(node: ast.With) -> bool:
    """Any item of the ``with`` whose expression mentions an armed-region
    call — including conditional forms like
    ``sup.armed(x) if sup else nullcontext()``."""
    for item in node.items:
        for sub in ast.walk(item.context_expr):
            if isinstance(sub, ast.Call):
                name = _call_name(sub)
                if name in _GUARD_ATTRS:
                    return True
    return False


@register(
    "unguarded-collective-barrier",
    Severity.B,
    "blocking cross-process sync outside a watchdog-armed region; wrap in "
    "supervisor.armed(...) or route through supervision.supervised_sync",
)
def check_barrier_guard(rule, ctx):
    # walk with an explicit stack so each call site knows its enclosing
    # With guards and function names
    def visit(node, guarded: bool, func_exempt: bool):
        if isinstance(node, ast.With):
            guarded = guarded or _with_is_guard(node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func_exempt = node.name.startswith(_EXEMPT_FUNC_PREFIX)
            guarded = False  # a guard outside the def does not cover calls at call time
        elif isinstance(node, ast.Call):
            name = _call_name(node)
            if name in _BLOCKING_SYNCS and not guarded and not func_exempt:
                yield make_finding(
                    rule, ctx, node,
                    f"'{name}' blocks on every peer with no armed deadline — one dead "
                    "rank hangs this site forever; wrap it in supervisor.armed(...) "
                    "or use supervision.supervised_sync",
                )
        for child in ast.iter_child_nodes(node):
            yield from visit(child, guarded, func_exempt)

    yield from visit(ctx.tree, guarded=False, func_exempt=False)
