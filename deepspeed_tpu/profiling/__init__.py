from deepspeed_tpu.profiling.flops_profiler import (
    FlopsProfiler,
    analyze_fn,
    get_model_profile,
    see_memory_usage,
)
