"""FLOPs profiler.

Reference: ``profiling/flops_profiler/profiler.py`` (``FlopsProfiler``
:11, standalone ``get_model_profile`` :888) — monkey-patches
``torch.nn.functional`` and hangs module hooks to count MACs/params/
latency per module.

TPU-native re-design (SURVEY §5.1): XLA already knows the cost of the
compiled program — ``jitted.lower().compile().cost_analysis()`` returns
exact flops/bytes for the *fused* computation, which is more truthful
than functional-patch counting (it sees rematerialization, fused
epilogues, and the backward pass).  The profiler therefore:

* profiles any jittable ``fn(*args)`` via AOT lowering (no execution
  needed for the static numbers);

  CAVEAT: XLA cost analysis counts a ``lax.scan`` body ONCE, not per
  trip — models that scan over layers (models/gpt2.py) or engines that
  scan over micro-batches under-report flops by that factor.  For MFU
  use an analytic count (bench.py does: flops/token ≈ 6N + attention),
  or unroll the scan for profiling;
* measures wall clock around real calls for achieved FLOPS / MFU against
  a configurable peak;
* integrates with the engine: ``profile_step`` triggers a one-shot
  report of the compiled train step (config block ``flops_profiler``,
  reference ``profiling/config.py:49``).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger

# bf16 peak TFLOPS per chip for MFU math; overridable per call.
PEAK_TFLOPS_BY_PLATFORM = {
    "tpu": 197.0,   # v5e bf16 (BASELINE hardware)
    "cpu": 0.5,     # so CPU-mesh tests produce sane (small) MFU numbers
    "gpu": 312.0,   # A100 bf16, for completeness
}

# peak HBM GB/s per chip — the roofline denominator that pairs with the
# table above (machine balance = peak flops / peak bytes; the attribution
# module's compute- vs memory-bound verdicts key on it).
PEAK_HBM_GBPS_BY_PLATFORM = {
    "tpu": 819.0,   # v5e HBM2
    # 0.5 TFLOPS / 100 GB/s → machine balance 5 flops/byte: far enough
    # from both the dryrun train matmuls (AI ~10) and the decode
    # matvecs (AI ~1) that the pinned roofline verdicts are stable
    "cpu": 100.0,
    "gpu": 2039.0,  # A100 80GB
}


def _num_params(tree: Any) -> int:
    return sum(int(np.prod(np.shape(p))) for p in jax.tree.leaves(tree))


def cost_bytes(cost: Optional[Dict[str, float]]) -> float:
    """HBM bytes from a ``cost_analysis()`` dict — one home for the
    'bytes accessed' vs 'bytes_accessed' key-spelling difference across
    jaxlib versions."""
    cost = cost or {}
    return float(cost.get("bytes accessed", cost.get("bytes_accessed", 0.0)))


def peak_flops(backend: Optional[str] = None, n_devices: int = 1) -> float:
    """bf16 peak FLOP/s for the MFU denominator.  Defaults to ONE
    chip's peak: XLA ``cost_analysis()`` reports the *partitioned*
    (per-device) module, so per-device flops over per-chip peak is the
    correct MFU (verified against the analytic 6N+attention count on
    the 8-device dryrun, within 10%; tests/test_telemetry.py pins it)."""
    backend = backend or jax.default_backend()
    return PEAK_TFLOPS_BY_PLATFORM.get(backend, 100.0) * 1e12 * max(1, int(n_devices))


def peak_hbm_bytes_per_s(backend: Optional[str] = None) -> float:
    """Peak HBM bytes/s for ONE chip — the roofline bandwidth ceiling
    (per-device, matching :func:`peak_flops`)."""
    backend = backend or jax.default_backend()
    return PEAK_HBM_GBPS_BY_PLATFORM.get(backend, 100.0) * 1e9


def derive_step_stats(
    cost: Optional[Dict[str, float]],
    wall_s: float,
    backend: Optional[str] = None,
) -> Dict[str, float]:
    """The one MFU/HBM derivation (shared by the profiler, the engine's
    telemetry gauges, and bench records): compiled-cost FLOPs and bytes
    over a measured step wall against the PER-CHIP peak.

    ``cost`` is the executable's ``cost_analysis()`` dict — the
    **per-device** flops/bytes of the GSPMD-partitioned module, which is
    why the denominator is one chip's peak.  NB the module-level scan
    caveat applies: a ``lax.scan`` body is counted ONCE — profile with
    the scan unrolled (bench.py's headline config does) for truthful
    absolute numbers."""
    cost = cost or {}
    flops = float(cost.get("flops", 0.0))
    hbm = cost_bytes(cost)
    peak = peak_flops(backend)
    achieved = flops / wall_s if wall_s and wall_s > 0 else float("nan")
    return {
        "flops_per_step": flops,
        "hbm_bytes_per_step": hbm,
        "achieved_flops": achieved,
        "mfu": achieved / peak if peak else float("nan"),
        "hbm_gbps": hbm / wall_s / 1e9 if wall_s and wall_s > 0 else float("nan"),
    }


def _fmt(n: float, unit: str = "") -> str:
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(n) >= scale:
            return f"{n / scale:.2f} {suffix}{unit}"
    return f"{n:.2f} {unit}"


def analyze_fn(fn: Callable, *args, static_argnums=()) -> Dict[str, float]:
    """AOT cost analysis of ``fn(*args)``: flops, HBM bytes accessed,
    peak-memory estimate — from XLA, post-fusion."""
    # out_shardings=None: AOT cost analysis only — nothing executes, so
    # no layout is imposed on real arrays
    lowered = jax.jit(fn, static_argnums=static_argnums, out_shardings=None).lower(*args)
    compiled = lowered.compile()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    mem = compiled.memory_analysis()
    out = {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": cost_bytes(cost),
        "peak_memory_bytes": float(getattr(mem, "temp_size_in_bytes", 0) or 0)
        + float(getattr(mem, "argument_size_in_bytes", 0) or 0),
    }
    return out


def get_model_profile(
    model_fn: Callable,
    args: Tuple = (),
    kwargs: Optional[dict] = None,
    print_profile: bool = True,
    detailed: bool = True,
    warm_up: int = 1,
    as_string: bool = False,
    params: Any = None,
) -> Tuple[Any, Any, Any]:
    """Reference ``get_model_profile`` (:888): returns
    ``(flops, macs, params)`` for one forward call.  MACs are flops/2
    (XLA counts multiply and add separately)."""
    kwargs = kwargs or {}
    cost = analyze_fn(lambda *a: model_fn(*a, **kwargs), *args)
    flops = cost["flops"]
    macs = flops / 2.0
    n_params = _num_params(params) if params is not None else _num_params(args[0]) if args else 0
    if print_profile:
        logger.info(
            f"model profile: flops={_fmt(flops, 'FLOPs')} macs={_fmt(macs, 'MACs')} "
            f"params={_fmt(n_params)} bytes={_fmt(cost['bytes_accessed'], 'B')}"
        )
    if as_string:
        return _fmt(flops, "FLOPs"), _fmt(macs, "MACs"), _fmt(n_params)
    return flops, macs, n_params


class FlopsProfiler:
    """Engine-attached profiler (reference ``FlopsProfiler`` :11).

    Engine calls ``maybe_profile(step)`` each train_batch; at
    ``profile_step`` it runs cost analysis on the already-compiled step,
    times the next execution, and prints flops / throughput / MFU.
    """

    def __init__(self, config, engine=None):
        self.cfg = config
        self.engine = engine
        self._static: Optional[Dict[str, float]] = None
        self._t0: Optional[float] = None
        self.results: Dict[str, float] = {}

    @property
    def enabled(self) -> bool:
        return bool(getattr(self.cfg, "enabled", False))

    def start_step(self, step: int) -> None:
        if self.enabled and step == self.cfg.profile_step:
            self._t0 = time.perf_counter()

    def end_step(self, step: int, cost: Optional[Dict[str, float]] = None, sync_token=None) -> None:
        """Consume the compiled step's XLA cost analysis (captured by
        the engine at AOT-compile time — no recompile happens here):
        FLOPs *and* HBM bytes over the fenced latency, via the shared
        :func:`derive_step_stats` derivation.  Results land in
        ``self.results`` and, when the telemetry plane is armed, as
        ``profile/*`` registry gauges."""
        if not (self.enabled and step == self.cfg.profile_step):
            return
        if sync_token is not None:
            jax.block_until_ready(sync_token)
        elapsed = time.perf_counter() - self._t0 if self._t0 else float("nan")
        stats = derive_step_stats(cost, elapsed)
        self.results = {"step": step, "latency_s": elapsed, **stats}
        params = _num_params(self.engine.state["params"]) if self.engine is not None else 0
        self.results["params"] = params
        from deepspeed_tpu.telemetry import get_registry

        reg = get_registry()
        if reg.enabled:
            for key in ("flops_per_step", "hbm_bytes_per_step", "mfu", "hbm_gbps"):
                v = stats[key]
                if np.isfinite(v):
                    reg.gauge(f"profile/{key}").set(v)
        log_dist(
            f"flops profiler @ step {step}: params={_fmt(params)} "
            f"flops/step={_fmt(stats['flops_per_step'], 'FLOPs')} "
            f"hbm={_fmt(stats['hbm_bytes_per_step'], 'B')} "
            f"({stats['hbm_gbps']:.1f} GB/s) latency={elapsed * 1e3:.1f}ms "
            f"achieved={_fmt(stats['achieved_flops'], 'FLOPS')} "
            f"MFU={100 * stats['mfu']:.1f}%"
        )


def _live_bytes_by_device() -> Dict[int, int]:
    """Per-device live-buffer accounting from ``jax.live_arrays()`` —
    the real number on backends whose PJRT client exposes no
    ``memory_stats`` (XLA:CPU, some tunnels): sum of addressable shard
    bytes per device over every live Array."""
    out: Dict[int, int] = {}
    try:
        arrays = jax.live_arrays()
    except Exception:  # pragma: no cover - very old jax
        return out
    for a in arrays:
        try:
            for s in a.addressable_shards:
                out[s.device.id] = out.get(s.device.id, 0) + int(s.data.nbytes)
        except Exception:  # noqa: BLE001 — deleted/donated arrays mid-walk
            continue
    return out


def see_memory_usage(message: str = "", force: bool = True) -> Dict[str, float]:
    """Reference ``see_memory_usage`` (runtime/utils.py:588): device +
    host memory snapshot.  Devices report PJRT ``memory_stats`` where
    the backend has them (TPU) and fall back to live-``jax.Array``
    shard accounting (CPU and any stats-less PJRT client) — real
    numbers on every platform, never silent zeros.  Host side prefers
    psutil and falls back to ``resource.getrusage`` peak RSS."""
    out: Dict[str, float] = {}
    live: Optional[Dict[int, int]] = None
    for d in jax.local_devices():
        stats = getattr(d, "memory_stats", lambda: None)()
        if stats:
            out[f"{d.id}/bytes_in_use"] = stats.get("bytes_in_use", 0)
            out[f"{d.id}/peak_bytes_in_use"] = stats.get("peak_bytes_in_use", 0)
        else:
            if live is None:
                live = _live_bytes_by_device()
            out[f"{d.id}/bytes_in_use"] = live.get(d.id, 0)
    try:
        import psutil

        vm = psutil.virtual_memory()
        out["host/used_gb"] = vm.used / 1e9
        out["host/percent"] = vm.percent
    except ImportError:
        try:
            import resource

            # ru_maxrss is KB on Linux — peak, not current, but honest
            out["host/peak_rss_gb"] = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
        except Exception:  # pragma: no cover - non-posix
            pass
    if message or out:
        dev_in_use = sum(v for k, v in out.items() if k.endswith("/bytes_in_use"))
        host = (
            f"host={out['host/used_gb']:.1f}GB" if "host/used_gb" in out
            else f"host_peak_rss={out.get('host/peak_rss_gb', 0):.1f}GB"
            if "host/peak_rss_gb" in out else ""
        )
        logger.info(f"memory usage {message}: device={_fmt(dev_in_use, 'B')} " + host)
    return out
