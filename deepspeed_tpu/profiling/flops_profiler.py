"""FLOPs profiler.

Reference: ``profiling/flops_profiler/profiler.py`` (``FlopsProfiler``
:11, standalone ``get_model_profile`` :888) — monkey-patches
``torch.nn.functional`` and hangs module hooks to count MACs/params/
latency per module.

TPU-native re-design (SURVEY §5.1): XLA already knows the cost of the
compiled program — ``jitted.lower().compile().cost_analysis()`` returns
exact flops/bytes for the *fused* computation, which is more truthful
than functional-patch counting (it sees rematerialization, fused
epilogues, and the backward pass).  The profiler therefore:

* profiles any jittable ``fn(*args)`` via AOT lowering (no execution
  needed for the static numbers);

  CAVEAT: XLA cost analysis counts a ``lax.scan`` body ONCE, not per
  trip — models that scan over layers (models/gpt2.py) or engines that
  scan over micro-batches under-report flops by that factor.  For MFU
  use an analytic count (bench.py does: flops/token ≈ 6N + attention),
  or unroll the scan for profiling;
* measures wall clock around real calls for achieved FLOPS / MFU against
  a configurable peak;
* integrates with the engine: ``profile_step`` triggers a one-shot
  report of the compiled train step (config block ``flops_profiler``,
  reference ``profiling/config.py:49``).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger

# bf16 peak TFLOPS per chip for MFU math; overridable per call.
PEAK_TFLOPS_BY_PLATFORM = {
    "tpu": 197.0,   # v5e bf16 (BASELINE hardware)
    "cpu": 0.5,     # so CPU-mesh tests produce sane (small) MFU numbers
    "gpu": 312.0,   # A100 bf16, for completeness
}


def _num_params(tree: Any) -> int:
    return sum(int(np.prod(np.shape(p))) for p in jax.tree.leaves(tree))


def _fmt(n: float, unit: str = "") -> str:
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(n) >= scale:
            return f"{n / scale:.2f} {suffix}{unit}"
    return f"{n:.2f} {unit}"


def analyze_fn(fn: Callable, *args, static_argnums=()) -> Dict[str, float]:
    """AOT cost analysis of ``fn(*args)``: flops, HBM bytes accessed,
    peak-memory estimate — from XLA, post-fusion."""
    lowered = jax.jit(fn, static_argnums=static_argnums).lower(*args)
    compiled = lowered.compile()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    mem = compiled.memory_analysis()
    out = {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", cost.get("bytes_accessed", 0.0))),
        "peak_memory_bytes": float(getattr(mem, "temp_size_in_bytes", 0) or 0)
        + float(getattr(mem, "argument_size_in_bytes", 0) or 0),
    }
    return out


def get_model_profile(
    model_fn: Callable,
    args: Tuple = (),
    kwargs: Optional[dict] = None,
    print_profile: bool = True,
    detailed: bool = True,
    warm_up: int = 1,
    as_string: bool = False,
    params: Any = None,
) -> Tuple[Any, Any, Any]:
    """Reference ``get_model_profile`` (:888): returns
    ``(flops, macs, params)`` for one forward call.  MACs are flops/2
    (XLA counts multiply and add separately)."""
    kwargs = kwargs or {}
    cost = analyze_fn(lambda *a: model_fn(*a, **kwargs), *args)
    flops = cost["flops"]
    macs = flops / 2.0
    n_params = _num_params(params) if params is not None else _num_params(args[0]) if args else 0
    if print_profile:
        logger.info(
            f"model profile: flops={_fmt(flops, 'FLOPs')} macs={_fmt(macs, 'MACs')} "
            f"params={_fmt(n_params)} bytes={_fmt(cost['bytes_accessed'], 'B')}"
        )
    if as_string:
        return _fmt(flops, "FLOPs"), _fmt(macs, "MACs"), _fmt(n_params)
    return flops, macs, n_params


class FlopsProfiler:
    """Engine-attached profiler (reference ``FlopsProfiler`` :11).

    Engine calls ``maybe_profile(step)`` each train_batch; at
    ``profile_step`` it runs cost analysis on the already-compiled step,
    times the next execution, and prints flops / throughput / MFU.
    """

    def __init__(self, config, engine=None):
        self.cfg = config
        self.engine = engine
        self._static: Optional[Dict[str, float]] = None
        self._t0: Optional[float] = None
        self.results: Dict[str, float] = {}

    @property
    def enabled(self) -> bool:
        return bool(getattr(self.cfg, "enabled", False))

    def start_step(self, step: int) -> None:
        if self.enabled and step == self.cfg.profile_step:
            self._t0 = time.perf_counter()

    def end_step(self, step: int, cost: Optional[Dict[str, float]] = None, sync_token=None) -> None:
        """``cost``: the train step's XLA cost analysis, captured by the
        engine when it AOT-compiled the step — no recompile happens here."""
        if not (self.enabled and step == self.cfg.profile_step):
            return
        if sync_token is not None:
            jax.block_until_ready(sync_token)
        elapsed = time.perf_counter() - self._t0 if self._t0 else float("nan")
        flops = float(cost.get("flops", float("nan"))) if cost else float("nan")
        n_dev = jax.device_count()
        peak = PEAK_TFLOPS_BY_PLATFORM.get(jax.default_backend(), 100.0) * 1e12 * n_dev
        achieved = flops / elapsed if elapsed and elapsed > 0 else float("nan")
        self.results = {
            "step": step,
            "flops_per_step": flops,
            "latency_s": elapsed,
            "achieved_flops": achieved,
            "mfu": achieved / peak if peak else float("nan"),
        }
        params = _num_params(self.engine.state["params"]) if self.engine is not None else 0
        log_dist(
            f"flops profiler @ step {step}: params={_fmt(params)} "
            f"flops/step={_fmt(flops, 'FLOPs')} latency={elapsed * 1e3:.1f}ms "
            f"achieved={_fmt(achieved, 'FLOPS')} MFU={100 * self.results['mfu']:.1f}%"
        )


def see_memory_usage(message: str = "", force: bool = True) -> Dict[str, float]:
    """Reference ``see_memory_usage`` (runtime/utils.py:588): device +
    host memory snapshot, from PJRT memory stats + psutil."""
    out: Dict[str, float] = {}
    for d in jax.local_devices():
        stats = getattr(d, "memory_stats", lambda: None)()
        if stats:
            out[f"{d.id}/bytes_in_use"] = stats.get("bytes_in_use", 0)
            out[f"{d.id}/peak_bytes_in_use"] = stats.get("peak_bytes_in_use", 0)
    try:
        import psutil

        vm = psutil.virtual_memory()
        out["host/used_gb"] = vm.used / 1e9
        out["host/percent"] = vm.percent
    except ImportError:
        pass
    if message or out:
        dev_in_use = sum(v for k, v in out.items() if k.endswith("/bytes_in_use"))
        logger.info(f"memory usage {message}: device={_fmt(dev_in_use, 'B')} "
                    + (f"host={out.get('host/used_gb', 0):.1f}GB" if "host/used_gb" in out else ""))
    return out
