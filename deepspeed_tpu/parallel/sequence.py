"""Sequence / context parallelism — first-class long-context support.

The reference (v0.4.5) has **no** sequence parallelism; its long-sequence
story is block-sparse attention + activation checkpointing (SURVEY.md
§5.7).  This module provides the modern successors as first-class mesh
citizens over the ``seq`` axis:

* **Ring attention** (`ring_attention`): K/V shards rotate around the
  ring via ``lax.ppermute`` (XLA ``collective-permute`` riding ICI)
  while each device's Q shard accumulates an online softmax — exact
  attention with O(T/P) activation memory per device, comm overlapped
  with the block matmuls by XLA's async collectives.
* **Ulysses-style attention** (`ulysses_attention`): two
  ``lax.all_to_all``s swap sequence-sharding for head-sharding, run the
  (flash) attention kernel on full-length sequences for H/P heads, and
  swap back — cheaper comm than ring for moderate P (2 all-to-alls of
  the activations) but requires ``heads % P == 0``.

Both run inside ``jax.shard_map`` with *only* the ``seq`` axis manual
(``axis_names={'seq'}``) so batch / tensor-parallel sharding on the same
arrays stays GSPMD-automatic and composes with ZeRO and TP untouched.

Layout convention matches ops.attention: ``(batch, heads, seq, head_dim)``
with the **seq dim sharded** over the ``seq`` mesh axis.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.attention.flash_attention import DEFAULT_MASK_VALUE, flash_attention, mha_reference
from deepspeed_tpu.ops.registry import register_op

SEQ_AXIS = "seq"


def _axis_size(mesh, axis_name: str) -> int:
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis_name, 1)


# ---------------------------------------------------------------------------
# Ring attention (per-shard body; runs under shard_map)
# ---------------------------------------------------------------------------

def _ring_attention_sharded(q, k, v, *, axis_name: str, causal: bool, sm_scale: float):
    """Exact attention with K/V rotating around the ``axis_name`` ring.

    ``q, k, v``: local shards ``(B, H, T/P, D)``; sequence is sharded
    contiguously (shard ``r`` holds positions ``[r*T/P, (r+1)*T/P)``).
    """
    from deepspeed_tpu.comm.collectives import static_axis_size

    ring = static_axis_size(axis_name)  # version-compat lax.axis_size
    my = jax.lax.axis_index(axis_name)
    b, h, t_local, d = q.shape
    qf = q.astype(jnp.float32) * sm_scale
    q_pos = my * t_local + jnp.arange(t_local)  # global query positions
    perm = [(j, (j + 1) % ring) for j in range(ring)]

    def step(carry, i):
        k_cur, v_cur, acc, m_prev, l_prev = carry
        # Kick off the rotation *before* the block math so XLA overlaps the
        # collective-permute with the matmuls (no data dependency).
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)

        src = jnp.mod(my - i, ring)  # rank whose K/V chunk we hold at step i
        k_pos = src * t_local + jnp.arange(t_local)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32))
        if causal:
            s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, DEFAULT_MASK_VALUE)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        return (k_nxt, v_nxt, acc, m_new, l_new), None

    init = (
        k,
        v,
        jnp.zeros((b, h, t_local, d), jnp.float32),
        jnp.full((b, h, t_local, 1), -jnp.inf, jnp.float32),
        jnp.zeros((b, h, t_local, 1), jnp.float32),
    )
    # remat each ring step: backward re-runs the block math instead of
    # saving (t_local × t_local) score blocks.
    stepr = jax.checkpoint(step, prevent_cse=False)
    (k_f, v_f, acc, m, l), _ = jax.lax.scan(stepr, init, jnp.arange(ring))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (causal, early shards)
    return (acc / l).astype(q.dtype)


# ---------------------------------------------------------------------------
# Ulysses (DeepSpeed-Ulysses-style all-to-all attention)
# ---------------------------------------------------------------------------

def _ulysses_sharded(q, k, v, *, axis_name: str, causal: bool, sm_scale: float, use_flash: bool):
    """seq-sharded → head-sharded via all_to_all, full-seq attention, back."""

    def scatter_heads(x):  # (B, H, T/P, D) -> (B, H/P, T, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def gather_heads(x):  # (B, H/P, T, D) -> (B, H, T/P, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    q, k, v = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if use_flash:
        o = flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    else:
        o = mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    return gather_heads(o)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    mesh=None,
    axis_name: str = SEQ_AXIS,
) -> jnp.ndarray:
    """Ring attention over the ``seq`` mesh axis.

    Inputs are **global** arrays ``(B, H, T, D)`` (sharded or not — GSPMD
    handles movement to the required seq-sharding); output matches
    ``mha_reference`` numerics exactly.
    """
    return _seq_parallel_call(_ring_attention_sharded, q, k, v, causal, sm_scale, mesh, axis_name)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    mesh=None,
    axis_name: str = SEQ_AXIS,
    use_flash: bool = True,
) -> jnp.ndarray:
    """All-to-all (Ulysses) sequence-parallel attention over ``seq``.

    Requires ``H % seq_parallel_size == 0``.
    """
    return _seq_parallel_call(
        _ulysses_sharded, q, k, v, causal, sm_scale, mesh, axis_name, use_flash=use_flash
    )


def _seq_parallel_call(body_fn, q, k, v, causal, sm_scale, mesh, axis_name, **body_kwargs):
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    mesh = _resolve_mesh(mesh)
    ring = _axis_size(mesh, axis_name)
    use_flash = body_kwargs.get("use_flash", True)
    if ring == 1:
        if use_flash:
            return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    if q.shape[2] % ring:
        raise ValueError(f"seq len {q.shape[2]} not divisible by seq axis size {ring}")
    if body_fn is _ulysses_sharded and q.shape[1] % ring:
        raise ValueError(f"ulysses needs heads ({q.shape[1]}) divisible by seq axis ({ring})")
    body = functools.partial(
        body_fn, axis_name=axis_name, causal=causal, sm_scale=float(sm_scale), **body_kwargs
    )
    spec = P(None, None, axis_name, None)
    # version-compat shard_map (axis_names/check_vma vs auto/check_rep
    # keyword drift across the jax 0.4.x line) — same shim the pipeline
    # engine's per-stage bodies use
    from deepspeed_tpu.comm.collectives import shard_map_manual

    fn = shard_map_manual(body, mesh, in_specs=(spec, spec, spec), out_specs=spec, manual_axes={axis_name})
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Ambient mesh — models are built before the engine/mesh exists, so
# sequence-parallel attention resolves the mesh lazily at trace time.
# Each engine activates its own mesh (``ambient_mesh``) around every
# trace, so multiple engines with different meshes co-exist in one
# process (train + eval, train + inference) with no global cross-talk;
# ``set_global_mesh`` remains as a *process default* for code running
# outside any engine (tests, notebooks) and sits below the ambient mesh
# in the resolution order: explicit arg > ambient (tracing engine) >
# process default.
# ---------------------------------------------------------------------------

import contextlib
from contextvars import ContextVar

_AMBIENT_MESH: ContextVar = ContextVar("ds_tpu_ambient_mesh", default=None)
_DEFAULT_MESH = None


@contextlib.contextmanager
def ambient_mesh(mesh):
    """Activate ``mesh`` for the duration of a trace (engine-scoped)."""
    token = _AMBIENT_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _AMBIENT_MESH.reset(token)


def scoped_to(mesh, fn):
    """Wrap a to-be-traced function so lazily-resolved parallel ops
    (ring/ulysses attention, MoE expert sharding) see ``mesh`` at trace
    time.  Engine-scoped (contextvar), so engines over different meshes
    co-exist in one process — no global singleton."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with ambient_mesh(mesh):
            return fn(*args, **kwargs)

    return wrapped


def set_global_mesh(mesh) -> None:
    """Set the process-default mesh (fallback for code outside engines)."""
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh


def get_global_mesh():
    """The mesh lazily-resolved ops would use here: the tracing engine's
    ambient mesh if inside one, else the process default."""
    amb = _AMBIENT_MESH.get()
    return amb if amb is not None else _DEFAULT_MESH


def _resolve_mesh(mesh):
    if mesh is not None:
        return mesh
    resolved = get_global_mesh()
    if resolved is None:
        raise ValueError(
            "sequence-parallel attention needs a mesh: pass mesh=..., run "
            "under an engine (it scopes its mesh around every trace), or "
            "set_global_mesh(...) for standalone use"
        )
    return resolved


@register_op("ring_attention", "xla+shard_map", "Exact ring attention over the seq axis (ppermute K/V rotation)")
def _load_ring_attention():
    return ring_attention


@register_op("ulysses_attention", "xla+shard_map", "All-to-all head<->seq parallel attention")
def _load_ulysses_attention():
    return ulysses_attention
