"""Parallelism strategies beyond the core mesh (comm/mesh.py).

* ``sequence`` — ring attention + Ulysses all-to-all sequence/context
  parallelism over the ``seq`` axis (SURVEY.md §5.7's modern successor).
"""
from deepspeed_tpu.parallel.sequence import (
    ring_attention,
    ulysses_attention,
    set_global_mesh,
    get_global_mesh,
    ambient_mesh,
)

__all__ = ["ring_attention", "ulysses_attention", "set_global_mesh", "get_global_mesh", "ambient_mesh"]
