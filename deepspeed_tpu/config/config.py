"""Typed config system.

Parses the reference's JSON config surface (``runtime/config.py:655``
``DeepSpeedConfig`` and its ~80 ``get_*`` accessors, defaults in
``runtime/constants.py``) into typed dataclasses.  Differences from the
reference, per the TPU design stance (SURVEY.md §5.6):

* unknown keys raise instead of being silently ignored;
* the batch-size triad invariant (``train_batch_size = micro_batch ×
  grad_accum × dp_world_size``, reference ``config.py:736-898``) is
  auto-completed and validated identically;
* a ``mesh`` block (TPU-native extension) declares named SPMD axis sizes,
  replacing the reference's mpu/process-group plumbing.
"""
from __future__ import annotations

import dataclasses
import difflib
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

from deepspeed_tpu.config import constants as C


class DeepSpeedConfigError(Exception):
    pass


def _pop(d: Dict[str, Any], key: str, default: Any = None) -> Any:
    return d.pop(key, default)


def _pop_alias(d: Dict[str, Any], key: str, alias: str, default: Any, block: str) -> Any:
    """Pop a key that also has a reference-compat alias.  Supplying both
    spellings raises instead of silently dropping one (the module's
    unknown-keys-raise stance applies to conflicts too)."""
    if key in d and alias in d:
        raise DeepSpeedConfigError(
            f"'{block}.{key}' and its alias '{block}.{alias}' are both set; use one"
        )
    return d.pop(key, d.pop(alias, default))


def _describe_unknown(keys: Iterable[str], block: str, valid: Iterable[str]) -> str:
    """'zero_optimization.offload_param.buffer_sz' (did you mean
    'buffer_size'?), ... — full nested paths plus nearest-key hints."""
    valid = sorted(str(v) for v in valid)
    parts = []
    for key in sorted(str(k) for k in keys):
        path = f"{block}.{key}" if block else key
        close = difflib.get_close_matches(key, valid, n=1, cutoff=0.6)
        hint = f" (did you mean '{close[0]}'?)" if close else ""
        parts.append(f"'{path}'{hint}")
    return ", ".join(parts)


def _check_empty(d: Dict[str, Any], block: str, valid: Iterable[str] = ()) -> None:
    if d:
        raise DeepSpeedConfigError(
            f"Unknown config key(s): {_describe_unknown(d.keys(), block, valid)}"
        )


def _known_keys(cls, *aliases: str) -> Iterable[str]:
    """A block's accepted keys: its dataclass field names plus any
    reference-compat aliases the parser also pops."""
    return tuple(f.name for f in dataclasses.fields(cls)) + aliases


@dataclass
class OffloadDeviceConfig:
    """``zero_optimization.offload_param`` / ``offload_optimizer``
    (reference ``runtime/zero/offload_config.py``).  On TPU, ``device:
    'cpu'`` means host-resident shards (SIMD host optimizer path) and
    ``device: 'nvme'`` means the aio swapper."""

    device: str = "none"  # none | cpu | nvme
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    max_in_cpu: int = 1_000_000_000
    ratio: float = 1.0

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]], block: str) -> "OffloadDeviceConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            device=_pop(d, "device", "none"),
            nvme_path=_pop(d, "nvme_path", None),
            buffer_count=int(_pop(d, "buffer_count", 5)),
            buffer_size=int(_pop(d, "buffer_size", 100_000_000)),
            pin_memory=bool(_pop(d, "pin_memory", False)),
            pipeline_read=bool(_pop(d, "pipeline_read", False)),
            pipeline_write=bool(_pop(d, "pipeline_write", False)),
            fast_init=bool(_pop(d, "fast_init", False)),
            max_in_cpu=int(_pop(d, "max_in_cpu", 1_000_000_000)),
            ratio=float(_pop(d, "ratio", 1.0)),
        )
        _check_empty(d, block, _known_keys(cls))
        if out.device not in ("none", "cpu", "nvme"):
            raise DeepSpeedConfigError(f"{block}.device must be none|cpu|nvme, got {out.device}")
        return out

    @property
    def enabled(self) -> bool:
        return self.device != "none"


@dataclass
class ZeroConfig:
    """``zero_optimization`` block (reference ``runtime/zero/config.py:14``)."""

    stage: int = C.ZERO_STAGE_DEFAULT
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = True
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = True
    offload_param: OffloadDeviceConfig = field(default_factory=OffloadDeviceConfig)
    offload_optimizer: OffloadDeviceConfig = field(default_factory=OffloadDeviceConfig)
    sub_group_size: int = 1_000_000_000
    prefetch_bucket_size: int = 50_000_000
    param_persistence_threshold: int = 100_000
    max_live_parameters: int = 1_000_000_000
    max_reuse_distance: int = 1_000_000_000
    gather_fp16_weights_on_model_save: bool = False
    round_robin_gradients: bool = False
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    cpu_offload: bool = False  # legacy alias for offload_optimizer.device=cpu
    # cross-replica weight-update sharding (arXiv:2004.13336): at stage
    # >= 1 the optimizer state/update also shards across the pure
    # ``data`` axis — ~dp× less update FLOPs + opt-state bytes per
    # replica for one updated-params all-gather (docs/sharding.md)
    cross_replica_weight_update: bool = True

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ZeroConfig":
        if d is None:
            return cls()
        d = dict(d)
        cpu_offload = bool(_pop(d, "cpu_offload", False))
        offload_param = OffloadDeviceConfig.from_dict(_pop(d, "offload_param", None), "zero_optimization.offload_param")
        offload_optimizer = OffloadDeviceConfig.from_dict(
            _pop(d, "offload_optimizer", None), "zero_optimization.offload_optimizer"
        )
        if cpu_offload and not offload_optimizer.enabled:
            offload_optimizer = dataclasses.replace(offload_optimizer, device="cpu")
        out = cls(
            stage=int(_pop(d, C.ZERO_STAGE, C.ZERO_STAGE_DEFAULT)),
            contiguous_gradients=bool(_pop(d, "contiguous_gradients", True)),
            reduce_scatter=bool(_pop(d, "reduce_scatter", True)),
            reduce_bucket_size=int(_pop(d, "reduce_bucket_size", 500_000_000)),
            allgather_partitions=bool(_pop(d, "allgather_partitions", True)),
            allgather_bucket_size=int(_pop(d, "allgather_bucket_size", 500_000_000)),
            overlap_comm=bool(_pop(d, "overlap_comm", True)),
            load_from_fp32_weights=bool(_pop(d, "load_from_fp32_weights", True)),
            elastic_checkpoint=bool(_pop(d, "elastic_checkpoint", True)),
            offload_param=offload_param,
            offload_optimizer=offload_optimizer,
            sub_group_size=int(_pop(d, "sub_group_size", 1_000_000_000)),
            prefetch_bucket_size=int(_pop_alias(d, "stage3_prefetch_bucket_size", "prefetch_bucket_size", 50_000_000, C.ZERO_OPTIMIZATION)),
            param_persistence_threshold=int(
                _pop_alias(d, "stage3_param_persistence_threshold", "param_persistence_threshold", 100_000, C.ZERO_OPTIMIZATION)
            ),
            max_live_parameters=int(_pop_alias(d, "stage3_max_live_parameters", "max_live_parameters", 1_000_000_000, C.ZERO_OPTIMIZATION)),
            max_reuse_distance=int(_pop_alias(d, "stage3_max_reuse_distance", "max_reuse_distance", 1_000_000_000, C.ZERO_OPTIMIZATION)),
            gather_fp16_weights_on_model_save=bool(
                _pop_alias(d, "stage3_gather_fp16_weights_on_model_save", "gather_fp16_weights_on_model_save", False, C.ZERO_OPTIMIZATION)
            ),
            round_robin_gradients=bool(_pop(d, "round_robin_gradients", False)),
            ignore_unused_parameters=bool(_pop(d, "ignore_unused_parameters", True)),
            legacy_stage1=bool(_pop(d, "legacy_stage1", False)),
            cpu_offload=cpu_offload,
            cross_replica_weight_update=bool(_pop(d, "cross_replica_weight_update", True)),
        )
        _check_empty(
            d, C.ZERO_OPTIMIZATION,
            _known_keys(
                cls,
                "stage3_prefetch_bucket_size",
                "stage3_param_persistence_threshold",
                "stage3_max_live_parameters",
                "stage3_max_reuse_distance",
                "stage3_gather_fp16_weights_on_model_save",
            ),
        )
        if not (0 <= out.stage <= C.MAX_STAGE_ZERO_OPTIMIZATION):
            raise DeepSpeedConfigError(f"zero_optimization.stage must be in [0,3], got {out.stage}")
        return out


@dataclass
class Fp16Config:
    enabled: bool = C.FP16_ENABLED_DEFAULT
    loss_scale: float = C.FP16_LOSS_SCALE_DEFAULT  # 0 => dynamic
    initial_scale_power: int = C.FP16_INITIAL_SCALE_POWER_DEFAULT
    loss_scale_window: int = C.FP16_LOSS_SCALE_WINDOW_DEFAULT
    hysteresis: int = C.FP16_HYSTERESIS_DEFAULT
    min_loss_scale: float = C.FP16_MIN_LOSS_SCALE_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "Fp16Config":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_pop(d, C.FP16_ENABLED, C.FP16_ENABLED_DEFAULT)),
            loss_scale=float(_pop(d, C.FP16_LOSS_SCALE, C.FP16_LOSS_SCALE_DEFAULT)),
            initial_scale_power=int(_pop(d, C.FP16_INITIAL_SCALE_POWER, C.FP16_INITIAL_SCALE_POWER_DEFAULT)),
            loss_scale_window=int(_pop(d, C.FP16_LOSS_SCALE_WINDOW, C.FP16_LOSS_SCALE_WINDOW_DEFAULT)),
            hysteresis=int(_pop(d, C.FP16_HYSTERESIS, C.FP16_HYSTERESIS_DEFAULT)),
            min_loss_scale=float(_pop(d, C.FP16_MIN_LOSS_SCALE, C.FP16_MIN_LOSS_SCALE_DEFAULT)),
        )
        _check_empty(d, C.FP16, _known_keys(cls))
        return out

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0


@dataclass
class Bf16Config:
    enabled: bool = C.BF16_ENABLED_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "Bf16Config":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(enabled=bool(_pop(d, C.BF16_ENABLED, C.BF16_ENABLED_DEFAULT)))
        _check_empty(d, C.BF16, _known_keys(cls))
        return out


@dataclass
class OptimizerConfig:
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)
    legacy_fusion: bool = False

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "OptimizerConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            type=_pop(d, C.TYPE, None),
            params=dict(_pop(d, C.OPTIMIZER_PARAMS, {}) or {}),
            legacy_fusion=bool(_pop(d, C.LEGACY_FUSION, C.LEGACY_FUSION_DEFAULT)),
        )
        _check_empty(d, C.OPTIMIZER, _known_keys(cls))
        if out.type is not None and not isinstance(out.type, str):
            raise DeepSpeedConfigError("optimizer.type must be a string")
        return out

    @property
    def name(self) -> Optional[str]:
        return self.type.lower() if self.type else None


@dataclass
class SchedulerConfig:
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "SchedulerConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(type=_pop(d, C.TYPE, None), params=dict(_pop(d, C.SCHEDULER_PARAMS, {}) or {}))
        _check_empty(d, C.SCHEDULER, _known_keys(cls))
        return out


@dataclass
class MeshConfig:
    """TPU-native named SPMD mesh axes (SURVEY.md §2.6 TPU equivalent).

    Axis sizes; ``data`` defaults to "whatever is left" (-1).  The full
    mesh device count must equal ``jax.device_count()`` at engine init.
    """

    data: int = -1
    fsdp: int = 1
    model: int = 1  # tensor parallel (the reference's "slice parallel")
    pipe: int = 1
    seq: int = 1  # sequence/context parallel (ring attention axis)
    expert: int = 1

    AXES = ("pipe", "data", "fsdp", "seq", "model", "expert")

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "MeshConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            data=int(_pop(d, "data", -1)),
            fsdp=int(_pop(d, "fsdp", 1)),
            # the mesh AXIS named "model" (tensor parallel), unrelated to
            # serving's kv_cache_dtype="model" sentinel that shares the
            # spelling  # ds-lint: disable=config-key-drift
            model=int(_pop(d, "model", 1)),
            pipe=int(_pop(d, "pipe", 1)),
            seq=int(_pop(d, "seq", 1)),
            expert=int(_pop(d, "expert", 1)),
        )
        _check_empty(d, C.MESH, _known_keys(cls))
        return out


@dataclass
class ResilienceCheckpointConfig:
    """``resilience.checkpoint`` — durability of the checkpoint tree."""

    atomic: bool = C.CHECKPOINT_ATOMIC_DEFAULT
    verify_on_load: bool = C.CHECKPOINT_VERIFY_ON_LOAD_DEFAULT
    checksum: str = C.CHECKPOINT_CHECKSUM_DEFAULT
    keep_last_n: int = C.CHECKPOINT_KEEP_LAST_N_DEFAULT  # 0 = keep all
    keep_every: int = C.CHECKPOINT_KEEP_EVERY_DEFAULT  # pin step multiples
    fail_on_missing: bool = C.CHECKPOINT_FAIL_ON_MISSING_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]], block: str) -> "ResilienceCheckpointConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            atomic=bool(_pop(d, "atomic", C.CHECKPOINT_ATOMIC_DEFAULT)),
            verify_on_load=bool(_pop(d, "verify_on_load", C.CHECKPOINT_VERIFY_ON_LOAD_DEFAULT)),
            checksum=str(_pop(d, "checksum", C.CHECKPOINT_CHECKSUM_DEFAULT)).lower(),
            keep_last_n=int(_pop(d, "keep_last_n", C.CHECKPOINT_KEEP_LAST_N_DEFAULT)),
            keep_every=int(_pop(d, "keep_every", C.CHECKPOINT_KEEP_EVERY_DEFAULT)),
            fail_on_missing=bool(_pop(d, C.CHECKPOINT_FAIL_ON_MISSING, C.CHECKPOINT_FAIL_ON_MISSING_DEFAULT)),
        )
        _check_empty(d, block, _known_keys(cls))
        if out.checksum not in C.CHECKPOINT_CHECKSUM_ALGORITHMS:
            raise DeepSpeedConfigError(
                f"'{block}.checksum' must be one of {C.CHECKPOINT_CHECKSUM_ALGORITHMS}, got '{out.checksum}'"
            )
        return out


@dataclass
class WatchdogConfig:
    """``resilience.watchdog`` — SIGTERM/SIGINT → emergency checkpoint at
    the next step boundary, then exit with a scheduler-readable code."""

    enabled: bool = C.WATCHDOG_ENABLED_DEFAULT
    grace_seconds: float = C.WATCHDOG_GRACE_SECONDS_DEFAULT
    exit_code: int = C.WATCHDOG_EXIT_CODE_DEFAULT
    save_dir: Optional[str] = None  # default: the engine's last ckpt dir

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]], block: str) -> "WatchdogConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_pop(d, "enabled", C.WATCHDOG_ENABLED_DEFAULT)),
            grace_seconds=float(_pop(d, "grace_seconds", C.WATCHDOG_GRACE_SECONDS_DEFAULT)),
            exit_code=int(_pop(d, "exit_code", C.WATCHDOG_EXIT_CODE_DEFAULT)),
            save_dir=_pop(d, "save_dir", None),
        )
        _check_empty(d, block, _known_keys(cls))
        if not (0 <= out.exit_code <= 255):
            raise DeepSpeedConfigError(f"'{block}.exit_code' must be in [0, 255], got {out.exit_code}")
        if out.grace_seconds < 0:
            raise DeepSpeedConfigError(f"'{block}.grace_seconds' must be >= 0, got {out.grace_seconds}")
        return out


@dataclass
class RetryConfig:
    """``resilience.retry`` — the shared bounded-retry policy applied to
    checkpoint I/O and distributed init."""

    max_attempts: int = C.RETRY_MAX_ATTEMPTS_DEFAULT
    backoff_seconds: float = C.RETRY_BACKOFF_SECONDS_DEFAULT
    backoff_max_seconds: float = C.RETRY_BACKOFF_MAX_SECONDS_DEFAULT
    jitter: float = C.RETRY_JITTER_DEFAULT
    timeout_seconds: Optional[float] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]], block: str) -> "RetryConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            max_attempts=int(_pop(d, "max_attempts", C.RETRY_MAX_ATTEMPTS_DEFAULT)),
            backoff_seconds=float(_pop(d, "backoff_seconds", C.RETRY_BACKOFF_SECONDS_DEFAULT)),
            backoff_max_seconds=float(_pop(d, "backoff_max_seconds", C.RETRY_BACKOFF_MAX_SECONDS_DEFAULT)),
            jitter=float(_pop(d, "jitter", C.RETRY_JITTER_DEFAULT)),
            timeout_seconds=_pop(d, "timeout_seconds", None),
        )
        _check_empty(d, block, _known_keys(cls))
        if out.max_attempts < 1:
            raise DeepSpeedConfigError(f"'{block}.max_attempts' must be >= 1, got {out.max_attempts}")
        return out

    def policy(self):
        """Materialize as a runtime RetryPolicy (lazy import keeps config
        parsing free of the resilience package)."""
        from deepspeed_tpu.resilience.policy import RetryPolicy

        return RetryPolicy(
            max_attempts=self.max_attempts,
            backoff_seconds=self.backoff_seconds,
            backoff_max_seconds=self.backoff_max_seconds,
            jitter=self.jitter,
            timeout_seconds=self.timeout_seconds,
        )


@dataclass
class DivergenceConfig:
    """``resilience.divergence`` — N consecutive NaN/overflow-skipped
    steps trip a configurable action (warn / lower the loss-scale floor /
    auto-rollback to the last verified checkpoint)."""

    enabled: bool = C.DIVERGENCE_ENABLED_DEFAULT
    threshold: int = C.DIVERGENCE_THRESHOLD_DEFAULT
    action: str = C.DIVERGENCE_ACTION_WARN
    # Opt-in host sync: without dynamic loss scaling (bf16 default) there
    # is no overflow flag, so NaN detection must read the loss each step.
    check_loss: bool = False

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]], block: str) -> "DivergenceConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_pop(d, "enabled", C.DIVERGENCE_ENABLED_DEFAULT)),
            threshold=int(_pop(d, "threshold", C.DIVERGENCE_THRESHOLD_DEFAULT)),
            action=str(_pop(d, "action", C.DIVERGENCE_ACTION_WARN)).lower(),
            check_loss=bool(_pop(d, "check_loss", False)),
        )
        _check_empty(d, block, _known_keys(cls))
        if out.action not in C.DIVERGENCE_ACTIONS:
            raise DeepSpeedConfigError(
                f"'{block}.action' must be one of {C.DIVERGENCE_ACTIONS}, got '{out.action}'"
            )
        if out.threshold < 1:
            raise DeepSpeedConfigError(f"'{block}.threshold' must be >= 1, got {out.threshold}")
        return out


@dataclass
class SupervisionConfig:
    """``resilience.supervision`` — the distributed failure domain:
    heartbeat liveness plane, hung-collective watchdog and the exit-44
    "peer-failed-and-saved" rescue contract (docs/resilience.md)."""

    enabled: bool = C.SUPERVISION_ENABLED_DEFAULT
    channel: str = C.SUPERVISION_CHANNEL_DEFAULT  # auto | tcp | file
    beat_dir: Optional[str] = None  # file-channel directory
    beat_interval_seconds: float = C.SUPERVISION_BEAT_INTERVAL_DEFAULT
    beat_timeout_seconds: float = C.SUPERVISION_BEAT_TIMEOUT_DEFAULT
    sync_timeout_seconds: float = C.SUPERVISION_SYNC_TIMEOUT_DEFAULT
    rescue_grace_seconds: float = C.SUPERVISION_RESCUE_GRACE_DEFAULT
    connect_grace_seconds: float = C.SUPERVISION_CONNECT_GRACE_DEFAULT
    snapshot_interval_steps: int = C.SUPERVISION_SNAPSHOT_INTERVAL_DEFAULT
    exit_code: int = C.SUPERVISION_EXIT_CODE_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]], block: str) -> "SupervisionConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_pop(d, "enabled", C.SUPERVISION_ENABLED_DEFAULT)),
            channel=str(_pop(d, "channel", C.SUPERVISION_CHANNEL_DEFAULT)).lower(),
            beat_dir=_pop(d, "beat_dir", None),
            beat_interval_seconds=float(
                _pop(d, "beat_interval_seconds", C.SUPERVISION_BEAT_INTERVAL_DEFAULT)
            ),
            beat_timeout_seconds=float(
                _pop(d, "beat_timeout_seconds", C.SUPERVISION_BEAT_TIMEOUT_DEFAULT)
            ),
            sync_timeout_seconds=float(
                _pop(d, "sync_timeout_seconds", C.SUPERVISION_SYNC_TIMEOUT_DEFAULT)
            ),
            rescue_grace_seconds=float(
                _pop(d, "rescue_grace_seconds", C.SUPERVISION_RESCUE_GRACE_DEFAULT)
            ),
            connect_grace_seconds=float(
                _pop(d, "connect_grace_seconds", C.SUPERVISION_CONNECT_GRACE_DEFAULT)
            ),
            snapshot_interval_steps=int(
                _pop(d, "snapshot_interval_steps", C.SUPERVISION_SNAPSHOT_INTERVAL_DEFAULT)
            ),
            exit_code=int(_pop(d, "exit_code", C.SUPERVISION_EXIT_CODE_DEFAULT)),
        )
        _check_empty(d, block, _known_keys(cls))
        if out.channel not in C.SUPERVISION_CHANNELS:
            raise DeepSpeedConfigError(
                f"'{block}.channel' must be one of {C.SUPERVISION_CHANNELS}, got '{out.channel}'"
            )
        if not (0 <= out.exit_code <= 255):
            raise DeepSpeedConfigError(f"'{block}.exit_code' must be in [0, 255], got {out.exit_code}")
        for name in ("beat_interval_seconds", "beat_timeout_seconds", "sync_timeout_seconds"):
            if getattr(out, name) <= 0:
                raise DeepSpeedConfigError(f"'{block}.{name}' must be > 0, got {getattr(out, name)}")
        if out.beat_timeout_seconds <= out.beat_interval_seconds:
            raise DeepSpeedConfigError(
                f"'{block}.beat_timeout_seconds' ({out.beat_timeout_seconds}) must exceed "
                f"beat_interval_seconds ({out.beat_interval_seconds}) or every beat gap reads as a death"
            )
        if out.snapshot_interval_steps < 1:
            raise DeepSpeedConfigError(
                f"'{block}.snapshot_interval_steps' must be >= 1, got {out.snapshot_interval_steps}"
            )
        return out


@dataclass
class ResilienceConfig:
    """``resilience`` block (TPU-native extension; docs/resilience.md)."""

    checkpoint: ResilienceCheckpointConfig = field(default_factory=ResilienceCheckpointConfig)
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    retry: RetryConfig = field(default_factory=RetryConfig)
    divergence: DivergenceConfig = field(default_factory=DivergenceConfig)
    supervision: SupervisionConfig = field(default_factory=SupervisionConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ResilienceConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            checkpoint=ResilienceCheckpointConfig.from_dict(
                _pop(d, C.RESILIENCE_CHECKPOINT, None), f"{C.RESILIENCE}.{C.RESILIENCE_CHECKPOINT}"
            ),
            watchdog=WatchdogConfig.from_dict(
                _pop(d, C.RESILIENCE_WATCHDOG, None), f"{C.RESILIENCE}.{C.RESILIENCE_WATCHDOG}"
            ),
            retry=RetryConfig.from_dict(
                _pop(d, C.RESILIENCE_RETRY, None), f"{C.RESILIENCE}.{C.RESILIENCE_RETRY}"
            ),
            divergence=DivergenceConfig.from_dict(
                _pop(d, C.RESILIENCE_DIVERGENCE, None), f"{C.RESILIENCE}.{C.RESILIENCE_DIVERGENCE}"
            ),
            supervision=SupervisionConfig.from_dict(
                _pop(d, C.RESILIENCE_SUPERVISION, None), f"{C.RESILIENCE}.{C.RESILIENCE_SUPERVISION}"
            ),
        )
        _check_empty(d, C.RESILIENCE, _known_keys(cls))
        return out


@dataclass
class PrefetchOverlapConfig:
    """``overlap.prefetch`` — pipelined load + sharded ``device_put`` of
    input batches ahead of the compiled step (``engine.prefetch_loader``)."""

    enabled: bool = C.PREFETCH_ENABLED_DEFAULT
    depth: int = C.PREFETCH_DEPTH_DEFAULT  # batches in flight per stage

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]], block: str) -> "PrefetchOverlapConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_pop(d, "enabled", C.PREFETCH_ENABLED_DEFAULT)),
            depth=int(_pop(d, "depth", C.PREFETCH_DEPTH_DEFAULT)),
        )
        _check_empty(d, block, _known_keys(cls))
        if out.depth < 1:
            raise DeepSpeedConfigError(f"'{block}.depth' must be >= 1, got {out.depth}")
        return out


@dataclass
class AsyncCheckpointConfig:
    """``overlap.async_checkpoint`` — snapshot device state at the step
    boundary, run the stage->manifest->rename commit on a background
    thread (docs/performance.md; durability contract per
    docs/resilience.md is unchanged)."""

    enabled: bool = C.ASYNC_CHECKPOINT_ENABLED_DEFAULT
    drain_timeout_seconds: float = C.ASYNC_CHECKPOINT_DRAIN_TIMEOUT_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]], block: str) -> "AsyncCheckpointConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_pop(d, "enabled", C.ASYNC_CHECKPOINT_ENABLED_DEFAULT)),
            drain_timeout_seconds=float(
                _pop(d, "drain_timeout_seconds", C.ASYNC_CHECKPOINT_DRAIN_TIMEOUT_DEFAULT)
            ),
        )
        _check_empty(d, block, _known_keys(cls))
        if out.drain_timeout_seconds <= 0:
            raise DeepSpeedConfigError(
                f"'{block}.drain_timeout_seconds' must be > 0, got {out.drain_timeout_seconds}"
            )
        return out


@dataclass
class TimelineConfig:
    """``overlap.timeline`` — per-step wall-time attribution
    (data_wait / compute / ckpt_stall / compile / other).

    ``fence``: per-step ``block_until_ready`` before the compute note.
    Honest per-step compute attribution requires it, but it costs a full
    host<->device round trip per step (exactly what ThroughputTimer
    avoids off report steps).  ``null`` (default) follows
    ``wall_clock_breakdown``; without the fence the timeline still
    attributes the host-measurable phases (data_wait / ckpt_stall /
    compile) and omits ``compute`` rather than record an unfenced lie."""

    enabled: bool = C.TIMELINE_ENABLED_DEFAULT
    window: int = C.TIMELINE_WINDOW_DEFAULT
    fence: Optional[bool] = None  # None = follow wall_clock_breakdown

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]], block: str) -> "TimelineConfig":
        if d is None:
            return cls()
        d = dict(d)
        fence = _pop(d, "fence", None)
        out = cls(
            enabled=bool(_pop(d, "enabled", C.TIMELINE_ENABLED_DEFAULT)),
            window=int(_pop(d, "window", C.TIMELINE_WINDOW_DEFAULT)),
            fence=None if fence is None else bool(fence),
        )
        _check_empty(d, block, _known_keys(cls))
        if out.window < 1:
            raise DeepSpeedConfigError(f"'{block}.window' must be >= 1, got {out.window}")
        return out


@dataclass
class OverlapConfig:
    """``overlap`` block (TPU-native extension; docs/performance.md)."""

    prefetch: PrefetchOverlapConfig = field(default_factory=PrefetchOverlapConfig)
    async_checkpoint: AsyncCheckpointConfig = field(default_factory=AsyncCheckpointConfig)
    timeline: TimelineConfig = field(default_factory=TimelineConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "OverlapConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            prefetch=PrefetchOverlapConfig.from_dict(
                _pop(d, C.OVERLAP_PREFETCH, None), f"{C.OVERLAP}.{C.OVERLAP_PREFETCH}"
            ),
            async_checkpoint=AsyncCheckpointConfig.from_dict(
                _pop(d, C.OVERLAP_ASYNC_CHECKPOINT, None),
                f"{C.OVERLAP}.{C.OVERLAP_ASYNC_CHECKPOINT}",
            ),
            timeline=TimelineConfig.from_dict(
                _pop(d, C.OVERLAP_TIMELINE, None), f"{C.OVERLAP}.{C.OVERLAP_TIMELINE}"
            ),
        )
        _check_empty(d, C.OVERLAP, _known_keys(cls))
        return out


@dataclass
class CommConfig:
    """``comm`` block (TPU-native extension; docs/comm.md): the wire
    strategy for gradient exchange — ``dense`` (full precision, the
    default), ``int8`` (EQuARX-style quantized allreduce: per-chunk
    scale + stochastic rounding), ``onebit`` (error-feedback sign +
    L1-scale compression, generalized from 1-bit Adam's exchange), or
    ``auto`` (policy-selected per tensor size/dtype/topology)."""

    strategy: str = C.COMM_STRATEGY_DEFAULT
    threshold_bytes: int = C.COMM_THRESHOLD_BYTES_DEFAULT
    dcn_threshold_bytes: int = C.COMM_DCN_THRESHOLD_BYTES_DEFAULT
    quantize_bits: int = C.COMM_QUANTIZE_BITS_DEFAULT
    error_feedback: bool = C.COMM_ERROR_FEEDBACK_DEFAULT
    stochastic_rounding: bool = C.COMM_STOCHASTIC_ROUNDING_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "CommConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            strategy=str(_pop(d, "strategy", C.COMM_STRATEGY_DEFAULT)).lower(),
            threshold_bytes=int(_pop(d, "threshold_bytes", C.COMM_THRESHOLD_BYTES_DEFAULT)),
            dcn_threshold_bytes=int(
                _pop(d, "dcn_threshold_bytes", C.COMM_DCN_THRESHOLD_BYTES_DEFAULT)
            ),
            quantize_bits=int(_pop(d, "quantize_bits", C.COMM_QUANTIZE_BITS_DEFAULT)),
            error_feedback=bool(_pop(d, "error_feedback", C.COMM_ERROR_FEEDBACK_DEFAULT)),
            stochastic_rounding=bool(
                _pop(d, "stochastic_rounding", C.COMM_STOCHASTIC_ROUNDING_DEFAULT)
            ),
        )
        _check_empty(d, C.COMM, _known_keys(cls))
        if out.strategy not in C.COMM_STRATEGIES:
            raise DeepSpeedConfigError(
                f"'{C.COMM}.strategy' must be one of {C.COMM_STRATEGIES}, got '{out.strategy}'"
            )
        if out.threshold_bytes < 0:
            raise DeepSpeedConfigError(
                f"'{C.COMM}.threshold_bytes' must be >= 0, got {out.threshold_bytes}"
            )
        if out.dcn_threshold_bytes < 0:
            raise DeepSpeedConfigError(
                f"'{C.COMM}.dcn_threshold_bytes' must be >= 0, got {out.dcn_threshold_bytes}"
            )
        if out.quantize_bits != C.COMM_QUANTIZE_BITS_DEFAULT:
            # XLA has no bit-packed dtype: int8 is the densest exchange
            # format ICI moves natively (comm/compressed.py module note);
            # the 1-bit TIER is the `onebit` strategy, whose signs also
            # ride as int8
            raise DeepSpeedConfigError(
                f"'{C.COMM}.quantize_bits' supports only {C.COMM_QUANTIZE_BITS_DEFAULT} "
                f"(int8 is the densest ICI-native exchange format; use strategy "
                f"'{C.COMM_STRATEGY_ONEBIT}' for the sign+scale tier), got {out.quantize_bits}"
            )
        return out


@dataclass
class ElasticConfig:
    """``serving.fleet.elastic`` block (docs/serving.md §Elastic
    fleet): load-driven autoscaling — hot/cold tick hysteresis over the
    router's own signals (queue depth, admitted-TTFT estimate, shed),
    warm-pool scale-up, and drain-based scale-down with live KV session
    migration to the survivors over the spill-manifest wire format."""

    enabled: bool = C.SERVING_FLEET_ELASTIC_ENABLED_DEFAULT
    min_replicas: int = C.SERVING_FLEET_ELASTIC_MIN_REPLICAS_DEFAULT
    max_replicas: int = C.SERVING_FLEET_ELASTIC_MAX_REPLICAS_DEFAULT
    scale_up_queue_depth: int = C.SERVING_FLEET_ELASTIC_SCALE_UP_QUEUE_DEPTH_DEFAULT
    scale_up_ttft_seconds: float = C.SERVING_FLEET_ELASTIC_SCALE_UP_TTFT_SECONDS_DEFAULT
    scale_down_queue_depth: int = (
        C.SERVING_FLEET_ELASTIC_SCALE_DOWN_QUEUE_DEPTH_DEFAULT
    )
    engage_ticks: int = C.SERVING_FLEET_ELASTIC_ENGAGE_TICKS_DEFAULT
    disengage_ticks: int = C.SERVING_FLEET_ELASTIC_DISENGAGE_TICKS_DEFAULT
    scale_up_cooldown_seconds: float = (
        C.SERVING_FLEET_ELASTIC_SCALE_UP_COOLDOWN_SECONDS_DEFAULT
    )
    scale_down_cooldown_seconds: float = (
        C.SERVING_FLEET_ELASTIC_SCALE_DOWN_COOLDOWN_SECONDS_DEFAULT
    )
    warm_pool_size: int = C.SERVING_FLEET_ELASTIC_WARM_POOL_SIZE_DEFAULT
    migration_deadline_seconds: float = (
        C.SERVING_FLEET_ELASTIC_MIGRATION_DEADLINE_SECONDS_DEFAULT
    )
    migration_retries: int = C.SERVING_FLEET_ELASTIC_MIGRATION_RETRIES_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ElasticConfig":
        if d is None:
            return cls()
        if isinstance(d, ElasticConfig):
            d = dataclasses.asdict(d)
        d = dict(d)
        block = f"{C.SERVING}.{C.SERVING_FLEET}.{C.SERVING_FLEET_ELASTIC}"
        out = cls(
            enabled=bool(_pop(d, "enabled", C.SERVING_FLEET_ELASTIC_ENABLED_DEFAULT)),
            min_replicas=int(
                _pop(d, "min_replicas", C.SERVING_FLEET_ELASTIC_MIN_REPLICAS_DEFAULT)
            ),
            max_replicas=int(
                _pop(d, "max_replicas", C.SERVING_FLEET_ELASTIC_MAX_REPLICAS_DEFAULT)
            ),
            scale_up_queue_depth=int(
                _pop(d, "scale_up_queue_depth",
                     C.SERVING_FLEET_ELASTIC_SCALE_UP_QUEUE_DEPTH_DEFAULT)
            ),
            scale_up_ttft_seconds=float(
                _pop(d, "scale_up_ttft_seconds",
                     C.SERVING_FLEET_ELASTIC_SCALE_UP_TTFT_SECONDS_DEFAULT)
            ),
            scale_down_queue_depth=int(
                _pop(d, "scale_down_queue_depth",
                     C.SERVING_FLEET_ELASTIC_SCALE_DOWN_QUEUE_DEPTH_DEFAULT)
            ),
            engage_ticks=int(
                _pop(d, "engage_ticks", C.SERVING_FLEET_ELASTIC_ENGAGE_TICKS_DEFAULT)
            ),
            disengage_ticks=int(
                _pop(d, "disengage_ticks",
                     C.SERVING_FLEET_ELASTIC_DISENGAGE_TICKS_DEFAULT)
            ),
            scale_up_cooldown_seconds=float(
                _pop(d, "scale_up_cooldown_seconds",
                     C.SERVING_FLEET_ELASTIC_SCALE_UP_COOLDOWN_SECONDS_DEFAULT)
            ),
            scale_down_cooldown_seconds=float(
                _pop(d, "scale_down_cooldown_seconds",
                     C.SERVING_FLEET_ELASTIC_SCALE_DOWN_COOLDOWN_SECONDS_DEFAULT)
            ),
            warm_pool_size=int(
                _pop(d, "warm_pool_size",
                     C.SERVING_FLEET_ELASTIC_WARM_POOL_SIZE_DEFAULT)
            ),
            migration_deadline_seconds=float(
                _pop(d, "migration_deadline_seconds",
                     C.SERVING_FLEET_ELASTIC_MIGRATION_DEADLINE_SECONDS_DEFAULT)
            ),
            migration_retries=int(
                _pop(d, "migration_retries",
                     C.SERVING_FLEET_ELASTIC_MIGRATION_RETRIES_DEFAULT)
            ),
        )
        _check_empty(d, block, _known_keys(cls))
        if out.min_replicas < 1:
            raise DeepSpeedConfigError(
                f"'{block}.min_replicas' must be >= 1, got {out.min_replicas}"
            )
        if out.max_replicas < out.min_replicas:
            raise DeepSpeedConfigError(
                f"'{block}.max_replicas' ({out.max_replicas}) must be >= "
                f"min_replicas ({out.min_replicas})"
            )
        if out.scale_up_queue_depth < 1:
            raise DeepSpeedConfigError(
                f"'{block}.scale_up_queue_depth' must be >= 1, "
                f"got {out.scale_up_queue_depth}"
            )
        if out.scale_up_ttft_seconds <= 0:
            raise DeepSpeedConfigError(
                f"'{block}.scale_up_ttft_seconds' must be > 0, "
                f"got {out.scale_up_ttft_seconds}"
            )
        if out.scale_down_queue_depth < 0:
            raise DeepSpeedConfigError(
                f"'{block}.scale_down_queue_depth' must be >= 0, "
                f"got {out.scale_down_queue_depth}"
            )
        if out.scale_down_queue_depth >= out.scale_up_queue_depth:
            raise DeepSpeedConfigError(
                f"'{block}.scale_down_queue_depth' "
                f"({out.scale_down_queue_depth}) must be < "
                f"scale_up_queue_depth ({out.scale_up_queue_depth}) — "
                f"overlapping thresholds would flap"
            )
        if out.engage_ticks < 1:
            raise DeepSpeedConfigError(
                f"'{block}.engage_ticks' must be >= 1, got {out.engage_ticks}"
            )
        if out.disengage_ticks < 1:
            raise DeepSpeedConfigError(
                f"'{block}.disengage_ticks' must be >= 1, "
                f"got {out.disengage_ticks}"
            )
        if out.scale_up_cooldown_seconds < 0:
            raise DeepSpeedConfigError(
                f"'{block}.scale_up_cooldown_seconds' must be >= 0, "
                f"got {out.scale_up_cooldown_seconds}"
            )
        if out.scale_down_cooldown_seconds < 0:
            raise DeepSpeedConfigError(
                f"'{block}.scale_down_cooldown_seconds' must be >= 0, "
                f"got {out.scale_down_cooldown_seconds}"
            )
        if out.warm_pool_size < 0:
            raise DeepSpeedConfigError(
                f"'{block}.warm_pool_size' must be >= 0, "
                f"got {out.warm_pool_size}"
            )
        if out.migration_deadline_seconds <= 0:
            raise DeepSpeedConfigError(
                f"'{block}.migration_deadline_seconds' must be > 0, "
                f"got {out.migration_deadline_seconds}"
            )
        if out.migration_retries < 0:
            raise DeepSpeedConfigError(
                f"'{block}.migration_retries' must be >= 0, "
                f"got {out.migration_retries}"
            )
        return out


@dataclass
class FleetConfig:
    """``serving.fleet`` block (docs/serving.md §Fleet): the front-door
    router over N engine replicas — least-estimated-TTFT placement, a
    per-replica circuit breaker with seeded-jitter exponential backoff,
    optional tail-latency hedging, and supervised lossless replica
    restart (journal replay under original ids)."""

    replicas: int = C.SERVING_FLEET_REPLICAS_DEFAULT
    route_retries: int = C.SERVING_FLEET_ROUTE_RETRIES_DEFAULT
    breaker_failures: int = C.SERVING_FLEET_BREAKER_FAILURES_DEFAULT
    breaker_backoff_seconds: float = C.SERVING_FLEET_BREAKER_BACKOFF_SECONDS_DEFAULT
    breaker_backoff_max_seconds: float = (
        C.SERVING_FLEET_BREAKER_BACKOFF_MAX_SECONDS_DEFAULT
    )
    breaker_halfopen_probes: int = C.SERVING_FLEET_BREAKER_HALFOPEN_PROBES_DEFAULT
    hedge: bool = C.SERVING_FLEET_HEDGE_DEFAULT
    hedge_factor: float = C.SERVING_FLEET_HEDGE_FACTOR_DEFAULT
    hedge_min_observations: int = C.SERVING_FLEET_HEDGE_MIN_OBSERVATIONS_DEFAULT
    max_restarts: int = C.SERVING_FLEET_MAX_RESTARTS_DEFAULT
    restart_backoff_seconds: float = C.SERVING_FLEET_RESTART_BACKOFF_SECONDS_DEFAULT
    restart_budget_reset_seconds: float = (
        C.SERVING_FLEET_RESTART_BUDGET_RESET_SECONDS_DEFAULT
    )
    elastic: ElasticConfig = dataclasses.field(default_factory=ElasticConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "FleetConfig":
        if d is None:
            return cls()
        if isinstance(d, FleetConfig):
            d = dataclasses.asdict(d)
        d = dict(d)
        block = f"{C.SERVING}.{C.SERVING_FLEET}"
        elastic = ElasticConfig.from_dict(_pop(d, C.SERVING_FLEET_ELASTIC, None))
        out = cls(
            replicas=int(_pop(d, "replicas", C.SERVING_FLEET_REPLICAS_DEFAULT)),
            route_retries=int(
                _pop(d, "route_retries", C.SERVING_FLEET_ROUTE_RETRIES_DEFAULT)
            ),
            breaker_failures=int(
                _pop(d, "breaker_failures", C.SERVING_FLEET_BREAKER_FAILURES_DEFAULT)
            ),
            breaker_backoff_seconds=float(
                _pop(d, "breaker_backoff_seconds",
                     C.SERVING_FLEET_BREAKER_BACKOFF_SECONDS_DEFAULT)
            ),
            breaker_backoff_max_seconds=float(
                _pop(d, "breaker_backoff_max_seconds",
                     C.SERVING_FLEET_BREAKER_BACKOFF_MAX_SECONDS_DEFAULT)
            ),
            breaker_halfopen_probes=int(
                _pop(d, "breaker_halfopen_probes",
                     C.SERVING_FLEET_BREAKER_HALFOPEN_PROBES_DEFAULT)
            ),
            hedge=bool(_pop(d, "hedge", C.SERVING_FLEET_HEDGE_DEFAULT)),
            hedge_factor=float(
                _pop(d, "hedge_factor", C.SERVING_FLEET_HEDGE_FACTOR_DEFAULT)
            ),
            hedge_min_observations=int(
                _pop(d, "hedge_min_observations",
                     C.SERVING_FLEET_HEDGE_MIN_OBSERVATIONS_DEFAULT)
            ),
            max_restarts=int(
                _pop(d, "max_restarts", C.SERVING_FLEET_MAX_RESTARTS_DEFAULT)
            ),
            restart_backoff_seconds=float(
                _pop(d, "restart_backoff_seconds",
                     C.SERVING_FLEET_RESTART_BACKOFF_SECONDS_DEFAULT)
            ),
            restart_budget_reset_seconds=float(
                _pop(d, "restart_budget_reset_seconds",
                     C.SERVING_FLEET_RESTART_BUDGET_RESET_SECONDS_DEFAULT)
            ),
            elastic=elastic,
        )
        _check_empty(d, block, _known_keys(cls))
        if out.replicas < 1:
            raise DeepSpeedConfigError(
                f"'{block}.replicas' must be >= 1, got {out.replicas}"
            )
        if out.route_retries < 0:
            raise DeepSpeedConfigError(
                f"'{block}.route_retries' must be >= 0, got {out.route_retries}"
            )
        if out.breaker_failures < 1:
            raise DeepSpeedConfigError(
                f"'{block}.breaker_failures' must be >= 1, got {out.breaker_failures}"
            )
        if out.breaker_backoff_seconds < 0:
            raise DeepSpeedConfigError(
                f"'{block}.breaker_backoff_seconds' must be >= 0, "
                f"got {out.breaker_backoff_seconds}"
            )
        if out.breaker_backoff_max_seconds < out.breaker_backoff_seconds:
            raise DeepSpeedConfigError(
                f"'{block}.breaker_backoff_max_seconds' "
                f"({out.breaker_backoff_max_seconds}) must be >= "
                f"breaker_backoff_seconds ({out.breaker_backoff_seconds})"
            )
        if out.breaker_halfopen_probes < 1:
            raise DeepSpeedConfigError(
                f"'{block}.breaker_halfopen_probes' must be >= 1, "
                f"got {out.breaker_halfopen_probes}"
            )
        if out.hedge_factor <= 0:
            raise DeepSpeedConfigError(
                f"'{block}.hedge_factor' must be > 0, got {out.hedge_factor}"
            )
        if out.hedge_min_observations < 1:
            raise DeepSpeedConfigError(
                f"'{block}.hedge_min_observations' must be >= 1, "
                f"got {out.hedge_min_observations}"
            )
        if out.max_restarts < 0:
            raise DeepSpeedConfigError(
                f"'{block}.max_restarts' must be >= 0 (0 = never restart), "
                f"got {out.max_restarts}"
            )
        if out.restart_backoff_seconds < 0:
            raise DeepSpeedConfigError(
                f"'{block}.restart_backoff_seconds' must be >= 0, "
                f"got {out.restart_backoff_seconds}"
            )
        if out.restart_budget_reset_seconds < 0:
            raise DeepSpeedConfigError(
                f"'{block}.restart_budget_reset_seconds' must be >= 0 "
                f"(0 = budget never decays), "
                f"got {out.restart_budget_reset_seconds}"
            )
        return out


@dataclass
class KVTiersConfig:
    """``serving.kvcache.tiers`` block (docs/serving.md §KV tiering):
    hierarchical page residency HBM (T0) → pinned host memory (T1) →
    disk (T2).  Cold pages demote asynchronously past the watermark;
    promotion is demand-driven plus scheduler-hinted prefetch."""

    enabled: bool = C.SERVING_KVCACHE_TIERS_ENABLED_DEFAULT
    host_pages: int = C.SERVING_KVCACHE_TIERS_HOST_PAGES_DEFAULT  # 0 = unbounded
    disk_dir: str = C.SERVING_KVCACHE_TIERS_DISK_DIR_DEFAULT  # "" = no T2
    # tokens of a parked session kept T0-resident; tail pages beyond
    # this demote (0 keeps whole sessions resident until cold)
    residency_window: int = C.SERVING_KVCACHE_TIERS_RESIDENCY_WINDOW_DEFAULT
    demote_watermark: float = C.SERVING_KVCACHE_TIERS_DEMOTE_WATERMARK_DEFAULT
    prefetch_ahead: int = C.SERVING_KVCACHE_TIERS_PREFETCH_AHEAD_DEFAULT
    demote_batch: int = C.SERVING_KVCACHE_TIERS_DEMOTE_BATCH_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "KVTiersConfig":
        if d is None:
            return cls()
        if isinstance(d, KVTiersConfig):
            d = dataclasses.asdict(d)
        d = dict(d)
        block = (f"{C.SERVING}.{C.SERVING_KVCACHE}"
                 f".{C.SERVING_KVCACHE_TIERS}")
        out = cls(
            enabled=bool(_pop(d, "enabled",
                              C.SERVING_KVCACHE_TIERS_ENABLED_DEFAULT)),
            host_pages=int(_pop(d, "host_pages",
                                C.SERVING_KVCACHE_TIERS_HOST_PAGES_DEFAULT)),
            disk_dir=str(_pop(d, "disk_dir",
                              C.SERVING_KVCACHE_TIERS_DISK_DIR_DEFAULT) or ""),
            residency_window=int(_pop(
                d, "residency_window",
                C.SERVING_KVCACHE_TIERS_RESIDENCY_WINDOW_DEFAULT)),
            demote_watermark=float(_pop(
                d, "demote_watermark",
                C.SERVING_KVCACHE_TIERS_DEMOTE_WATERMARK_DEFAULT)),
            prefetch_ahead=int(_pop(
                d, "prefetch_ahead",
                C.SERVING_KVCACHE_TIERS_PREFETCH_AHEAD_DEFAULT)),
            demote_batch=int(_pop(
                d, "demote_batch",
                C.SERVING_KVCACHE_TIERS_DEMOTE_BATCH_DEFAULT)),
        )
        _check_empty(d, block, _known_keys(cls))
        if out.host_pages < 0:
            raise DeepSpeedConfigError(
                f"'{block}.host_pages' must be >= 0 (0 = unbounded), "
                f"got {out.host_pages}"
            )
        if out.residency_window < 0:
            raise DeepSpeedConfigError(
                f"'{block}.residency_window' must be >= 0 (0 keeps whole "
                f"sessions resident), got {out.residency_window}"
            )
        if not (0.0 < out.demote_watermark <= 1.0):
            raise DeepSpeedConfigError(
                f"'{block}.demote_watermark' must be in (0, 1], "
                f"got {out.demote_watermark}"
            )
        if out.prefetch_ahead < 0:
            raise DeepSpeedConfigError(
                f"'{block}.prefetch_ahead' must be >= 0, "
                f"got {out.prefetch_ahead}"
            )
        if out.demote_batch < 1:
            raise DeepSpeedConfigError(
                f"'{block}.demote_batch' must be >= 1, got {out.demote_batch}"
            )
        return out


@dataclass
class KVCacheConfig:
    """``serving.kvcache`` block (docs/serving.md §Paged KV & prefix
    caching): the paged KV pool — fixed-shape page buffers with a host
    page allocator, shared-prefix dedup via a radix index, copy-on-write
    for partially filled shared pages, and durable per-``session_id`` KV
    reuse (warm in-pool, spilled to ``spill_dir`` when cold / at drain)."""

    enabled: bool = C.SERVING_KVCACHE_ENABLED_DEFAULT
    page_len: int = C.SERVING_KVCACHE_PAGE_LEN_DEFAULT
    num_pages: int = C.SERVING_KVCACHE_NUM_PAGES_DEFAULT  # 0 = derive
    # prompt prefixes (token-id lists) pre-registered in the radix index
    # at engine start; pinned entries are never evicted under pressure
    pinned_prefixes: Tuple[Tuple[int, ...], ...] = ()
    session_ttl_seconds: float = C.SERVING_KVCACHE_SESSION_TTL_SECONDS_DEFAULT
    spill_dir: str = C.SERVING_KVCACHE_SPILL_DIR_DEFAULT
    # hierarchical HBM -> host -> disk page tiering (docs/serving.md
    # §KV tiering)
    tiers: KVTiersConfig = field(default_factory=KVTiersConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "KVCacheConfig":
        if d is None:
            return cls()
        if isinstance(d, KVCacheConfig):
            d = dataclasses.asdict(d)
        d = dict(d)
        block = f"{C.SERVING}.{C.SERVING_KVCACHE}"
        tiers = KVTiersConfig.from_dict(
            _pop(d, C.SERVING_KVCACHE_TIERS, None))
        raw_pins = _pop(d, "pinned_prefixes", ())
        if raw_pins is None:
            raw_pins = ()
        if not isinstance(raw_pins, (list, tuple)):
            raise DeepSpeedConfigError(
                f"'{block}.pinned_prefixes' must be a list of token-id "
                f"lists, got {type(raw_pins).__name__}"
            )
        pins: List[Tuple[int, ...]] = []
        for i, spec in enumerate(raw_pins):
            if not isinstance(spec, (list, tuple)) or not spec:
                raise DeepSpeedConfigError(
                    f"'{block}.pinned_prefixes[{i}]' must be a non-empty "
                    f"list of token ids"
                )
            pins.append(tuple(int(t) for t in spec))
        out = cls(
            tiers=tiers,
            enabled=bool(_pop(d, "enabled", C.SERVING_KVCACHE_ENABLED_DEFAULT)),
            page_len=int(_pop(d, "page_len", C.SERVING_KVCACHE_PAGE_LEN_DEFAULT)),
            num_pages=int(_pop(d, "num_pages", C.SERVING_KVCACHE_NUM_PAGES_DEFAULT)),
            pinned_prefixes=tuple(pins),
            session_ttl_seconds=float(
                _pop(d, "session_ttl_seconds",
                     C.SERVING_KVCACHE_SESSION_TTL_SECONDS_DEFAULT)
            ),
            spill_dir=str(_pop(d, "spill_dir", C.SERVING_KVCACHE_SPILL_DIR_DEFAULT) or ""),
        )
        _check_empty(d, block, _known_keys(cls))
        if out.page_len < 1:
            raise DeepSpeedConfigError(
                f"'{block}.page_len' must be >= 1, got {out.page_len}"
            )
        if out.num_pages < 0:
            raise DeepSpeedConfigError(
                f"'{block}.num_pages' must be >= 0 (0 derives it from the "
                f"slot capacity), got {out.num_pages}"
            )
        if out.session_ttl_seconds < 0:
            raise DeepSpeedConfigError(
                f"'{block}.session_ttl_seconds' must be >= 0, "
                f"got {out.session_ttl_seconds}"
            )
        return out


@dataclass
class FrontdoorConfig:
    """``serving.frontdoor`` block (docs/serving.md §Front-door): the
    stdlib HTTP front-door — chunked streaming token responses, request
    deadlines mapped onto scheduler deadlines, ``Retry-After``-bearing
    429/503 overload answers, and SIGTERM graceful drain composed with
    the serving watchdog."""

    enabled: bool = C.SERVING_FRONTDOOR_ENABLED_DEFAULT
    host: str = C.SERVING_FRONTDOOR_HOST_DEFAULT
    port: int = C.SERVING_FRONTDOOR_PORT_DEFAULT  # 0 = ephemeral
    stream_poll_seconds: float = C.SERVING_FRONTDOOR_STREAM_POLL_SECONDS_DEFAULT
    max_body_bytes: int = C.SERVING_FRONTDOOR_MAX_BODY_BYTES_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "FrontdoorConfig":
        if d is None:
            return cls()
        if isinstance(d, FrontdoorConfig):
            d = dataclasses.asdict(d)
        d = dict(d)
        block = f"{C.SERVING}.{C.SERVING_FRONTDOOR}"
        out = cls(
            enabled=bool(_pop(d, "enabled", C.SERVING_FRONTDOOR_ENABLED_DEFAULT)),
            host=str(_pop(d, "host", C.SERVING_FRONTDOOR_HOST_DEFAULT)),
            port=int(_pop(d, "port", C.SERVING_FRONTDOOR_PORT_DEFAULT)),
            stream_poll_seconds=float(
                _pop(d, "stream_poll_seconds",
                     C.SERVING_FRONTDOOR_STREAM_POLL_SECONDS_DEFAULT)
            ),
            max_body_bytes=int(
                _pop(d, "max_body_bytes",
                     C.SERVING_FRONTDOOR_MAX_BODY_BYTES_DEFAULT)
            ),
        )
        _check_empty(d, block, _known_keys(cls))
        if not 0 <= out.port <= 65535:
            raise DeepSpeedConfigError(
                f"'{block}.port' must be in [0, 65535] (0 = ephemeral), "
                f"got {out.port}"
            )
        if out.stream_poll_seconds <= 0:
            raise DeepSpeedConfigError(
                f"'{block}.stream_poll_seconds' must be > 0, "
                f"got {out.stream_poll_seconds}"
            )
        if out.max_body_bytes < 1:
            raise DeepSpeedConfigError(
                f"'{block}.max_body_bytes' must be >= 1, "
                f"got {out.max_body_bytes}"
            )
        return out


# per-tenant override spec keys accepted under serving.tenants.overrides
_TENANT_SPEC_KEYS = (
    "refill_tokens_per_second",
    "burst_tokens",
    "weight",
    "slo_class",
    "kv_pages_max",
    "pinned_prefixes_max",
)


@dataclass
class TenantsConfig:
    """``serving.tenants`` block (docs/serving.md §Front-door): the
    multi-tenant dimension — per-tenant token-bucket admission rates,
    weighted-fair queueing ahead of priority tiers, SLO classes mapped
    onto the degradation ladder's priorities, and per-tenant paged-KV
    page / pinned-prefix quotas.  Field values are the defaults for any
    tenant; ``overrides`` refines them per tenant name."""

    enabled: bool = C.SERVING_TENANTS_ENABLED_DEFAULT
    refill_tokens_per_second: float = (
        C.SERVING_TENANTS_REFILL_TOKENS_PER_SECOND_DEFAULT)
    burst_tokens: float = C.SERVING_TENANTS_BURST_TOKENS_DEFAULT
    weight: float = C.SERVING_TENANTS_WEIGHT_DEFAULT
    slo_class: str = C.SERVING_TENANTS_SLO_CLASS_DEFAULT
    kv_pages_max: int = C.SERVING_TENANTS_KV_PAGES_MAX_DEFAULT
    pinned_prefixes_max: int = C.SERVING_TENANTS_PINNED_PREFIXES_MAX_DEFAULT
    overrides: Dict[str, Dict[str, Any]] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "TenantsConfig":
        if d is None:
            return cls()
        if isinstance(d, TenantsConfig):
            d = dataclasses.asdict(d)
        d = dict(d)
        block = f"{C.SERVING}.{C.SERVING_TENANTS}"
        raw_over = _pop(d, "overrides", None) or {}
        if not isinstance(raw_over, dict):
            raise DeepSpeedConfigError(
                f"'{block}.overrides' must be a dict of per-tenant spec "
                f"dicts, got {type(raw_over).__name__}"
            )
        overrides: Dict[str, Dict[str, Any]] = {}
        for name, spec in raw_over.items():
            if not isinstance(spec, dict):
                raise DeepSpeedConfigError(
                    f"'{block}.overrides[{name!r}]' must be a dict, "
                    f"got {type(spec).__name__}"
                )
            unknown = sorted(set(spec) - set(_TENANT_SPEC_KEYS))
            if unknown:
                raise DeepSpeedConfigError(
                    f"'{block}.overrides[{name!r}]' has unknown keys "
                    f"{unknown}; known: {sorted(_TENANT_SPEC_KEYS)}"
                )
            slo = spec.get("slo_class")
            if slo is not None and slo not in C.SERVING_TENANTS_SLO_CLASSES:
                raise DeepSpeedConfigError(
                    f"'{block}.overrides[{name!r}].slo_class' must be one "
                    f"of {C.SERVING_TENANTS_SLO_CLASSES}, got '{slo}'"
                )
            overrides[str(name)] = dict(spec)
        out = cls(
            enabled=bool(_pop(d, "enabled", C.SERVING_TENANTS_ENABLED_DEFAULT)),
            refill_tokens_per_second=float(
                _pop(d, "refill_tokens_per_second",
                     C.SERVING_TENANTS_REFILL_TOKENS_PER_SECOND_DEFAULT)
            ),
            burst_tokens=float(
                _pop(d, "burst_tokens", C.SERVING_TENANTS_BURST_TOKENS_DEFAULT)
            ),
            weight=float(_pop(d, "weight", C.SERVING_TENANTS_WEIGHT_DEFAULT)),
            slo_class=str(
                _pop(d, "slo_class", C.SERVING_TENANTS_SLO_CLASS_DEFAULT)
            ).lower(),
            kv_pages_max=int(
                _pop(d, "kv_pages_max", C.SERVING_TENANTS_KV_PAGES_MAX_DEFAULT)
            ),
            pinned_prefixes_max=int(
                _pop(d, "pinned_prefixes_max",
                     C.SERVING_TENANTS_PINNED_PREFIXES_MAX_DEFAULT)
            ),
            overrides=overrides,
        )
        _check_empty(d, block, _known_keys(cls))
        if out.refill_tokens_per_second < 0:
            raise DeepSpeedConfigError(
                f"'{block}.refill_tokens_per_second' must be >= 0 "
                f"(0 with burst_tokens 0 = unlimited), "
                f"got {out.refill_tokens_per_second}"
            )
        if out.burst_tokens < 0:
            raise DeepSpeedConfigError(
                f"'{block}.burst_tokens' must be >= 0, got {out.burst_tokens}"
            )
        if out.weight <= 0:
            raise DeepSpeedConfigError(
                f"'{block}.weight' must be > 0, got {out.weight}"
            )
        if out.slo_class not in C.SERVING_TENANTS_SLO_CLASSES:
            raise DeepSpeedConfigError(
                f"'{block}.slo_class' must be one of "
                f"{C.SERVING_TENANTS_SLO_CLASSES}, got '{out.slo_class}'"
            )
        if out.kv_pages_max < 0 or out.pinned_prefixes_max < 0:
            raise DeepSpeedConfigError(
                f"'{block}.kv_pages_max'/'pinned_prefixes_max' must be >= 0 "
                f"(0 = uncapped), got "
                f"{out.kv_pages_max}/{out.pinned_prefixes_max}"
            )
        return out


@dataclass
class ServingConfig:
    """``serving`` block (TPU-native extension; docs/serving.md): the
    continuous-batching slot-pool engine.  ``num_slots`` concurrent
    sequences share one fixed-shape KV pool; prompts prefill in
    ``prefill_chunk``-token chunks interleaved with decode steps;
    ``max_queue`` bounds admission (submit() rejects past it) and
    ``deadline_seconds`` expires requests that wait too long for a
    slot."""

    num_slots: int = C.SERVING_NUM_SLOTS_DEFAULT
    max_len: int = C.SERVING_MAX_LEN_DEFAULT  # 0 = derive from the engine
    kv_cache_dtype: str = C.SERVING_KV_CACHE_DTYPE_DEFAULT
    prefill_chunk: int = C.SERVING_PREFILL_CHUNK_DEFAULT
    prefill_chunks_per_step: int = C.SERVING_PREFILL_CHUNKS_PER_STEP_DEFAULT
    max_queue: int = C.SERVING_MAX_QUEUE_DEFAULT
    max_new_tokens: int = C.SERVING_MAX_NEW_TOKENS_DEFAULT
    deadline_seconds: float = C.SERVING_DEADLINE_SECONDS_DEFAULT
    # static top-k head width for per-slot sampling: traced per-request
    # top_k thresholds against the top-max_top_k logits (one executable
    # for any greedy/sampled mix); submit() rejects top_k > max_top_k
    max_top_k: int = C.SERVING_MAX_TOP_K_DEFAULT
    # -- resilience (docs/serving.md §Resilience) ----------------------
    # estimated-TTFT admission test: shed normal/low-priority submits
    # whose estimated TTFT (queue backlog / measured step rate) exceeds
    # this; 0 disables the test (hard max_queue bound still applies)
    slo_ttft_ms: float = C.SERVING_SLO_TTFT_MS_DEFAULT
    # degradation ladder: engage on queue_depth >= watermark*max_queue
    # sustained degrade_engage_steps ticks, step back down after
    # degrade_disengage_steps calm ticks (hysteresis)
    degrade_queue_watermark: float = C.SERVING_DEGRADE_QUEUE_WATERMARK_DEFAULT
    degrade_engage_steps: int = C.SERVING_DEGRADE_ENGAGE_STEPS_DEFAULT
    degrade_disengage_steps: int = C.SERVING_DEGRADE_DISENGAGE_STEPS_DEFAULT
    degrade_max_new_tokens: int = C.SERVING_DEGRADE_MAX_NEW_TOKENS_DEFAULT
    # graceful drain: SIGTERM stops admission and drains in-flight
    # requests for at most this long before the journal commit + exit 43
    drain_deadline_seconds: float = C.SERVING_DRAIN_DEADLINE_SECONDS_DEFAULT
    # write-ahead request journal ("" = off): submit/admit/first-token/
    # retire records under serving/journal.py's atomic segment protocol
    journal_dir: str = C.SERVING_JOURNAL_DIR_DEFAULT
    journal_segment_records: int = C.SERVING_JOURNAL_SEGMENT_RECORDS_DEFAULT
    journal_keep_segments: int = C.SERVING_JOURNAL_KEEP_SEGMENTS_DEFAULT
    # fleet front-door (docs/serving.md §Fleet): router + breaker +
    # hedging + supervised replica restart over N engine replicas
    fleet: FleetConfig = field(default_factory=FleetConfig)
    # paged KV pool with prefix dedup + COW + session reuse
    # (docs/serving.md §Paged KV & prefix caching)
    kvcache: KVCacheConfig = field(default_factory=KVCacheConfig)
    # stdlib HTTP front-door with chunked streaming + graceful drain
    # (docs/serving.md §Front-door)
    frontdoor: FrontdoorConfig = field(default_factory=FrontdoorConfig)
    # multi-tenant fairness/SLO/quota dimension (docs/serving.md
    # §Front-door)
    tenants: TenantsConfig = field(default_factory=TenantsConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ServingConfig":
        if d is None:
            return cls()
        d = dict(d)
        fleet = FleetConfig.from_dict(_pop(d, C.SERVING_FLEET, None))
        kvcache = KVCacheConfig.from_dict(_pop(d, C.SERVING_KVCACHE, None))
        frontdoor = FrontdoorConfig.from_dict(
            _pop(d, C.SERVING_FRONTDOOR, None))
        tenants = TenantsConfig.from_dict(_pop(d, C.SERVING_TENANTS, None))
        out = cls(
            fleet=fleet,
            kvcache=kvcache,
            frontdoor=frontdoor,
            tenants=tenants,
            num_slots=int(_pop(d, "num_slots", C.SERVING_NUM_SLOTS_DEFAULT)),
            max_len=int(_pop(d, "max_len", C.SERVING_MAX_LEN_DEFAULT)),
            kv_cache_dtype=str(
                _pop(d, "kv_cache_dtype", C.SERVING_KV_CACHE_DTYPE_DEFAULT)
            ).lower(),
            prefill_chunk=int(_pop(d, "prefill_chunk", C.SERVING_PREFILL_CHUNK_DEFAULT)),
            prefill_chunks_per_step=int(
                _pop(d, "prefill_chunks_per_step", C.SERVING_PREFILL_CHUNKS_PER_STEP_DEFAULT)
            ),
            max_queue=int(_pop(d, "max_queue", C.SERVING_MAX_QUEUE_DEFAULT)),
            max_new_tokens=int(_pop(d, "max_new_tokens", C.SERVING_MAX_NEW_TOKENS_DEFAULT)),
            deadline_seconds=float(
                _pop(d, "deadline_seconds", C.SERVING_DEADLINE_SECONDS_DEFAULT)
            ),
            max_top_k=int(_pop(d, "max_top_k", C.SERVING_MAX_TOP_K_DEFAULT)),
            slo_ttft_ms=float(_pop(d, "slo_ttft_ms", C.SERVING_SLO_TTFT_MS_DEFAULT)),
            degrade_queue_watermark=float(
                _pop(d, "degrade_queue_watermark", C.SERVING_DEGRADE_QUEUE_WATERMARK_DEFAULT)
            ),
            degrade_engage_steps=int(
                _pop(d, "degrade_engage_steps", C.SERVING_DEGRADE_ENGAGE_STEPS_DEFAULT)
            ),
            degrade_disengage_steps=int(
                _pop(d, "degrade_disengage_steps", C.SERVING_DEGRADE_DISENGAGE_STEPS_DEFAULT)
            ),
            degrade_max_new_tokens=int(
                _pop(d, "degrade_max_new_tokens", C.SERVING_DEGRADE_MAX_NEW_TOKENS_DEFAULT)
            ),
            drain_deadline_seconds=float(
                _pop(d, "drain_deadline_seconds", C.SERVING_DRAIN_DEADLINE_SECONDS_DEFAULT)
            ),
            journal_dir=str(_pop(d, "journal_dir", C.SERVING_JOURNAL_DIR_DEFAULT) or ""),
            journal_segment_records=int(
                _pop(d, "journal_segment_records", C.SERVING_JOURNAL_SEGMENT_RECORDS_DEFAULT)
            ),
            journal_keep_segments=int(
                _pop(d, "journal_keep_segments", C.SERVING_JOURNAL_KEEP_SEGMENTS_DEFAULT)
            ),
        )
        _check_empty(d, C.SERVING, _known_keys(cls))
        if out.max_top_k < 1:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.max_top_k' must be >= 1, got {out.max_top_k}"
            )
        if out.num_slots < 1:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.num_slots' must be >= 1, got {out.num_slots}"
            )
        if out.kv_cache_dtype not in C.SERVING_KV_CACHE_DTYPES:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.kv_cache_dtype' must be one of "
                f"{C.SERVING_KV_CACHE_DTYPES}, got '{out.kv_cache_dtype}'"
            )
        if out.prefill_chunk < 1:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.prefill_chunk' must be >= 1, got {out.prefill_chunk}"
            )
        if out.prefill_chunks_per_step < 1:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.prefill_chunks_per_step' must be >= 1, "
                f"got {out.prefill_chunks_per_step}"
            )
        if out.max_len < 0:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.max_len' must be >= 0 (0 derives it from the "
                f"engine's capacity), got {out.max_len}"
            )
        if out.max_len and out.max_len % out.prefill_chunk:
            # chunk writes land via dynamic_update_slice, whose start
            # clamps near the cache end — a chunk-multiple capacity is
            # what guarantees the last chunk never clamps (docs/serving.md)
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.max_len' ({out.max_len}) must be a multiple of "
                f"prefill_chunk ({out.prefill_chunk})"
            )
        if out.max_queue < 0:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.max_queue' must be >= 0, got {out.max_queue}"
            )
        if out.max_new_tokens < 1:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.max_new_tokens' must be >= 1, got {out.max_new_tokens}"
            )
        if out.deadline_seconds < 0:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.deadline_seconds' must be >= 0, got {out.deadline_seconds}"
            )
        if out.slo_ttft_ms < 0:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.slo_ttft_ms' must be >= 0 (0 disables the "
                f"admission test), got {out.slo_ttft_ms}"
            )
        if not 0.0 < out.degrade_queue_watermark <= 1.0:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.degrade_queue_watermark' must be in (0, 1] "
                f"(a fraction of max_queue), got {out.degrade_queue_watermark}"
            )
        if out.degrade_engage_steps < 1 or out.degrade_disengage_steps < 1:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.degrade_engage_steps'/'degrade_disengage_steps' must "
                f"be >= 1, got {out.degrade_engage_steps}/{out.degrade_disengage_steps}"
            )
        if out.degrade_max_new_tokens < 0:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.degrade_max_new_tokens' must be >= 0 (0 disables "
                f"the clamp rung), got {out.degrade_max_new_tokens}"
            )
        if out.drain_deadline_seconds < 0:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.drain_deadline_seconds' must be >= 0, "
                f"got {out.drain_deadline_seconds}"
            )
        if out.journal_segment_records < 1:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.journal_segment_records' must be >= 1, "
                f"got {out.journal_segment_records}"
            )
        if out.journal_keep_segments < 1:
            raise DeepSpeedConfigError(
                f"'{C.SERVING}.journal_keep_segments' must be >= 1, "
                f"got {out.journal_keep_segments}"
            )
        return out


@dataclass
class SanitizerConfig:
    """``sanitizer`` block (ds_san; docs/ds_san.md).  Opt-in runtime
    checkers around the engine step: recompile-storm detection, implicit
    transfer attribution, use-after-donation, sharding drift, NaN
    provenance.  ``DS_SAN=1`` activates the env defaults without a
    config edit — the launch-time switch arms the sanitizer even when
    this block is absent or says disabled."""

    enabled: bool = C.SAN_ENABLED_DEFAULT
    checkers: List[str] = field(default_factory=lambda: list(C.SAN_CHECKERS))
    compile_budget: int = C.SAN_COMPILE_BUDGET_DEFAULT
    drift_interval: int = C.SAN_DRIFT_INTERVAL_DEFAULT
    report_path: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "SanitizerConfig":
        if d is None:
            return cls()
        d = dict(d)
        explicit_enabled = "enabled" in d
        raw = _pop(d, "checkers", None)
        checkers = list(C.SAN_CHECKERS) if raw is None else [str(c).lower() for c in raw]
        out = cls(
            enabled=bool(_pop(d, "enabled", C.SAN_ENABLED_DEFAULT)),
            checkers=checkers,
            compile_budget=int(_pop(d, "compile_budget", C.SAN_COMPILE_BUDGET_DEFAULT)),
            drift_interval=int(_pop(d, "drift_interval", C.SAN_DRIFT_INTERVAL_DEFAULT)),
            report_path=_pop(d, "report_path", None),
        )
        _check_empty(d, C.SANITIZER, _known_keys(cls))
        unknown = set(out.checkers) - set(C.SAN_CHECKERS)
        if unknown:
            raise DeepSpeedConfigError(
                f"'{C.SANITIZER}.checkers' has unknown checker(s) "
                f"{sorted(unknown)}; valid: {C.SAN_CHECKERS}"
            )
        if out.compile_budget < 1:
            raise DeepSpeedConfigError(
                f"'{C.SANITIZER}.compile_budget' must be >= 1, got {out.compile_budget}"
            )
        if out.drift_interval < 1:
            raise DeepSpeedConfigError(
                f"'{C.SANITIZER}.drift_interval' must be >= 1, got {out.drift_interval}"
            )
        # an `enabled` key written in the JSON is an explicit decision:
        # `enabled: false` there opts the engine out even of a
        # process-wide (env/CLI-installed) sanitizer — but a block that
        # only tunes knobs must not disarm a DS_SAN=1 launch
        out._explicit = explicit_enabled
        return out

    @classmethod
    def from_env(cls, base: Optional["SanitizerConfig"] = None) -> "SanitizerConfig":
        """``DS_SAN=1`` defaults, refined by ``DS_SAN_CHECKERS`` (comma
        list), ``DS_SAN_BUDGET`` and ``DS_SAN_DRIFT_INTERVAL``.  ``base``
        (a knobs-only config block from the JSON) supplies the starting
        values so an env-armed launch keeps the block's tuning."""
        import os

        d: Dict[str, Any] = {"enabled": os.environ.get("DS_SAN", "") == "1"}
        if base is not None:
            d.update(
                checkers=list(base.checkers),
                compile_budget=base.compile_budget,
                drift_interval=base.drift_interval,
                report_path=base.report_path,
            )
        raw = os.environ.get("DS_SAN_CHECKERS")
        if raw:
            d["checkers"] = [c.strip() for c in raw.split(",") if c.strip()]
        if os.environ.get("DS_SAN_BUDGET"):
            d["compile_budget"] = int(os.environ["DS_SAN_BUDGET"])
        if os.environ.get("DS_SAN_DRIFT_INTERVAL"):
            d["drift_interval"] = int(os.environ["DS_SAN_DRIFT_INTERVAL"])
        if os.environ.get("DS_SAN_REPORT"):
            d["report_path"] = os.environ["DS_SAN_REPORT"]
        return cls.from_dict(d)


@dataclass
class ActivationCheckpointingConfig:
    """Reference ``runtime/activation_checkpointing/config.py``.  On TPU,
    ``partition_activations`` maps to sharding saved residuals over the
    model axis; ``cpu_checkpointing`` maps to a host-offload remat policy."""

    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ActivationCheckpointingConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            partition_activations=bool(_pop(d, "partition_activations", False)),
            contiguous_memory_optimization=bool(_pop(d, "contiguous_memory_optimization", False)),
            cpu_checkpointing=bool(_pop(d, "cpu_checkpointing", False)),
            number_checkpoints=_pop(d, "number_checkpoints", None),
            synchronize_checkpoint_boundary=bool(_pop(d, "synchronize_checkpoint_boundary", False)),
            profile=bool(_pop(d, "profile", False)),
        )
        _check_empty(d, "activation_checkpointing", _known_keys(cls))
        return out


@dataclass
class FlopsProfilerConfig:
    enabled: bool = False
    # default 2, not the reference's 1: under JAX, step 1 includes the XLA
    # compile, which would make the timed window meaningless
    profile_step: int = 2
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "FlopsProfilerConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_pop(d, "enabled", False)),
            profile_step=int(_pop(d, "profile_step", 2)),
            module_depth=int(_pop(d, "module_depth", -1)),
            top_modules=int(_pop(d, "top_modules", 1)),
            detailed=bool(_pop(d, "detailed", True)),
            output_file=_pop(d, "output_file", None),
        )
        _check_empty(d, "flops_profiler", _known_keys(cls))
        return out


@dataclass
class TelemetryConfig:
    """``telemetry`` block (TPU-native extension; docs/telemetry.md):
    the unified observability plane.  ``enabled`` arms the in-process
    metrics registry (host dict updates only — measured <1% steps/s;
    docs/telemetry.md overhead table); ``exporters`` turn on background
    sinks (``jsonl`` | ``prometheus`` | ``tensorboard``) flushing every
    ``export_interval_seconds`` off the hot path; ``trace`` records
    Chrome-trace spans (StepTimeline phases, checkpoint writer, serving
    request lifecycles) exported to ``trace_path``; ``profiler_dir``
    enables the programmatic ``jax.profiler`` window capture
    (on demand, or on the first serving TTFT above
    ``slo_ttft_breach_ms``); ``aggregate`` piggybacks compact metric
    snapshots on the supervision heartbeat so rank 0 exports cluster
    min/mean/max with dead-rank flags in the same stream."""

    enabled: bool = C.TELEMETRY_ENABLED_DEFAULT
    ring: int = C.TELEMETRY_RING_DEFAULT
    exporters: Tuple[str, ...] = ()
    export_interval_seconds: float = C.TELEMETRY_EXPORT_INTERVAL_DEFAULT
    output_path: str = C.TELEMETRY_OUTPUT_PATH_DEFAULT
    trace: bool = C.TELEMETRY_TRACE_ENABLED_DEFAULT
    trace_path: str = ""  # "" = <output_path>/trace.json
    trace_buffer_events: int = C.TELEMETRY_TRACE_BUFFER_DEFAULT
    profiler_dir: str = ""
    profiler_capture_ms: int = C.TELEMETRY_PROFILER_CAPTURE_MS_DEFAULT
    slo_ttft_breach_ms: float = C.TELEMETRY_SLO_TTFT_BREACH_MS_DEFAULT
    aggregate: bool = C.TELEMETRY_AGGREGATE_DEFAULT
    # per-kernel cost attribution + runtime anomaly watch (ISSUE 11)
    attribution: bool = C.TELEMETRY_ATTRIBUTION_DEFAULT
    attribution_max_hlo_mb: float = C.TELEMETRY_ATTRIBUTION_MAX_HLO_MB_DEFAULT
    spike_factor: float = C.TELEMETRY_SPIKE_FACTOR_DEFAULT
    spike_min_window: int = C.TELEMETRY_SPIKE_MIN_WINDOW_DEFAULT
    straggler_factor: float = C.TELEMETRY_STRAGGLER_FACTOR_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "TelemetryConfig":
        if d is None:
            return cls()
        d = dict(d)
        raw_exp = _pop(d, "exporters", ())
        if isinstance(raw_exp, str):
            raw_exp = [raw_exp]
        out = cls(
            enabled=bool(_pop(d, "enabled", C.TELEMETRY_ENABLED_DEFAULT)),
            ring=int(_pop(d, "ring", C.TELEMETRY_RING_DEFAULT)),
            exporters=tuple(str(e).lower() for e in raw_exp),
            export_interval_seconds=float(
                _pop(d, "export_interval_seconds", C.TELEMETRY_EXPORT_INTERVAL_DEFAULT)
            ),
            output_path=str(_pop(d, C.TELEMETRY_OUTPUT_PATH, C.TELEMETRY_OUTPUT_PATH_DEFAULT)),
            trace=bool(_pop(d, "trace", C.TELEMETRY_TRACE_ENABLED_DEFAULT)),
            trace_path=str(_pop(d, "trace_path", "")),
            trace_buffer_events=int(
                _pop(d, "trace_buffer_events", C.TELEMETRY_TRACE_BUFFER_DEFAULT)
            ),
            profiler_dir=str(_pop(d, "profiler_dir", "")),
            profiler_capture_ms=int(
                _pop(d, "profiler_capture_ms", C.TELEMETRY_PROFILER_CAPTURE_MS_DEFAULT)
            ),
            slo_ttft_breach_ms=float(
                _pop(d, "slo_ttft_breach_ms", C.TELEMETRY_SLO_TTFT_BREACH_MS_DEFAULT)
            ),
            aggregate=bool(_pop(d, "aggregate", C.TELEMETRY_AGGREGATE_DEFAULT)),
            attribution=bool(_pop(d, "attribution", C.TELEMETRY_ATTRIBUTION_DEFAULT)),
            attribution_max_hlo_mb=float(
                _pop(d, "attribution_max_hlo_mb", C.TELEMETRY_ATTRIBUTION_MAX_HLO_MB_DEFAULT)
            ),
            spike_factor=float(_pop(d, "spike_factor", C.TELEMETRY_SPIKE_FACTOR_DEFAULT)),
            spike_min_window=int(
                _pop(d, "spike_min_window", C.TELEMETRY_SPIKE_MIN_WINDOW_DEFAULT)
            ),
            straggler_factor=float(
                _pop(d, "straggler_factor", C.TELEMETRY_STRAGGLER_FACTOR_DEFAULT)
            ),
        )
        _check_empty(d, C.TELEMETRY, _known_keys(cls))
        unknown = set(out.exporters) - set(C.TELEMETRY_EXPORTERS)
        if unknown:
            raise DeepSpeedConfigError(
                f"'{C.TELEMETRY}.exporters' must be a subset of "
                f"{C.TELEMETRY_EXPORTERS}, got {sorted(unknown)}"
            )
        if out.ring < 16:
            raise DeepSpeedConfigError(
                f"'{C.TELEMETRY}.ring' must be >= 16, got {out.ring}"
            )
        if out.export_interval_seconds <= 0:
            raise DeepSpeedConfigError(
                f"'{C.TELEMETRY}.export_interval_seconds' must be > 0, "
                f"got {out.export_interval_seconds}"
            )
        if out.trace_buffer_events < 1000:
            raise DeepSpeedConfigError(
                f"'{C.TELEMETRY}.trace_buffer_events' must be >= 1000, "
                f"got {out.trace_buffer_events}"
            )
        if out.profiler_capture_ms <= 0:
            raise DeepSpeedConfigError(
                f"'{C.TELEMETRY}.profiler_capture_ms' must be > 0, "
                f"got {out.profiler_capture_ms}"
            )
        if out.slo_ttft_breach_ms < 0:
            raise DeepSpeedConfigError(
                f"'{C.TELEMETRY}.slo_ttft_breach_ms' must be >= 0, "
                f"got {out.slo_ttft_breach_ms}"
            )
        if out.spike_factor <= 1.0:
            raise DeepSpeedConfigError(
                f"'{C.TELEMETRY}.spike_factor' must be > 1, got {out.spike_factor}"
            )
        if out.straggler_factor <= 1.0:
            raise DeepSpeedConfigError(
                f"'{C.TELEMETRY}.straggler_factor' must be > 1, "
                f"got {out.straggler_factor}"
            )
        if out.attribution_max_hlo_mb <= 0:
            raise DeepSpeedConfigError(
                f"'{C.TELEMETRY}.attribution_max_hlo_mb' must be > 0, "
                f"got {out.attribution_max_hlo_mb}"
            )
        return out


@dataclass
class TensorboardConfig:
    enabled: bool = C.TENSORBOARD_ENABLED_DEFAULT
    output_path: str = C.TENSORBOARD_OUTPUT_PATH_DEFAULT
    job_name: str = C.TENSORBOARD_JOB_NAME_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "TensorboardConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_pop(d, C.TENSORBOARD_ENABLED, C.TENSORBOARD_ENABLED_DEFAULT)),
            output_path=_pop(d, C.TENSORBOARD_OUTPUT_PATH, C.TENSORBOARD_OUTPUT_PATH_DEFAULT),
            job_name=_pop(d, C.TENSORBOARD_JOB_NAME, C.TENSORBOARD_JOB_NAME_DEFAULT),
        )
        _check_empty(d, C.TENSORBOARD, _known_keys(cls))
        return out


@dataclass
class PipelineConfig:
    """``pipeline`` block (reference ``runtime/config.py:409`` area)."""

    stages: Any = "auto"
    partition: str = "best"
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    # "1f1b" (reference TrainSchedule, schedule.py:182 — live activations
    # bounded by the stage count) or "gpipe" (all-forward-then-all-
    # backward — lower bubble in the compiled formulation, O(M) memory)
    schedule: str = "1f1b"

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "PipelineConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            stages=_pop(d, "stages", "auto"),
            partition=_pop(d, "partition", "best"),
            seed_layers=bool(_pop(d, "seed_layers", False)),
            activation_checkpoint_interval=int(_pop(d, "activation_checkpoint_interval", 0)),
            schedule=str(_pop(d, "schedule", "1f1b")).lower(),
        )
        if out.schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"pipeline.schedule must be '1f1b' or 'gpipe', got {out.schedule!r}")
        _check_empty(d, C.PIPELINE, _known_keys(cls))
        return out


@dataclass
class AioConfig:
    """``aio`` block (reference ``runtime/swap_tensor/aio_config.py``)."""

    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "AioConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            block_size=int(_pop(d, "block_size", 1048576)),
            queue_depth=int(_pop(d, "queue_depth", 8)),
            thread_count=int(_pop(d, "thread_count", 1)),
            single_submit=bool(_pop(d, "single_submit", False)),
            overlap_events=bool(_pop(d, "overlap_events", True)),
        )
        _check_empty(d, "aio", _known_keys(cls))
        return out


@dataclass
class QuantizeTrainingConfig:
    """MoQ progressive quantize-training (reference ``runtime/config.py:186-221``)."""

    enabled: bool = False
    quantize_verbose: bool = False
    quantizer_kernel: bool = False
    quantize_type: str = "symmetric"
    quantize_bits_start: int = 16
    quantize_bits_target: int = 8
    quantize_schedule_offset: int = 1000
    quantize_groups: int = 1
    fp16_mixed_quantize: bool = False
    quantize_change_ratio: float = 0.001
    quantize_rounding: str = "nearest"  # nearest | stochastic
    eigenvalue_enabled: bool = False
    eigenvalue_verbose: bool = False
    eigenvalue_max_iter: int = 100
    eigenvalue_tol: float = 1e-2
    eigenvalue_stability: float = 1e-6
    eigenvalue_gas_boundary_resolution: int = 1
    eigenvalue_layer_name: str = "bert.encoder.layer"
    eigenvalue_layer_num: int = 0

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "QuantizeTrainingConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_pop(d, "enabled", False)),
            quantize_verbose=bool(_pop(d, "quantize_verbose", False)),
            quantizer_kernel=bool(_pop(d, "quantizer_kernel", False)),
            quantize_type=_pop(d, "quantize_type", "symmetric"),
            quantize_bits_start=int(_pop_alias(d, "quantize_bits_start", "start_bits", 16, "quantize_training")),
            quantize_bits_target=int(_pop_alias(d, "quantize_bits_target", "target_bits", 8, "quantize_training")),
            quantize_schedule_offset=int(_pop(d, "quantize_schedule_offset", 1000)),
            quantize_groups=int(_pop(d, "quantize_groups", 1)),
            fp16_mixed_quantize=bool(_pop(d, "fp16_mixed_quantize", False)),
            quantize_change_ratio=float(_pop(d, "quantize_change_ratio", 0.001)),
            quantize_rounding=_pop(d, "quantize_rounding", "nearest"),
            eigenvalue_enabled=bool(_pop(d, "eigenvalue_enabled", False)),
            eigenvalue_verbose=bool(_pop(d, "eigenvalue_verbose", False)),
            eigenvalue_max_iter=int(_pop(d, "eigenvalue_max_iter", 100)),
            eigenvalue_tol=float(_pop(d, "eigenvalue_tol", 1e-2)),
            eigenvalue_stability=float(_pop(d, "eigenvalue_stability", 1e-6)),
            eigenvalue_gas_boundary_resolution=int(_pop(d, "eigenvalue_gas_boundary_resolution", 1)),
            eigenvalue_layer_name=_pop(d, "eigenvalue_layer_name", "bert.encoder.layer"),
            eigenvalue_layer_num=int(_pop(d, "eigenvalue_layer_num", 0)),
        )
        _check_empty(d, "quantize_training", _known_keys(cls, "start_bits", "target_bits"))
        return out


@dataclass
class ProgressiveLayerDropConfig:
    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ProgressiveLayerDropConfig":
        if d is None:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_pop(d, "enabled", False)),
            theta=float(_pop(d, "theta", 0.5)),
            gamma=float(_pop(d, "gamma", 0.001)),
        )
        _check_empty(d, "progressive_layer_drop", _known_keys(cls))
        return out


@dataclass
class SparseAttentionConfig:
    mode: Optional[str] = None  # dense|fixed|variable|bigbird|bslongformer
    params: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "SparseAttentionConfig":
        if d is None:
            return cls()
        d = dict(d)
        mode = _pop(d, "mode", None)
        # remaining keys are mode params (block, different_layout_per_head, ...)
        return cls(mode=mode, params=d)


_KNOWN_TOP_LEVEL = {
    C.TRAIN_BATCH_SIZE,
    C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
    C.GRADIENT_ACCUMULATION_STEPS,
    C.OPTIMIZER,
    C.SCHEDULER,
    C.FP16,
    C.BF16,
    C.AMP,
    C.GRADIENT_CLIPPING,
    C.PRESCALE_GRADIENTS,
    C.GRADIENT_PREDIVIDE_FACTOR,
    C.SPARSE_GRADIENTS,
    C.ALLREDUCE_ALWAYS_FP32,
    C.ZERO_OPTIMIZATION,
    C.STEPS_PER_PRINT,
    C.WALL_CLOCK_BREAKDOWN,
    C.MEMORY_BREAKDOWN,
    C.DUMP_STATE,
    C.DISABLE_ALLGATHER,
    C.TENSORBOARD,
    C.PIPELINE,
    C.CHECKPOINT_TAG_VALIDATION,
    C.MESH,
    C.RESILIENCE,
    C.OVERLAP,
    C.SANITIZER,
    C.COMM,
    C.SERVING,
    C.TELEMETRY,
    C.KERNELS,
    "activation_checkpointing",
    "flops_profiler",
    "aio",
    "elasticity",
    "quantize_training",
    "progressive_layer_drop",
    "sparse_attention",
    "zero_allow_untested_optimizer",
    "dataloader_drop_last",
    "seed",
}


@dataclass
class KernelsConfig:
    """``kernels`` block (TPU-native extension; docs/kernels.md): the
    Pallas kernel suite.  ``enabled``: ``"auto"`` arms the suite on
    TPU-class backends only (the lax/XLA paths stay the CPU ground
    truth); ``true``/``false`` force it.  ``flash_decode`` /
    ``fused_update`` subtract individual kernels from an armed suite.
    ``autotune`` is the block-size tuner mode (``off`` = deterministic
    defaults only, ``cache`` = read cached measured winners, ``force``
    = allow re-measuring); ``autotune_cache_path`` overrides where the
    JSON cache lives (default: next to the persistent compile cache).
    The ``DS_KERNELS`` / ``DS_KERNEL_AUTOTUNE`` env vars win over this
    block (escape hatches)."""

    enabled: Any = C.KERNELS_ENABLED_AUTO
    flash_decode: bool = C.KERNELS_FLASH_DECODE_DEFAULT
    fused_update: bool = C.KERNELS_FUSED_UPDATE_DEFAULT
    autotune: str = C.KERNELS_AUTOTUNE_DEFAULT
    autotune_cache_path: str = ""

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "KernelsConfig":
        if d is None:
            return cls()
        d = dict(d)
        enabled = _pop(d, "enabled", C.KERNELS_ENABLED_AUTO)
        out = cls(
            enabled=enabled,
            flash_decode=bool(_pop(d, "flash_decode", C.KERNELS_FLASH_DECODE_DEFAULT)),
            fused_update=bool(_pop(d, "fused_update", C.KERNELS_FUSED_UPDATE_DEFAULT)),
            autotune=str(_pop(d, "autotune", C.KERNELS_AUTOTUNE_DEFAULT)).lower(),
            autotune_cache_path=str(_pop(d, "autotune_cache_path", "")),
        )
        _check_empty(d, C.KERNELS, _known_keys(cls))
        if out.enabled not in C.KERNELS_ENABLED_CHOICES:
            raise DeepSpeedConfigError(
                f"'{C.KERNELS}.enabled' must be one of {C.KERNELS_ENABLED_CHOICES}, "
                f"got {out.enabled!r}"
            )
        if out.autotune not in C.KERNELS_AUTOTUNE_MODES:
            raise DeepSpeedConfigError(
                f"'{C.KERNELS}.autotune' must be one of {C.KERNELS_AUTOTUNE_MODES}, "
                f"got {out.autotune!r}"
            )
        return out


class DeepSpeedConfig:
    """Parse a config dict / JSON path and resolve the batch-size triad.

    ``world_size`` here is the *data-parallel* world size (``data × fsdp``
    mesh axes), matching the reference's use of dp_world_size in
    ``runtime/config.py:736-898``.
    """

    def __init__(self, config: Any, world_size: Optional[int] = None, mesh_shape: Optional[Dict[str, int]] = None):
        if isinstance(config, str):
            with open(config, "r") as f:
                d = json.load(f)
        elif isinstance(config, dict):
            d = json.loads(json.dumps(config))  # deep copy + json-type check
        else:
            raise DeepSpeedConfigError(f"config must be a dict or a path to a JSON file, got {type(config)}")

        unknown = set(d.keys()) - _KNOWN_TOP_LEVEL
        if unknown:
            raise DeepSpeedConfigError(
                "Unknown top-level config key(s): "
                + _describe_unknown(unknown, "", _KNOWN_TOP_LEVEL)
            )

        self._raw = d
        self.train_batch_size = d.get(C.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu = d.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps = d.get(C.GRADIENT_ACCUMULATION_STEPS)

        self.optimizer = OptimizerConfig.from_dict(d.get(C.OPTIMIZER))
        self.scheduler = SchedulerConfig.from_dict(d.get(C.SCHEDULER))
        self.fp16 = Fp16Config.from_dict(d.get(C.FP16))
        self.bf16 = Bf16Config.from_dict(d.get(C.BF16))
        self.zero_config = ZeroConfig.from_dict(d.get(C.ZERO_OPTIMIZATION))
        self.mesh = MeshConfig.from_dict(d.get(C.MESH))
        if mesh_shape:
            for axis, size in mesh_shape.items():
                setattr(self.mesh, axis, size)
        self.activation_checkpointing = ActivationCheckpointingConfig.from_dict(d.get("activation_checkpointing"))
        self.flops_profiler = FlopsProfilerConfig.from_dict(d.get("flops_profiler"))
        self.tensorboard = TensorboardConfig.from_dict(d.get(C.TENSORBOARD))
        self.pipeline = PipelineConfig.from_dict(d.get(C.PIPELINE))
        self.aio = AioConfig.from_dict(d.get("aio"))
        self.quantize_training = QuantizeTrainingConfig.from_dict(d.get("quantize_training"))
        self.progressive_layer_drop = ProgressiveLayerDropConfig.from_dict(d.get("progressive_layer_drop"))
        self.sparse_attention = SparseAttentionConfig.from_dict(d.get("sparse_attention"))
        self.resilience = ResilienceConfig.from_dict(d.get(C.RESILIENCE))
        self.overlap = OverlapConfig.from_dict(d.get(C.OVERLAP))
        self.sanitizer = SanitizerConfig.from_dict(d.get(C.SANITIZER))
        self.comm = CommConfig.from_dict(d.get(C.COMM))
        self.serving = ServingConfig.from_dict(d.get(C.SERVING))
        self.telemetry = TelemetryConfig.from_dict(d.get(C.TELEMETRY))
        self.kernels = KernelsConfig.from_dict(d.get(C.KERNELS))
        self.elasticity_dict = d.get("elasticity")

        self.gradient_clipping = float(d.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT))
        self.prescale_gradients = bool(d.get(C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT))
        self.gradient_predivide_factor = float(d.get(C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT))
        self.sparse_gradients_enabled = bool(d.get(C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT))
        self.allreduce_always_fp32 = bool(d.get(C.ALLREDUCE_ALWAYS_FP32, C.ALLREDUCE_ALWAYS_FP32_DEFAULT))
        self.steps_per_print = int(d.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT))
        self.wall_clock_breakdown = bool(d.get(C.WALL_CLOCK_BREAKDOWN, C.WALL_CLOCK_BREAKDOWN_DEFAULT))
        self.memory_breakdown = bool(d.get(C.MEMORY_BREAKDOWN, C.MEMORY_BREAKDOWN_DEFAULT))
        self.dump_state = bool(d.get(C.DUMP_STATE, C.DUMP_STATE_DEFAULT))
        self.disable_allgather = bool(d.get(C.DISABLE_ALLGATHER, C.DISABLE_ALLGATHER_DEFAULT))
        self.checkpoint_tag_validation_mode = d.get(C.CHECKPOINT_TAG_VALIDATION, C.CHECKPOINT_TAG_VALIDATION_DEFAULT)
        self.zero_allow_untested_optimizer = bool(d.get("zero_allow_untested_optimizer", False))
        self.dataloader_drop_last = bool(d.get("dataloader_drop_last", False))
        self.seed = int(d.get("seed", 42))

        if self.checkpoint_tag_validation_mode not in C.CHECKPOINT_TAG_VALIDATION_MODES:
            raise DeepSpeedConfigError(
                f"checkpoint_tag_validation must be one of {C.CHECKPOINT_TAG_VALIDATION_MODES}"
            )
        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")

        self.world_size = world_size if world_size is not None else 1
        self._resolve_batch_triad()

    # --- batch triad (reference runtime/config.py:736-898) ---
    def _resolve_batch_triad(self) -> None:
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        ws = self.world_size

        if train is not None and micro is not None and gas is not None:
            pass
        elif train is not None and micro is not None:
            gas, rem = divmod(train, micro * ws)
            if rem:
                raise DeepSpeedConfigError(
                    f"train_batch_size ({train}) not divisible by micro_batch*world_size ({micro}*{ws})"
                )
        elif train is not None and gas is not None:
            micro, rem = divmod(train, gas * ws)
            if rem:
                raise DeepSpeedConfigError(
                    f"train_batch_size ({train}) not divisible by grad_accum*world_size ({gas}*{ws})"
                )
        elif micro is not None and gas is not None:
            train = micro * gas * ws
        elif train is not None:
            gas = 1
            micro, rem = divmod(train, ws)
            if rem:
                raise DeepSpeedConfigError(f"train_batch_size ({train}) not divisible by world_size ({ws})")
        elif micro is not None:
            gas = 1
            train = micro * ws
        else:
            raise DeepSpeedConfigError(
                "At least one of train_batch_size / train_micro_batch_size_per_gpu must be set"
            )

        self.train_batch_size = int(train)
        self.train_micro_batch_size_per_gpu = int(micro)
        self.gradient_accumulation_steps = int(gas)
        if self.train_batch_size != self.train_micro_batch_size_per_gpu * self.gradient_accumulation_steps * ws:
            raise DeepSpeedConfigError(
                f"Batch triad check failed: {self.train_batch_size} != "
                f"{self.train_micro_batch_size_per_gpu} * {self.gradient_accumulation_steps} * {ws}"
            )

    # --- convenience ---
    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self) -> int:
        return self.zero_config.stage

    @property
    def compute_dtype(self) -> str:
        if self.fp16.enabled:
            return "float16"
        if self.bf16.enabled:
            return "bfloat16"
        return "float32"

    def print_config(self) -> str:
        return json.dumps(self._raw, indent=2, sort_keys=True)
