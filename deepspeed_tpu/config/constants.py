"""Config keys and defaults.

Mirrors the key/default tables of the reference's ``runtime/constants.py``
(406 LoC of KEY/DEFAULT pairs) and ``runtime/zero/constants.py`` — kept as
module-level constants so recipes written against the reference's JSON
surface parse unchanged.  bf16 is the TPU-native mixed-precision mode; the
``fp16`` block is accepted for compatibility and drives the same master-weight
machinery (loss scaling defaults off under bf16).
"""

#############################################
# Batch size triad
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False

SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"

MAX_GRAD_NORM = "max_grad_norm"

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
SGD_OPTIMIZER = "sgd"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER,
    ADAMW_OPTIMIZER,
    LAMB_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER,
    ONEBIT_LAMB_OPTIMIZER,
    SGD_OPTIMIZER,
]

#############################################
# Precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1

BF16 = "bf16"
BF16_ENABLED = "enabled"
BF16_ENABLED_DEFAULT = False

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

#############################################
# Gradient handling
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

ALLREDUCE_ALWAYS_FP32 = "fp32_allreduce"
ALLREDUCE_ALWAYS_FP32_DEFAULT = False

#############################################
# ZeRO
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

ZERO_STAGE = "stage"
ZERO_STAGE_DEFAULT = 0
ZERO_OPTIMIZATION_DISABLED = 0
ZERO_OPTIMIZATION_OPTIMIZER_STATES = 1
ZERO_OPTIMIZATION_GRADIENTS = 2
ZERO_OPTIMIZATION_WEIGHTS = 3
MAX_STAGE_ZERO_OPTIMIZATION = ZERO_OPTIMIZATION_WEIGHTS

#############################################
# Misc engine knobs
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

GRADIENT_ACCUMULATION_BOUNDARY = "gradient_accumulation_boundary"

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

#############################################
# Monitoring
#############################################
TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"

#############################################
# Pipeline
#############################################
PIPELINE = "pipeline"

#############################################
# Checkpoint tag validation
#############################################
CHECKPOINT_TAG_VALIDATION = "checkpoint_tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]

#############################################
# Mesh (TPU-native extension: named-axis SPMD mesh replaces process groups)
#############################################
MESH = "mesh"

#############################################
# Resilience (atomic checkpoints, preemption watchdog, failure policies)
#############################################
RESILIENCE = "resilience"

RESILIENCE_CHECKPOINT = "checkpoint"
CHECKPOINT_ATOMIC_DEFAULT = True
CHECKPOINT_VERIFY_ON_LOAD_DEFAULT = True
CHECKPOINT_CHECKSUM_DEFAULT = "sha256"
CHECKPOINT_CHECKSUM_ALGORITHMS = ["sha256", "crc32", "none"]
CHECKPOINT_KEEP_LAST_N_DEFAULT = 0  # 0 = keep everything
CHECKPOINT_KEEP_EVERY_DEFAULT = 0  # 0 = no step-multiple pinning
CHECKPOINT_FAIL_ON_MISSING = "fail_on_missing"
CHECKPOINT_FAIL_ON_MISSING_DEFAULT = False

RESILIENCE_WATCHDOG = "watchdog"
WATCHDOG_ENABLED_DEFAULT = False
WATCHDOG_GRACE_SECONDS_DEFAULT = 60.0
WATCHDOG_EXIT_CODE_DEFAULT = 43  # "preempted and saved" (docs/resilience.md)

RESILIENCE_RETRY = "retry"
RETRY_MAX_ATTEMPTS_DEFAULT = 3
RETRY_BACKOFF_SECONDS_DEFAULT = 0.5
RETRY_BACKOFF_MAX_SECONDS_DEFAULT = 30.0
RETRY_JITTER_DEFAULT = 0.25

RESILIENCE_SUPERVISION = "supervision"
SUPERVISION_ENABLED_DEFAULT = False
SUPERVISION_CHANNEL_DEFAULT = "auto"  # auto | tcp | file
SUPERVISION_CHANNELS = ["auto", "tcp", "file"]
SUPERVISION_BEAT_INTERVAL_DEFAULT = 1.0  # seconds between liveness beats
SUPERVISION_BEAT_TIMEOUT_DEFAULT = 5.0  # stale-beat death deadline
SUPERVISION_SYNC_TIMEOUT_DEFAULT = 300.0  # armed blocking-sync deadline
SUPERVISION_RESCUE_GRACE_DEFAULT = 5.0  # main-thread surface window
SUPERVISION_CONNECT_GRACE_DEFAULT = 60.0  # tcp channel connect budget
SUPERVISION_SNAPSHOT_INTERVAL_DEFAULT = 1  # step boundaries per snapshot
SUPERVISION_EXIT_CODE_DEFAULT = 44  # "peer-failed-and-saved" (docs/resilience.md)

#############################################
# Overlap (input prefetch, async checkpointing, step-phase timeline)
#############################################
OVERLAP = "overlap"

OVERLAP_PREFETCH = "prefetch"
PREFETCH_ENABLED_DEFAULT = True
PREFETCH_DEPTH_DEFAULT = 2

OVERLAP_ASYNC_CHECKPOINT = "async_checkpoint"
ASYNC_CHECKPOINT_ENABLED_DEFAULT = False
ASYNC_CHECKPOINT_DRAIN_TIMEOUT_DEFAULT = 300.0  # seconds

OVERLAP_TIMELINE = "timeline"
TIMELINE_ENABLED_DEFAULT = True
TIMELINE_WINDOW_DEFAULT = 512  # steps retained for summaries

#############################################
# Comm (strategy-selected quantized collectives; docs/comm.md)
#############################################
COMM = "comm"
COMM_STRATEGY_AUTO = "auto"
COMM_STRATEGY_DENSE = "dense"
COMM_STRATEGY_INT8 = "int8"
COMM_STRATEGY_ONEBIT = "onebit"
COMM_STRATEGIES = [
    COMM_STRATEGY_AUTO,
    COMM_STRATEGY_DENSE,
    COMM_STRATEGY_INT8,
    COMM_STRATEGY_ONEBIT,
]
# dense by default: compressed gradient exchange changes numerics and
# must be an explicit opt-in ("auto" enables the size/dtype policy)
COMM_STRATEGY_DEFAULT = COMM_STRATEGY_DENSE
COMM_THRESHOLD_BYTES_DEFAULT = 65536  # below this, dense always wins
# DCN-crossing exchanges are bandwidth-bound ~25x sooner than ICI
# (per-link GB/s gap), so `auto` compresses above a much lower floor
COMM_DCN_THRESHOLD_BYTES_DEFAULT = 4096
COMM_QUANTIZE_BITS_DEFAULT = 8  # int8 is the densest ICI-native format
COMM_ERROR_FEEDBACK_DEFAULT = True  # onebit strategy's residual carry
COMM_STOCHASTIC_ROUNDING_DEFAULT = True  # int8 strategy's unbiased rounding

#############################################
# Serving (continuous-batching slot-pool engine; docs/serving.md)
#############################################
SERVING = "serving"
SERVING_NUM_SLOTS_DEFAULT = 8  # concurrent sequences in the slot pool
SERVING_MAX_LEN_DEFAULT = 0  # 0 = derive from min(max_out_tokens, n_positions)
SERVING_KV_CACHE_DTYPE_DEFAULT = "model"  # model | int8
SERVING_KV_CACHE_DTYPES = ["model", "int8"]
SERVING_PREFILL_CHUNK_DEFAULT = 64  # prompt tokens per prefill chunk
SERVING_PREFILL_CHUNKS_PER_STEP_DEFAULT = 1  # chunks interleaved per decode step
SERVING_MAX_QUEUE_DEFAULT = 64  # waiting requests before submit() rejects
SERVING_MAX_NEW_TOKENS_DEFAULT = 128  # per-request default generation budget
SERVING_DEADLINE_SECONDS_DEFAULT = 0.0  # 0 = no queue-wait deadline
# static top-k head width for per-slot sampling (traced per-request k
# thresholds against the top-max_top_k logits; one decode executable
# for any greedy/sampled mix) — requests with top_k > max_top_k reject
SERVING_MAX_TOP_K_DEFAULT = 64
# -- serving resilience (docs/serving.md §Resilience) -----------------
# priority tiers: 0 = high (never TTFT-shed), 1 = normal, 2 = low
# (first to shed when the degradation ladder tops out)
SERVING_PRIORITY_HIGH = 0
SERVING_PRIORITY_NORMAL = 1
SERVING_PRIORITY_LOW = 2
SERVING_SLO_TTFT_MS_DEFAULT = 0.0  # 0 = no estimated-TTFT admission test
# overload shed floor: a retry_after below this tells clients nothing
SERVING_RETRY_AFTER_MIN_SECONDS_DEFAULT = 0.05
# degradation ladder: engage when queue_depth >= watermark * max_queue
# sustained engage_steps ticks; step back down after disengage_steps
# calm ticks (hysteresis — disengage slower than engage)
SERVING_DEGRADE_QUEUE_WATERMARK_DEFAULT = 0.75
SERVING_DEGRADE_ENGAGE_STEPS_DEFAULT = 8
SERVING_DEGRADE_DISENGAGE_STEPS_DEFAULT = 16
SERVING_DEGRADE_MAX_NEW_TOKENS_DEFAULT = 32  # rung-1 clamp; 0 disables the rung
SERVING_DRAIN_DEADLINE_SECONDS_DEFAULT = 30.0  # SIGTERM in-flight drain budget
SERVING_JOURNAL_DIR_DEFAULT = ""  # "" = request journaling off
SERVING_JOURNAL_SEGMENT_RECORDS_DEFAULT = 512  # records per WAL segment
SERVING_JOURNAL_KEEP_SEGMENTS_DEFAULT = 4  # sealed segments before compaction
# -- paged KV cache (serving.kvcache.*; docs/serving.md §Paged KV) ----
SERVING_KVCACHE = "kvcache"
SERVING_KVCACHE_ENABLED_DEFAULT = False  # paged pool off = slot-contiguous pool
SERVING_KVCACHE_PAGE_LEN_DEFAULT = 128  # tokens per KV page (kernel wants %128)
SERVING_KVCACHE_NUM_PAGES_DEFAULT = 0  # 0 = derive (garbage page + 2x slot capacity)
SERVING_KVCACHE_SESSION_TTL_SECONDS_DEFAULT = 0.0  # 0 = warm sessions never expire
SERVING_KVCACHE_SPILL_DIR_DEFAULT = ""  # "" = cold sessions drop instead of spill
# -- hierarchical KV tiering (serving.kvcache.tiers.*; docs/serving.md
# §KV tiering): HBM (T0) -> pinned host memory (T1) -> disk (T2) ------
SERVING_KVCACHE_TIERS = "tiers"
SERVING_KVCACHE_TIERS_ENABLED_DEFAULT = False
SERVING_KVCACHE_TIERS_HOST_PAGES_DEFAULT = 0  # T1 page cap; 0 = unbounded
SERVING_KVCACHE_TIERS_DISK_DIR_DEFAULT = ""  # "" = no T2 (host tier only)
SERVING_KVCACHE_TIERS_RESIDENCY_WINDOW_DEFAULT = 0  # tokens kept in T0 per parked session; 0 = all
SERVING_KVCACHE_TIERS_DEMOTE_WATERMARK_DEFAULT = 0.75  # demote when pages_live exceeds this fraction
SERVING_KVCACHE_TIERS_PREFETCH_AHEAD_DEFAULT = 4  # queued admits prefetched per tick
SERVING_KVCACHE_TIERS_DEMOTE_BATCH_DEFAULT = 4  # entries demoted per tick (bounds step-boundary traffic)
# -- fleet front-door (serving.fleet.*; docs/serving.md §Fleet) -------
SERVING_FLEET = "fleet"
SERVING_FLEET_REPLICAS_DEFAULT = 1  # engine replicas behind the router
SERVING_FLEET_ROUTE_RETRIES_DEFAULT = 2  # extra replicas tried per submit
# circuit breaker: consecutive failures that trip a replica OPEN, then
# seeded-jitter exponential backoff (resilience/policy.py RetryPolicy
# schedule) before a half-open probe is admitted
SERVING_FLEET_BREAKER_FAILURES_DEFAULT = 3
SERVING_FLEET_BREAKER_BACKOFF_SECONDS_DEFAULT = 0.5
SERVING_FLEET_BREAKER_BACKOFF_MAX_SECONDS_DEFAULT = 30.0
SERVING_FLEET_BREAKER_HALFOPEN_PROBES_DEFAULT = 1
# tail-latency hedging: duplicate a first-token-less request to a
# second replica after hedge_factor * observed p99 TTFT (armed only
# past hedge_min_observations samples); first token wins, the loser is
# cancelled via scheduler retirement
SERVING_FLEET_HEDGE_DEFAULT = False
SERVING_FLEET_HEDGE_FACTOR_DEFAULT = 1.5
SERVING_FLEET_HEDGE_MIN_OBSERVATIONS_DEFAULT = 16
# replica supervision: restarts per replica before it stays dead, with
# the same RetryPolicy backoff schedule between restart attempts
SERVING_FLEET_MAX_RESTARTS_DEFAULT = 3
SERVING_FLEET_RESTART_BACKOFF_SECONDS_DEFAULT = 0.2
# restart-budget decay (leaky bucket): every this-many seconds of clean
# service since the last restart attempt forgives one consumed attempt,
# so one bad hour does not permanently exhaust a long-lived replica's
# budget; 0 = never decay (the pre-elastic behavior)
SERVING_FLEET_RESTART_BUDGET_RESET_SECONDS_DEFAULT = 0.0
# -- elastic fleet (serving.fleet.elastic.*; docs/serving.md §Elastic
# fleet): load-driven autoscaling with warm-pool scale-up and
# drain + live-KV-session-migration scale-down -------------------------
SERVING_FLEET_ELASTIC = "elastic"
SERVING_FLEET_ELASTIC_ENABLED_DEFAULT = False
SERVING_FLEET_ELASTIC_MIN_REPLICAS_DEFAULT = 1
SERVING_FLEET_ELASTIC_MAX_REPLICAS_DEFAULT = 4
# scale-up pressure: a tick is HOT when mean queued-per-routable-replica
# crosses the depth threshold, any replica's admitted-TTFT estimate
# crosses the ttft threshold, or the router absorbed shed/rejections
# since the last tick
SERVING_FLEET_ELASTIC_SCALE_UP_QUEUE_DEPTH_DEFAULT = 4
SERVING_FLEET_ELASTIC_SCALE_UP_TTFT_SECONDS_DEFAULT = 1.0
SERVING_FLEET_ELASTIC_SCALE_DOWN_QUEUE_DEPTH_DEFAULT = 1
# hysteresis: engage fast (consecutive hot ticks), disengage slow
# (consecutive cold ticks) — the degradation ladder's shape
SERVING_FLEET_ELASTIC_ENGAGE_TICKS_DEFAULT = 3
SERVING_FLEET_ELASTIC_DISENGAGE_TICKS_DEFAULT = 12
SERVING_FLEET_ELASTIC_SCALE_UP_COOLDOWN_SECONDS_DEFAULT = 5.0
SERVING_FLEET_ELASTIC_SCALE_DOWN_COOLDOWN_SECONDS_DEFAULT = 30.0
# pre-built (factory + warm hook, off the routing thread) replicas kept
# ready so a scale-up is an O(1) attach instead of a jit compile
SERVING_FLEET_ELASTIC_WARM_POOL_SIZE_DEFAULT = 1
# scale-down victim drain budget: while the victim still holds
# in-flight requests past this deadline the scale-down ABORTS (the
# victim revives) — it never proceeds over live work
SERVING_FLEET_ELASTIC_MIGRATION_DEADLINE_SECONDS_DEFAULT = 30.0
SERVING_FLEET_ELASTIC_MIGRATION_RETRIES_DEFAULT = 3
# -- multi-tenant front-door (serving.frontdoor.* / serving.tenants.*;
# docs/serving.md §Front-door) ----------------------------------------
SERVING_FRONTDOOR = "frontdoor"
SERVING_FRONTDOOR_ENABLED_DEFAULT = False
SERVING_FRONTDOOR_HOST_DEFAULT = "127.0.0.1"
SERVING_FRONTDOOR_PORT_DEFAULT = 0  # 0 = ephemeral (OS-assigned) port
# chunked-streaming poll cadence: how often the handler thread samples
# a live request's partial tokens between engine steps
SERVING_FRONTDOOR_STREAM_POLL_SECONDS_DEFAULT = 0.01
# hard cap on a single request body (token-id JSON) — a front door
# should bound untrusted input before it reaches the scheduler
SERVING_FRONTDOOR_MAX_BODY_BYTES_DEFAULT = 1 << 20
SERVING_TENANTS = "tenants"
SERVING_TENANTS_ENABLED_DEFAULT = False
# default (per-tenant) token-bucket admission rate: budget tokens
# (prompt + reserved max_new) per second, and the burst ceiling;
# rate 0 + burst 0 = unlimited tenant
SERVING_TENANTS_REFILL_TOKENS_PER_SECOND_DEFAULT = 0.0
SERVING_TENANTS_BURST_TOKENS_DEFAULT = 0.0
SERVING_TENANTS_WEIGHT_DEFAULT = 1.0  # WFQ share weight
SERVING_TENANTS_SLO_CLASS_DEFAULT = "silver"  # gold | silver | bronze
SERVING_TENANTS_SLO_CLASSES = ["gold", "silver", "bronze"]
SERVING_TENANTS_KV_PAGES_MAX_DEFAULT = 0  # 0 = no per-tenant page cap
SERVING_TENANTS_PINNED_PREFIXES_MAX_DEFAULT = 0  # 0 = no pin cap

#############################################
# Telemetry (unified metrics registry / trace export; docs/telemetry.md)
#############################################
TELEMETRY = "telemetry"
TELEMETRY_ENABLED_DEFAULT = True  # in-process registry only; no sinks by default
TELEMETRY_RING_DEFAULT = 1024  # per-metric ring-buffer samples
TELEMETRY_EXPORTERS = ["jsonl", "prometheus", "tensorboard"]
TELEMETRY_EXPORT_INTERVAL_DEFAULT = 10.0  # seconds between sink flushes
TELEMETRY_OUTPUT_PATH = "output_path"
TELEMETRY_OUTPUT_PATH_DEFAULT = ""  # "" = ./telemetry when a sink needs a path
TELEMETRY_TRACE_ENABLED_DEFAULT = False  # Chrome-trace span buffer
TELEMETRY_TRACE_BUFFER_DEFAULT = 100_000  # span ring-buffer events
TELEMETRY_PROFILER_CAPTURE_MS_DEFAULT = 2000  # jax.profiler window length
TELEMETRY_SLO_TTFT_BREACH_MS_DEFAULT = 0.0  # 0 = no on-breach capture
TELEMETRY_AGGREGATE_DEFAULT = True  # piggyback snapshots on supervision beats
TELEMETRY_ATTRIBUTION_DEFAULT = True  # per-kernel cost attribution at compile time
TELEMETRY_ATTRIBUTION_MAX_HLO_MB_DEFAULT = 256.0  # skip the walk past this text size
TELEMETRY_SPIKE_FACTOR_DEFAULT = 2.5  # step wall > factor x window mean -> anomaly
TELEMETRY_SPIKE_MIN_WINDOW_DEFAULT = 8  # samples before the spike watch arms
TELEMETRY_STRAGGLER_FACTOR_DEFAULT = 1.5  # rank wall > factor x cluster median

#############################################
# Sanitizer (ds_san: trace-time & runtime checkers; docs/ds_san.md)
#############################################
SANITIZER = "sanitizer"
SAN_ENABLED_DEFAULT = False
SAN_CHECKERS = ["recompile", "transfer", "donation", "sharding", "nonfinite"]
SAN_COMPILE_BUDGET_DEFAULT = 8  # compiles per call site before storm
SAN_DRIFT_INTERVAL_DEFAULT = 16  # steps between sharding-drift sweeps

RESILIENCE_DIVERGENCE = "divergence"
DIVERGENCE_ENABLED_DEFAULT = True
DIVERGENCE_THRESHOLD_DEFAULT = 20
DIVERGENCE_ACTION_WARN = "warn"
DIVERGENCE_ACTION_FLOOR = "floor_loss_scale"
DIVERGENCE_ACTION_ROLLBACK = "rollback"
DIVERGENCE_ACTIONS = [
    DIVERGENCE_ACTION_WARN,
    DIVERGENCE_ACTION_FLOOR,
    DIVERGENCE_ACTION_ROLLBACK,
]

#############################################
# Pallas kernel suite (ops/kernels; docs/kernels.md)
#############################################
KERNELS = "kernels"
KERNELS_ENABLED_AUTO = "auto"  # armed on TPU-class backends only
KERNELS_ENABLED_CHOICES = [KERNELS_ENABLED_AUTO, True, False]
KERNELS_FLASH_DECODE_DEFAULT = True  # fused int8-KV flash-decode kernel
KERNELS_FUSED_UPDATE_DEFAULT = True  # one-HBM-pass Adam/LAMB update
KERNELS_AUTOTUNE_MODES = ["off", "cache", "force"]
KERNELS_AUTOTUNE_DEFAULT = "cache"  # read-mostly; CI/tier-1 never measure
