"""Partition-rule engine: the single home of PartitionSpec construction.

Every engine in the zoo (DeepSpeedEngine, PipelineEngine,
ZeroInfinityEngine, InferenceEngine, ServingEngine) resolves its
parameter/batch/state layouts through this package instead of
hand-building ``jax.sharding.PartitionSpec`` literals — the convergence
the reference's per-subsystem partitioners (Megatron mpu, ZeRO
partition_parameters.py, module_inject/replace_module.py) never had.
The ds_lint rule ``hand-built-partition-spec`` enforces the seam.

Layers (docs/sharding.md):

* :mod:`~deepspeed_tpu.sharding.layout` — :class:`SpecLayout`, the
  canonical axis names + batch/row/replicated spec constructors.
* :mod:`~deepspeed_tpu.sharding.rules` — the ordered regex rule table
  (fmengine ``match_partition_rules`` / T5X logical-axes style) with
  built-in gpt2/bert/neo/MoE family rule sets.
* :mod:`~deepspeed_tpu.sharding.mesh` — ``build_mesh()`` device-topology
  mesh derivation incl. 2-level hybrid ICI×DCN meshes, and the
  :class:`MeshTopology` descriptor the comm policy table keys on.
* :mod:`~deepspeed_tpu.sharding.update` — cross-replica weight-update
  sharding (arXiv:2004.13336, the XLA-native ZeRO-1): axis-placement
  primitives and the update-phase byte/FLOP model.
"""
from deepspeed_tpu.sharding.layout import (
    SpecLayout,
    batch_pspec,
    batch_sharding,
    dp_rows_spec,
    fsdp_trailing_spec,
    replicated_pspec,
    replicated_sharding,
    stacked_batch_pspec,
    stacked_micro_batch_pspec,
)
from deepspeed_tpu.sharding.mesh import (
    MeshTopology,
    build_mesh,
    derive_topology,
)
from deepspeed_tpu.sharding.rules import (
    PartitionRules,
    match_partition_rules,
    moe_param_specs,
    rules_for_config,
    rules_for_family,
)
from deepspeed_tpu.sharding.update import (
    add_mesh_axis,
    add_update_axis,
    spec_tuple,
    weight_update_model,
)

__all__ = [
    "SpecLayout",
    "batch_pspec",
    "batch_sharding",
    "dp_rows_spec",
    "fsdp_trailing_spec",
    "replicated_pspec",
    "replicated_sharding",
    "stacked_batch_pspec",
    "stacked_micro_batch_pspec",
    "MeshTopology",
    "build_mesh",
    "derive_topology",
    "PartitionRules",
    "match_partition_rules",
    "moe_param_specs",
    "rules_for_config",
    "rules_for_family",
    "add_mesh_axis",
    "add_update_axis",
    "spec_tuple",
    "weight_update_model",
]
