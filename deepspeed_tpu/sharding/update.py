"""Cross-replica weight-update sharding (arXiv:2004.13336) — the
XLA-native ZeRO-1 — plus the axis-placement primitives the ZeRO rule
layer builds specs from.

The paper's observation: in data-parallel training the gradients are
all-reduced dense, but the *weight update* (optimizer math over the
full parameter/moment set) is embarrassingly shardable — annotate the
optimizer state sharded across replicas and the partitioner computes
each replica's 1/dp slice of the update, then all-gathers the updated
parameters once.  Per-replica update FLOPs and optimizer-state bytes
drop ~dp× for one params-sized all-gather per step; the loss trajectory
is unchanged (the math is elementwise).  Here it is the DEFAULT at
``zero_optimization.stage >= 1``: the ``fsdp`` axis shards state as
before, and the pure ``data`` axis — replicated in classic GSPMD ZeRO —
joins the update sharding (``zero_optimization.cross_replica_weight_update``,
on by default; zero/stages.py consumes these primitives).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import PartitionSpec


def spec_tuple(spec: Optional[PartitionSpec], ndim: int) -> Tuple[Any, ...]:
    """Normalize a PartitionSpec to a full-length tuple."""
    if spec is None:
        return (None,) * ndim
    t = tuple(spec)
    return t + (None,) * (ndim - len(t))


def _entry_axes(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def add_mesh_axis(
    shape: Sequence[int],
    base_spec: Optional[PartitionSpec],
    axis: str,
    size: int,
    min_size: int = 0,
) -> PartitionSpec:
    """Add one mesh axis to a leaf's PartitionSpec: the largest dim that
    (a) is not already sharded and (b) is divisible by ``size``.  Leaves
    smaller than ``min_size`` elements (the ZeRO-3 persistence
    threshold) or with no divisible dim stay as-is (replicated over the
    axis)."""
    ndim = len(shape)
    base = spec_tuple(base_spec, ndim)
    if size <= 1:
        return PartitionSpec(*base)
    if int(np.prod(shape)) < max(min_size, 1) and min_size > 0:
        return PartitionSpec(*base)
    candidates = [
        (shape[i], i)
        for i in range(ndim)
        if base[i] is None and shape[i] % size == 0 and shape[i] >= size
    ]
    if not candidates:
        return PartitionSpec(*base)
    _, dim = max(candidates)
    new = list(base)
    new[dim] = axis
    return PartitionSpec(*new)


def add_update_axis(
    shape: Sequence[int],
    spec: PartitionSpec,
    data_axis: str,
    data_size: int,
    fsdp_axis: str = "fsdp",
    fsdp_size: int = 1,
) -> PartitionSpec:
    """Extend an (already fsdp-placed) optimizer-state spec across the
    pure data axis — the cross-replica weight-update placement.

    Preference order: extend the fsdp-carrying dim to
    ``(fsdp, data)`` (fsdp-major, so each data-rank's slice is a
    sub-block of the grad reduce-scatter shard it already holds —
    no resharding comm); else place ``data`` alone on the largest
    still-free dim divisible by ``data_size``; else leave the spec
    as-is (the leaf's update stays replicated over data)."""
    ndim = len(shape)
    base = spec_tuple(spec, ndim)
    if data_size <= 1:
        return PartitionSpec(*base)
    for i in range(ndim):
        axes = _entry_axes(base[i])
        if fsdp_axis in axes and data_axis not in axes:
            if shape[i] % (fsdp_size * data_size) == 0:
                new = list(base)
                new[i] = tuple(axes) + (data_axis,)
                return PartitionSpec(*new)
    return add_mesh_axis(shape, PartitionSpec(*base), data_axis, data_size)


# ---------------------------------------------------------------------------
# update-phase byte/FLOP model (docs/sharding.md)
# ---------------------------------------------------------------------------

# First-order FLOPs of one Adam(W) update per parameter (ema m, ema v,
# sqrt, divide, weight decay, axpy) — the constant cancels in ratios;
# it exists so absolute numbers in reports are honest about units.
ADAM_FLOPS_PER_PARAM = 12


def weight_update_model(
    n_params: int,
    dp: int,
    sharded: bool = True,
    state_slots: int = 2,
    state_bytes: int = 4,
    master_bytes: int = 4,
) -> Dict[str, Any]:
    """Per-replica cost of the optimizer-update phase under replicated
    vs cross-replica-sharded weight updates (arXiv:2004.13336 §3).

    ``state_slots``: params-shaped optimizer-state mirrors (Adam: m+v).
    Returns per-replica update FLOPs, optimizer-state bytes, and the
    update all-gather wire bytes (sharded pays one params-sized gather
    of the updated values; replicated pays none).  Validated against
    compiled-HLO/memory numbers in tests/test_sharding.py."""
    shard = max(1, dp) if sharded else 1
    return {
        "dp": dp,
        "sharded": bool(sharded),
        "update_flops_per_replica": ADAM_FLOPS_PER_PARAM * n_params // shard,
        "opt_state_bytes_per_replica": state_slots * state_bytes * n_params // shard,
        "update_allgather_bytes": master_bytes * n_params if (sharded and dp > 1) else 0,
    }
