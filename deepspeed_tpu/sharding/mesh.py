"""Mesh derivation from device topology, including 2-level hybrid
ICI×DCN meshes for multi-slice scale-out.

``build_mesh()`` resolves the configured axis sizes over the available
devices and — when the devices span more than one *granule* (a TPU
slice, a host process, or a ``DS_DCN_SLICES``-simulated slice) —
arranges them ``create_hybrid_device_mesh``-style so only the
DCN-tolerant outer axes (``pipe``, ``data``) cross the slow inter-slice
links while ``model``/``seq`` stay inside a slice's ICI domain (the
T5X/scaling-book recipe, SNIPPETS.md [1]; the reference tunes NCCL
hierarchies for the same reason, SURVEY §2.6).

The returned :class:`MeshTopology` is the descriptor the comm layer's
policy table keys on: per-axis ICI/DCN factoring, slice count, and
link-kind queries (``crosses_dcn``), so collective strategy selection
can stay dense intra-slice and compress inter-slice (docs/comm.md).
"""
from __future__ import annotations

import dataclasses
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger

# Canonical axis order: outermost (slowest-varying, most DCN-tolerant)
# first.  pipe and data tolerate slower links; model/seq need the
# fastest ICI, so they are innermost (adjacent device ids share a
# physical link on TPU slices).
MESH_AXES: Tuple[str, ...] = ("pipe", "data", "fsdp", "seq", "model", "expert")

LINK_ICI = "ici"
LINK_DCN = "dcn"
LINK_MIXED = "ici+dcn"


def resolve_mesh_shape(cfg, n_devices: int) -> Dict[str, int]:
    """Fill in the -1 ("remaining") axis and validate the product."""
    sizes = {ax: int(getattr(cfg, ax)) for ax in MESH_AXES}
    free = [ax for ax, s in sizes.items() if s == -1]
    if len(free) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {free}")
    fixed = 1
    for ax, s in sizes.items():
        if s != -1:
            if s < 1:
                raise ValueError(f"mesh axis {ax} must be >=1 or -1, got {s}")
            fixed *= s
    if free:
        rem, mod = divmod(n_devices, fixed)
        if mod:
            raise ValueError(f"{n_devices} devices not divisible by fixed axes product {fixed}")
        sizes[free[0]] = rem
    total = int(np.prod(list(sizes.values())))
    if total != n_devices:
        raise ValueError(f"Mesh {sizes} covers {total} devices but {n_devices} are available")
    return sizes


def split_dcn_ici(sizes: Dict[str, int], n_granules: int) -> Optional[Tuple[Dict[str, int], Dict[str, int]]]:
    """Factor each axis into (DCN, ICI) parts: the granule count is
    absorbed by the outermost (most DCN-tolerant) axes first — ``pipe``
    and ``data`` ride the slow inter-granule links, while
    ``model``/``seq`` stay inside a granule's ICI domain.  Returns
    ``(dcn_sizes, ici_sizes)`` or None when the granule count cannot be
    factored into the axis sizes."""
    dcn = {ax: 1 for ax in sizes}
    ici = dict(sizes)
    left = n_granules
    # outermost first; tolerate meshes missing some canonical axes
    order = [ax for ax in MESH_AXES if ax in ici] + [ax for ax in ici if ax not in MESH_AXES]
    for ax in order:
        if left == 1:
            break
        f = math.gcd(left, ici[ax])
        # absorb the largest factor of `left` that divides this axis
        while f > 1 and left % f == 0 and ici[ax] % f == 0:
            dcn[ax] *= f
            ici[ax] //= f
            left //= f
            f = math.gcd(left, ici[ax])
    return None if left != 1 else (dcn, ici)


@dataclasses.dataclass(frozen=True)
class MeshTopology:
    """Per-axis ICI/DCN factoring of a device mesh — the topology
    descriptor layout and comm decisions key on."""

    sizes: Dict[str, int]
    dcn: Dict[str, int]
    ici: Dict[str, int]

    @classmethod
    def single_slice(cls, sizes: Dict[str, int]) -> "MeshTopology":
        return cls(sizes=dict(sizes), dcn={ax: 1 for ax in sizes}, ici=dict(sizes))

    @property
    def num_slices(self) -> int:
        return int(np.prod(list(self.dcn.values())))

    @property
    def slice_devices(self) -> int:
        return int(np.prod(list(self.ici.values())))

    def link(self, axis: str) -> str:
        """The link kind an exchange over ``axis`` rides: ``ici`` (all
        inside one slice), ``dcn`` (every hop crosses slices), or
        ``ici+dcn`` (a 2-level hierarchy)."""
        d, i = self.dcn.get(axis, 1), self.ici.get(axis, 1)
        if d > 1 and i > 1:
            return LINK_MIXED
        if d > 1:
            return LINK_DCN
        return LINK_ICI

    def crosses_dcn(self, axes) -> bool:
        names = axes if isinstance(axes, (tuple, list)) else (axes,)
        return any(self.dcn.get(a, 1) > 1 for a in names)

    def dcn_ranks(self, axes) -> int:
        names = axes if isinstance(axes, (tuple, list)) else (axes,)
        return int(np.prod([self.dcn.get(a, 1) for a in names]))

    def ici_ranks(self, axes) -> int:
        names = axes if isinstance(axes, (tuple, list)) else (axes,)
        return int(np.prod([self.ici.get(a, 1) for a in names]))

    def describe(self) -> str:
        if self.num_slices <= 1:
            return "single slice (all-ICI)"
        dcn = "×".join(str(self.dcn[ax]) for ax in MESH_AXES if ax in self.dcn)
        ici = "×".join(str(self.ici[ax]) for ax in MESH_AXES if ax in self.ici)
        return f"{self.num_slices} slices: dcn={dcn} ici={ici}"


# ---------------------------------------------------------------------------
# granule detection: what shares fast ICI?
# ---------------------------------------------------------------------------

def _granules(devices: Sequence) -> Optional[List[List]]:
    """Split ``devices`` into ICI granules: ``DS_DCN_SLICES=K``
    (simulation / explicit override) > TPU ``slice_index`` metadata >
    one-granule-per-process (multi-host without slice metadata)."""
    import jax

    env = os.environ.get("DS_DCN_SLICES", "")
    if env:
        k = int(env)
        if k > 1:
            if len(devices) % k:
                raise ValueError(
                    f"DS_DCN_SLICES={k} does not divide {len(devices)} devices"
                )
            per = len(devices) // k
            return [list(devices[i * per : (i + 1) * per]) for i in range(k)]
        return None
    slice_ids = [getattr(d, "slice_index", None) for d in devices]
    if all(s is not None for s in slice_ids) and len(set(slice_ids)) > 1:
        by: Dict[int, List] = {}
        for d, s in zip(devices, slice_ids):
            by.setdefault(s, []).append(d)
        groups = [by[s] for s in sorted(by)]
        if len({len(g) for g in groups}) == 1:
            return groups
        logger.warning("uneven slice_index granules; treating mesh as single-slice")
        return None
    if jax.process_count() > 1 and len(devices) == jax.device_count():
        by = {}
        for d in devices:
            by.setdefault(d.process_index, []).append(d)
        groups = [by[p] for p in sorted(by)]
        if len({len(g) for g in groups}) == 1:
            return groups
    return None


def _assemble_hybrid(granules: List[List], dcn: Dict[str, int], ici: Dict[str, int]) -> np.ndarray:
    """Place each granule's devices as one contiguous ICI block of the
    final mesh array: axis index = dcn_idx * ici_size + ici_idx, so
    within-block neighbors share ICI and only block boundaries cross
    DCN (the ``create_hybrid_device_mesh`` arrangement, built directly
    from the granule lists so it also works for simulated slices)."""
    ici_shape = tuple(ici[ax] for ax in MESH_AXES)
    dcn_shape = tuple(dcn[ax] for ax in MESH_AXES)
    final = tuple(d * i for d, i in zip(dcn_shape, ici_shape))
    out = np.empty(final, dtype=object)
    for gi, gdevs in enumerate(granules):
        didx = np.unravel_index(gi, dcn_shape)
        block = np.asarray(gdevs, dtype=object).reshape(ici_shape)
        slices = tuple(slice(d * i, (d + 1) * i) for d, i in zip(didx, ici_shape))
        out[slices] = block
    return out


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------

def build_mesh(cfg=None, devices: Optional[Sequence] = None):
    """Build the framework mesh over the given (default: all) devices
    and derive its :class:`MeshTopology`.

    Returns ``(mesh, topology)``.  Single-granule device sets get the
    flat canonical arrangement; multi-granule sets get the 2-level
    hybrid arrangement (real TPU multi-slice/multi-host via
    ``mesh_utils.create_hybrid_device_mesh`` when its metadata is
    usable, else direct granule-block assembly)."""
    import jax
    from jax.sharding import Mesh

    if cfg is None:
        from deepspeed_tpu.config.config import MeshConfig

        cfg = MeshConfig()
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    sizes = resolve_mesh_shape(cfg, len(devices))
    shape = tuple(sizes[ax] for ax in MESH_AXES)

    granules = _granules(devices)
    dev_array = None
    topology = MeshTopology.single_slice(sizes)
    if granules is not None and len(granules) > 1:
        split = split_dcn_ici(sizes, len(granules))
        if split is not None:
            dcn, ici = split
            topology = MeshTopology(sizes=sizes, dcn=dcn, ici=ici)
            if jax.process_count() > 1 and not os.environ.get("DS_DCN_SLICES"):
                try:
                    from jax.experimental import mesh_utils

                    # process_is_granule: our dcn factors come from the
                    # granule count, so each process is one granule (the
                    # default groups by slice_index, which only matches
                    # when processes == slices)
                    dev_array = mesh_utils.create_hybrid_device_mesh(
                        tuple(ici[ax] for ax in MESH_AXES),
                        tuple(dcn[ax] for ax in MESH_AXES),
                        devices=devices,
                        process_is_granule=len(granules) == jax.process_count(),
                    )
                except Exception as e:
                    logger.warning(f"create_hybrid_device_mesh failed ({e}); assembling granule blocks directly")
            if dev_array is None:
                dev_array = _assemble_hybrid(granules, dcn, ici)
            logger.info(f"hybrid mesh: {topology.describe()}")
        else:
            logger.warning(
                f"{len(granules)} granules do not factor into mesh {sizes}; "
                "using flat device order (cross-slice collectives may ride slow links)"
            )
    if dev_array is None:
        dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, MESH_AXES)
    logger.info(
        "mesh: " + " × ".join(f"{ax}={sizes[ax]}" for ax in MESH_AXES if sizes[ax] > 1 or ax == "data")
    )
    return mesh, topology


def derive_topology(mesh) -> MeshTopology:
    """Best-effort topology for a caller-provided mesh: factor the axis
    sizes by the granule count of its devices (DS_DCN_SLICES simulation,
    TPU slice metadata, or processes); all-ICI when single-granule or
    the factoring fails.  A mesh built by :func:`build_mesh` should use
    the topology returned alongside it instead."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    devices = list(mesh.devices.flat)
    granules = _granules(devices)
    if granules is None or len(granules) <= 1:
        return MeshTopology.single_slice(sizes)
    split = split_dcn_ici(sizes, len(granules))
    if split is None:
        return MeshTopology.single_slice(sizes)
    dcn, ici = split
    return MeshTopology(sizes=sizes, dcn=dcn, ici=ici)
