"""Ordered regex partition-rule tables (fmengine ``match_partition_rules``
/ T5X logical-axes style, SNIPPETS.md [1][2]).

A rule table is an ordered sequence of ``(regex, PartitionSpec|None)``
pairs matched against the ``/``-joined path of each parameter leaf;
the FIRST match wins, ``None`` means "no tensor-parallel base spec"
(the ZeRO layer may still add fsdp/data axes).  Built-in tables cover
the model families the repo ships (gpt2 / bert / gpt-neo / MoE) and new
families register with :func:`register_family` — sharding for free, no
engine changes (ROADMAP item 3 payoff).

Packed int8 weights (runtime/weight_quantizer.pack_int8_tree) nest one
level: ``.../<name>_w/q`` carries the weight spec and ``.../<name>_w/s``
drops the contracted (input) dim — the rule engine normalizes those
paths so every consumer (inference, serving pools) resolves identically.
"""
from __future__ import annotations

import re
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import PartitionSpec

from deepspeed_tpu.sharding.layout import DEFAULT_LAYOUT, SpecLayout

Rule = Tuple[str, Optional[PartitionSpec]]
SpecFn = Callable[[str, Sequence[int]], Optional[PartitionSpec]]


# ---------------------------------------------------------------------------
# MoE expert-parallel specs — the single source of truth for the MoE
# weight layout (experts over ``expert``, FFN hidden dim over the tp
# axis); moe/layer.py re-exports this for back-compat.
# ---------------------------------------------------------------------------

def moe_param_specs(
    layer_dim: bool = False, tp_axis: Optional[str] = None, layout: SpecLayout = DEFAULT_LAYOUT
) -> Dict[str, PartitionSpec]:
    """PartitionSpecs for MoE weights: experts over ``expert`` and
    (optionally) the expert-FFN hidden dim over ``tp_axis`` (EP × TP).
    ``layer_dim=True`` prepends a replicated leading dim for models that
    stack per-layer weights for ``lax.scan`` (models/gpt2.py)."""
    e = layout.expert_axis
    specs = {
        "gate_w": PartitionSpec(),
        "w1": PartitionSpec(e, None, tp_axis),
        "b1": PartitionSpec(e, tp_axis),
        "w2": PartitionSpec(e, tp_axis, None),
        "b2": PartitionSpec(e, None),
    }
    if layer_dim:
        specs = {k: PartitionSpec(None, *v) for k, v in specs.items()}
    return specs


# ---------------------------------------------------------------------------
# core matcher
# ---------------------------------------------------------------------------

class PartitionRules:
    """An ordered (regex → PartitionSpec) table with the packed-int8
    path normalization.  ``spec(path, shape)`` returns the
    tensor-parallel base spec for one leaf (None = replicated over tp),
    the contract :class:`~deepspeed_tpu.runtime.zero.stages.ZeroShardingRules`
    consumes."""

    def __init__(self, rules: Sequence[Rule], name: str = "custom", layout: SpecLayout = DEFAULT_LAYOUT):
        self.name = name
        self.layout = layout
        self.rules: Tuple[Tuple[re.Pattern, Optional[PartitionSpec]], ...] = tuple(
            (re.compile(rx), spec) for rx, spec in rules
        )

    # -- construction ---------------------------------------------------
    @classmethod
    def from_fn(cls, fn: SpecFn, name: str = "client-fn") -> "PartitionRules":
        """Wrap a legacy ``tp_spec_fn(path, shape)`` callable so every
        consumer sees one interface."""
        self = cls((), name=name)
        self._fn = fn
        return self

    @classmethod
    def empty(cls) -> "PartitionRules":
        return cls((), name="none")

    @classmethod
    def coerce(cls, partition_rules=None, tp_spec_fn=None) -> "PartitionRules":
        """Normalize the engines' layout inputs — a legacy ``tp_spec_fn``
        callable, a :class:`PartitionRules`, a family name, an ordered
        rule table, or nothing — into one :class:`PartitionRules` (the
        single coercion both DeepSpeedEngine and PipelineEngine use)."""
        if tp_spec_fn is not None:
            return cls.from_fn(tp_spec_fn)
        if partition_rules is None:
            return cls.empty()
        if isinstance(partition_rules, cls):
            return partition_rules
        if isinstance(partition_rules, str):
            return rules_for_family(partition_rules)
        return cls(partition_rules)

    # -- resolution -----------------------------------------------------
    _fn: Optional[SpecFn] = None

    def _match(self, path: str) -> Optional[PartitionSpec]:
        for rx, spec in self.rules:
            if rx.search(path) is not None:
                return spec
        return None

    def matches(self, path: str) -> bool:
        """Whether ANY rule covers ``path`` (a matched ``None`` spec —
        "explicitly replicated" — still counts; fn-backed tables are
        treated as total)."""
        if self._fn is not None:
            return True
        return any(rx.search(path) is not None for rx, _ in self.rules)

    def base_spec(self, path: str, shape: Sequence[int]) -> Optional[PartitionSpec]:
        """The raw table lookup (no packed normalization)."""
        if self._fn is not None:
            return self._fn(path, shape)
        return self._match(path)

    def spec(self, path: str, shape: Sequence[int]) -> Optional[PartitionSpec]:
        """Table lookup with packed-int8 normalization: ``.../x/q``
        resolves as ``.../x``; ``.../x/s`` additionally drops the
        contracted (second-to-last) dim of the resolved spec.

        Legacy client fns see the RAW path: the q/s convention belongs
        to the family tables (packed-int8 trees the inference engines
        build); a client ``tp_spec_fn`` may legitimately name leaves
        ``q`` or ``s`` and must keep its pre-rule-engine behavior."""
        if self._fn is not None:
            return self._fn(path, shape)
        parts = path.split("/")
        packed_kind = parts[-1] if len(parts) > 1 and parts[-1] in ("q", "s") else None
        if packed_kind is None:
            return self.base_spec(path, shape)
        base = self.base_spec("/".join(parts[:-1]), shape)
        if base is None:
            return None
        if packed_kind == "s":
            dims = tuple(base)
            if len(dims) < 2:
                return PartitionSpec()
            return PartitionSpec(*(dims[:-2] + (dims[-1],)))
        return base

    def tp_spec_fn(self) -> SpecFn:
        """Adapter with the legacy ``tp_spec_fn(path, shape)`` shape."""
        return self.spec

    # -- composition ----------------------------------------------------
    def stacked(self, axis: Optional[str] = None, prefix: str = "blocks") -> "PartitionRules":
        """Pipeline-stacked view: leaves under ``prefix`` gained a
        leading stacked-layer dim sharded over ``axis`` (default: the
        layout's pipe axis).  Per-block specs (rank < leaf rank — legacy
        client fns see the per-block shape) shift right by one; full-rank
        specs (the built-in family tables already carry a replicated
        stacked-layer dim) get the axis composed onto their leading dim."""
        ax = axis if axis is not None else self.layout.pipe_axis

        def fn(path: str, shape: Sequence[int]) -> Optional[PartitionSpec]:
            if path == prefix or path.startswith(prefix + "/"):
                base = self.spec(path, tuple(shape)[1:])
                dims = tuple(base) if base is not None else ()
                if len(shape) and len(dims) >= len(shape):
                    lead = dims[0]
                    if lead is None:
                        return PartitionSpec(ax, *dims[1:])
                    lead_axes = (lead,) if isinstance(lead, str) else tuple(lead)
                    return PartitionSpec((ax,) + lead_axes, *dims[1:])
                return PartitionSpec(ax, *dims)
            return self.spec(path, shape)

        out = PartitionRules.from_fn(fn, name=f"{self.name}+stacked({ax})")
        out.layout = self.layout
        return out

    # -- whole-tree resolution (fmengine match_partition_rules) ---------
    def tree_specs(self, params: Any, strict: bool = False) -> Any:
        """Resolve the whole param tree to base specs: scalars →
        replicated; unmatched leaves → replicated (or raise when
        ``strict``)."""
        import jax

        def get(path_parts, leaf):
            path = _path_str(path_parts)
            shape = tuple(np.shape(leaf))
            if len(shape) == 0 or int(np.prod(shape)) == 1:
                return PartitionSpec()
            spec = self.spec(path, shape)
            if spec is None:
                # a matched None rule means "explicitly replicated";
                # only a path NO rule covers is a strict-mode error
                if strict and not self.matches(path):
                    raise ValueError(f"partition rule not found for param: {path}")
                return PartitionSpec()
            return spec

        return jax.tree_util.tree_map_with_path(get, params)

    def __repr__(self) -> str:
        kind = "fn" if self._fn is not None else f"{len(self.rules)} rules"
        return f"PartitionRules({self.name!r}, {kind})"


def match_partition_rules(rules: Sequence[Rule], params: Any, strict: bool = True) -> Any:
    """fmengine-style convenience: resolve a pytree of PartitionSpecs
    from an ordered rule table; scalar leaves stay replicated; unmatched
    leaves raise (pass ``strict=False`` to replicate them instead)."""
    return PartitionRules(rules, name="inline").tree_specs(params, strict=strict)


# ---------------------------------------------------------------------------
# built-in family tables
# ---------------------------------------------------------------------------

def _transformer_tp_rules(layout: SpecLayout) -> Tuple[Rule, ...]:
    """Megatron column/row split for the stacked fused-block layout both
    model families share (models/gpt2.py, models/bert.py): qkv/fc
    column-parallel, proj row-parallel.  Block weights carry a leading
    stacked-layer dim, so the specs are rank-3."""
    tp = layout.tp_axis
    return (
        # column-parallel: output features over tp
        (r"(^|/)qkv_w$", PartitionSpec(None, None, tp)),
        (r"(^|/)qkv_b$", PartitionSpec(None, tp)),
        (r"(^|/)fc_w$", PartitionSpec(None, None, tp)),
        (r"(^|/)fc_b$", PartitionSpec(None, tp)),
        # row-parallel: input (contracted) features over tp
        (r"(^|/)proj_w$", PartitionSpec(None, tp, None)),
        (r"(^|/)fc_proj_w$", PartitionSpec(None, tp, None)),
    )


def _moe_rules(layout: SpecLayout) -> Tuple[Rule, ...]:
    """Expert weights (stacked layer dim leading) from the canonical MoE
    layout; the router (gate_w) stays replicated so it is NOT ruled here
    (the default replication covers it)."""
    specs = moe_param_specs(layer_dim=True, tp_axis=layout.tp_axis, layout=layout)
    return tuple((rf"(^|/){name}$", spec) for name, spec in specs.items() if name != "gate_w")


def _gpt2_rules(layout: SpecLayout) -> Tuple[Rule, ...]:
    return _transformer_tp_rules(layout) + _moe_rules(layout) + (
        # vocab-parallel token embedding (tied head resolves to the same
        # table); wpe/layernorms/biases fall through to replicated
        (r"(^|/)wte$", layout.vocab_embedding()),
    )


def _bert_rules(layout: SpecLayout) -> Tuple[Rule, ...]:
    return _transformer_tp_rules(layout) + (
        (r"(^|/)tok_emb$", layout.vocab_embedding()),
    )


_FAMILIES: Dict[str, Callable[[SpecLayout], Tuple[Rule, ...]]] = {}


def register_family(name: str, builder: Callable[[SpecLayout], Tuple[Rule, ...]]) -> None:
    """Register a family rule-table builder (new model families get
    sharding by adding one table, not by touching engines)."""
    _FAMILIES[name] = builder


def _neo_rules(layout: SpecLayout) -> Tuple[Rule, ...]:
    """GPT-Neo shares the GPT-2 param schema (models/gpt2.py PRESETS
    "gpt-neo-2.7b" is a GPT2Config with local-attention layers) but is
    dense-only, so its table carries no MoE expert rows — every row
    here matches a leaf a Neo checkpoint can actually contain
    (ds_shard ``dead-rule-row``)."""
    return _transformer_tp_rules(layout) + (
        (r"(^|/)wte$", layout.vocab_embedding()),
    )


def _moe_family_rules(layout: SpecLayout) -> Tuple[Rule, ...]:
    """MoE GPT-2 (models/gpt2.py with n_experts > 0): attention stays
    Megatron-split, the FFN is the expert stack — the dense fc_w/fc_b/
    fc_proj_w rows never match an MoE tree (the experts replace the
    dense FFN entirely), so they are omitted rather than kept dead."""
    tp = layout.tp_axis
    return (
        (r"(^|/)qkv_w$", PartitionSpec(None, None, tp)),
        (r"(^|/)qkv_b$", PartitionSpec(None, tp)),
        (r"(^|/)proj_w$", PartitionSpec(None, tp, None)),
    ) + _moe_rules(layout) + (
        (r"(^|/)wte$", layout.vocab_embedding()),
    )


register_family("gpt2", _gpt2_rules)
register_family("bert", _bert_rules)
register_family("neo", _neo_rules)
register_family("moe", _moe_family_rules)

_RULES_CACHE: Dict[Tuple[str, SpecLayout], PartitionRules] = {}


def rules_for_family(name: str, layout: SpecLayout = DEFAULT_LAYOUT) -> PartitionRules:
    """The built-in rule table for a model family (``gpt2`` / ``bert`` /
    ``neo`` / ``moe``)."""
    key = (name, layout)
    if key not in _RULES_CACHE:
        if name not in _FAMILIES:
            raise ValueError(f"unknown model family {name!r}; known: {sorted(_FAMILIES)}")
        _RULES_CACHE[key] = PartitionRules(_FAMILIES[name](layout), name=name, layout=layout)
    return _RULES_CACHE[key]


def rules_for_config(model_config: Any, layout: SpecLayout = DEFAULT_LAYOUT) -> PartitionRules:
    """Family rules for a model config object (GPT2Config → gpt2,
    BertConfig → bert) — how the inference/serving engines resolve."""
    for klass in type(model_config).__mro__:
        if klass.__name__ == "GPT2Config":
            return rules_for_family("gpt2", layout)
        if klass.__name__ == "BertConfig":
            return rules_for_family("bert", layout)
    raise ValueError(
        f"no built-in partition rules for model config {type(model_config).__name__}"
    )


def family_catalog() -> Dict[str, int]:
    """{family: rule count} for ds_report."""
    return {name: len(builder(DEFAULT_LAYOUT)) for name, builder in sorted(_FAMILIES.items())}


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)
