"""SpecLayout: canonical axis names and the spec constructors every
engine consumes (SNIPPETS.md [3] style).

The mesh axes are fixed framework-wide (comm/mesh.py ``MESH_AXES``):
``pipe``/``data``/``fsdp``/``seq``/``model``/``expert``.  A
:class:`SpecLayout` names them once so engines ask for *meanings*
("the batch spec", "per-rank exchange rows") instead of spelling axis
tuples — the seam the ``hand-built-partition-spec`` lint rule enforces.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple, Union

from jax.sharding import PartitionSpec

Axis = str
Axes = Union[Axis, Tuple[Axis, ...]]


@dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs over the framework mesh axes."""

    data_axis: Axis = "data"
    fsdp_axis: Axis = "fsdp"
    tp_axis: Axis = "model"
    pipe_axis: Axis = "pipe"
    seq_axis: Axis = "seq"
    expert_axis: Axis = "expert"

    # -- the DP grid ----------------------------------------------------
    @property
    def dp_axes(self) -> Tuple[Axis, Axis]:
        """The full data-parallel grid ZeRO partitions over: the pure
        ``data`` axis composed with the ``fsdp`` axis."""
        return (self.data_axis, self.fsdp_axis)

    # -- activations / batches -----------------------------------------
    def batch(self, ndim: int = 2, seq_dim: Optional[int] = 1, seq_sharded: bool = False) -> PartitionSpec:
        """Batch input: dim 0 over the dp grid, optionally the sequence
        dim over ``seq`` (context parallelism)."""
        spec: list = [None] * ndim
        spec[0] = self.dp_axes
        if seq_sharded and seq_dim is not None and ndim > seq_dim:
            spec[seq_dim] = self.seq_axis
        return PartitionSpec(*spec)

    def stacked_batch(self, ndim: int, seq_sharded: bool = False) -> PartitionSpec:
        """A (gas, micro, ...) stacked batch: replicated gas dim, then
        the normal batch spec."""
        return PartitionSpec(None, *tuple(self.batch(ndim - 1, seq_sharded=seq_sharded)))

    def micro_batch_stack(self, ndim: int = 2) -> PartitionSpec:
        """(M, mb, ...) micro-batch stack inside a pipelined step: the
        micro dim whole, the batch dim over the dp grid."""
        return PartitionSpec(None, self.dp_axes, *([None] * (ndim - 2)))

    # -- per-rank exchange rows ----------------------------------------
    def rows(self, axes: Optional[Axes] = None) -> PartitionSpec:
        """(n, M) per-rank rows sharded one row per rank of ``axes``
        (default: the dp grid) — the explicit-exchange layout."""
        return PartitionSpec(self.dp_axes if axes is None else axes)

    # -- parameters -----------------------------------------------------
    def replicated(self) -> PartitionSpec:
        return PartitionSpec()

    def vocab_embedding(self) -> PartitionSpec:
        """Vocab-parallel embedding table (V, D): vocab over tp."""
        return PartitionSpec(self.tp_axis, None)

    def column_parallel(self, ndim: int = 2) -> PartitionSpec:
        """Megatron column-parallel weight: output dim over tp."""
        return PartitionSpec(*([None] * (ndim - 1) + [self.tp_axis]))

    def row_parallel(self, ndim: int = 2) -> PartitionSpec:
        """Megatron row-parallel weight: input (contracted) dim over tp."""
        return PartitionSpec(*([None] * (ndim - 2) + [self.tp_axis, None]))

    def stacked(self, spec: Optional[PartitionSpec]) -> PartitionSpec:
        """Prepend the pipeline-stacked layer dim to a per-block spec."""
        return PartitionSpec(self.pipe_axis, *(tuple(spec) if spec is not None else ()))

    def fsdp_trailing(self, shape: Sequence[int], fsdp_size: int) -> PartitionSpec:
        """Stacked-block leaf ``(layers, ...)``: shard the largest
        trailing dim divisible by ``fsdp_size`` (the leading stacked dim
        stays whole); replicate when nothing divides — the
        ZeRO-Infinity group-upload layout (zero/param_offload.py)."""
        dims = list(shape)
        if fsdp_size <= 1 or len(dims) < 2:
            return PartitionSpec()
        best = None
        for i in range(len(dims) - 1, 0, -1):
            if dims[i] % fsdp_size == 0 and (best is None or dims[i] > dims[best]):
                best = i
        if best is None:
            return PartitionSpec()
        spec = [None] * len(dims)
        spec[best] = self.fsdp_axis
        return PartitionSpec(*spec)


DEFAULT_LAYOUT = SpecLayout()


# ---------------------------------------------------------------------------
# module-level helpers (the spellings engines import)
# ---------------------------------------------------------------------------

def batch_pspec(ndim: int = 2, seq_dim: Optional[int] = 1, seq_sharded: bool = False) -> PartitionSpec:
    """PartitionSpec for a batch input (see :meth:`SpecLayout.batch`)."""
    return DEFAULT_LAYOUT.batch(ndim, seq_dim=seq_dim, seq_sharded=seq_sharded)


def stacked_batch_pspec(ndim: int, seq_sharded: bool = False) -> PartitionSpec:
    return DEFAULT_LAYOUT.stacked_batch(ndim, seq_sharded=seq_sharded)


def stacked_micro_batch_pspec(ndim: int = 2) -> PartitionSpec:
    return DEFAULT_LAYOUT.micro_batch_stack(ndim)


def dp_rows_spec(axes: Optional[Axes] = None) -> PartitionSpec:
    return DEFAULT_LAYOUT.rows(axes)


def replicated_pspec() -> PartitionSpec:
    return PartitionSpec()


def fsdp_trailing_spec(shape: Sequence[int], fsdp_size: int) -> PartitionSpec:
    return DEFAULT_LAYOUT.fsdp_trailing(shape, fsdp_size)


def replicated_sharding(mesh):
    """A replicated NamedSharding on ``mesh`` (explicit device staging)."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh, ndim: int = 1, seq_sharded: bool = False):
    """NamedSharding for a batch of ``ndim`` dims on ``mesh``."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, batch_pspec(ndim, seq_sharded=seq_sharded))
