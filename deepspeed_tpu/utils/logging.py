"""Rank-aware logging.

TPU-native analog of the reference's ``deepspeed/utils/logging.py``
(``LoggerFactory`` at :15, ``log_dist`` at :48).  In a JAX multi-host
program "rank" means ``jax.process_index()``; inside a single-process
SPMD program every device is driven by one Python thread, so rank
filtering only matters across hosts.
"""
from __future__ import annotations

import functools
import logging
import os
import sys

_LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"


class LoggerFactory:
    @staticmethod
    def create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            handler = logging.StreamHandler(stream=sys.stdout)
            handler.setFormatter(logging.Formatter(_LOG_FORMAT))
            handler.setLevel(level)
            logger_.addHandler(handler)
        return logger_


logger = LoggerFactory.create_logger()


@functools.lru_cache(maxsize=1)
def _process_index() -> int:
    # Avoid importing jax at module import time (keeps CLI tools fast) and
    # tolerate running before distributed init.
    if "JAX_PROCESS_INDEX" in os.environ:
        return int(os.environ["JAX_PROCESS_INDEX"])
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks=None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given host ranks (default: rank 0 only).

    ``ranks=[-1]`` logs on every host — same contract as the reference
    (``utils/logging.py:48``).
    """
    my_rank = _process_index()
    if ranks is None:
        ranks = [0]
    if -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message: str) -> None:
    _warn_once_impl(message)


@functools.lru_cache(maxsize=None)
def _warn_once_impl(message: str) -> None:
    logger.warning(message)
