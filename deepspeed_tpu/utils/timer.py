"""Async-dispatch-aware wall clock timers and throughput accounting.

TPU-native analog of the reference's ``deepspeed/utils/timer.py``:
``SynchronizedWallClockTimer`` (:19) synchronizes CUDA streams around each
named timer; on TPU the equivalent barrier is blocking on the most recent
output array (``jax.block_until_ready``) — XLA dispatch is asynchronous, so
without a sync point wall-clock numbers only measure Python overhead.

``ThroughputTimer`` mirrors the reference's samples/sec logger (:100).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist

FORWARD_TIMER = "forward"
BACKWARD_TIMER = "backward"
STEP_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


def _sync(token: Any = None) -> None:
    """Block until device work feeding ``token`` (or all work) is done."""
    if token is not None:
        try:
            import jax

            jax.block_until_ready(token)
            return
        except Exception:
            pass
    # No token: rely on caller having something to block on; a plain
    # time.time() read still bounds host-side time.


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0

    def start(self, sync_token: Any = None) -> None:
        assert not self.started, f"timer {self.name} already started"
        _sync(sync_token)
        self._start = time.time()
        self.started = True

    def stop(self, sync_token: Any = None, record: bool = True) -> None:
        assert self.started, f"timer {self.name} not started"
        _sync(sync_token)
        if record:
            self._elapsed += time.time() - self._start
        self.started = False

    def reset(self) -> None:
        self.started = False
        self._elapsed = 0.0

    def elapsed(self, reset: bool = True) -> float:
        if self.started:
            # report including current in-flight interval
            now = time.time()
            value = self._elapsed + (now - self._start)
        else:
            value = self._elapsed
        if reset:
            self._elapsed = 0.0
            if self.started:
                self._start = time.time()
        return value


class SynchronizedWallClockTimer:
    """Named timers; ``sync_token`` lets callers pass the array whose
    readiness defines "device done" (cheaper than a full device sync)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has_timer(self, name: str) -> bool:
        return name in self.timers

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True, ranks=None) -> None:
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}ms")
        if parts:
            log_dist("time (ms) | " + " | ".join(parts), ranks=ranks)

    def get_mean(self, names: List[str], normalizer: float = 1.0, reset: bool = True) -> Dict[str, float]:
        out = {}
        for name in names:
            if name in self.timers:
                out[name] = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
        return out


class ThroughputTimer:
    def __init__(self, batch_size: int, start_step: int = 2, steps_per_output: int = 50, monitor_memory: bool = False, logging_fn=None):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or (lambda msg: log_dist(msg))
        self._window_start = 0.0
        self._window_step0 = 0

    def update_epoch_count(self) -> None:
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self) -> None:
        self.initialized = True

    def start(self) -> None:
        self.started = True
        if self.global_step_count >= self.start_step:
            self.start_time = time.time()

    def stop(self, sync_token: Any = None, report_speed: bool = True) -> None:
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        self.global_step_count += 1
        if self.start_time > 0:
            # sync only on reporting steps: a per-step device sync costs a
            # full host<->device round trip and defeats async dispatch
            # (the telescoped sum across a report window stays correct)
            will_report = report_speed and self.global_step_count % self.steps_per_output == 0
            if will_report:
                _sync(sync_token)
            self.end_time = time.time()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            if will_report:
                # "current" speed over the whole report window — per-step
                # durations are meaningless without per-step syncs
                window = self.end_time - self._window_start if self._window_start else duration
                window_steps = self.global_step_count - self._window_step0
                self.logging(
                    f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                    f"global_step={self.global_step_count}, "
                    f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.3f}, "
                    f"CurrSamplesPerSec={self.batch_size * window_steps / max(window, 1e-9):.3f}"
                )
                self._window_start = self.end_time
                self._window_step0 = self.global_step_count

    def avg_samples_per_sec(self) -> float:
        if self.global_step_count > self.start_step and self.total_elapsed_time > 0:
            samples = self.batch_size * (self.global_step_count - self.start_step)
            return samples / self.total_elapsed_time
        return float("nan")
