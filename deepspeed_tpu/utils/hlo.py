"""HLO text analysis helpers — collective byte accounting.

The reference tracks its comm volume implicitly (bucket sizes,
allgather_bucket_size knobs, stage2.py:1489 allgather tail); under XLA
the compiled HLO is the ground truth, so the framework ships a parser
that attributes wire bytes to each collective op.  Used by the ZeRO
comm bench rung (bench.py), the 1-bit wire-byte regression tests
(tests/test_onebit.py), and the ZeRO collective-byte regression test.
"""
from __future__ import annotations

import re
from typing import Dict, Optional

# op -> ring-traffic weight: an all-reduce moves ~2x its payload
# (reduce-scatter + all-gather phases); the others ~1x.
COLLECTIVE_WEIGHTS = {
    "all-reduce": 2,
    "all-gather": 1,
    "all-to-all": 1,
    "collective-permute": 1,
    "reduce-scatter": 1,
}

DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
    "s32": 4, "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def collective_bytes_by_op(hlo_text: str, dtype_filter: Optional[str] = None) -> Dict[str, int]:
    """Estimated wire bytes per collective op kind in an HLO dump.

    Byte counts are the op RESULT shapes times the ring weight — a
    first-order ring-traffic model, good for regression ratios and
    roofline demand estimates (not a cycle-accurate simulator).
    ``dtype_filter`` restricts to one dtype tag (e.g. "f32").
    """
    totals: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        parts = line.split(" = ", 1)
        if len(parts) != 2:
            continue
        rhs = parts[1]
        cut, weight, kind = -1, 1, None
        for c, w in COLLECTIVE_WEIGHTS.items():
            for op in (f" {c}(", f" {c}-start("):
                i = rhs.find(op)
                if i >= 0 and (cut < 0 or i < cut):
                    cut, weight, kind = i, w, c
        if cut < 0:
            continue
        n_bytes = 0
        for dt, dims in _SHAPE_RE.findall(rhs[:cut]):
            if dt not in DTYPE_BYTES or (dtype_filter and dt != dtype_filter):
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            n_bytes += n * DTYPE_BYTES[dt] * weight
        totals[kind] = totals.get(kind, 0) + n_bytes
    return totals


def collective_bytes(hlo_text: str, dtype_filter: Optional[str] = None) -> int:
    """Total estimated wire bytes of all collectives in an HLO dump."""
    return sum(collective_bytes_by_op(hlo_text, dtype_filter).values())
