"""Offline checkpoint → full fp32 state dict.

Reference: ``deepspeed/utils/zero_to_fp32.py`` (:119 core) — stitches the
per-rank ZeRO shard files back into one fp32 ``state_dict`` without a
live engine (the script the reference copies into every checkpoint dir).

Here the sharded-checkpoint format is orbax/tensorstore, which reshards
transparently on read — so "consolidation" is a metadata-driven restore
of the params subtree into host numpy, then an optional dump to ``.npz``
or a torch ``.pt`` (for handing weights back to torch tooling).
"""
from __future__ import annotations

import argparse
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _resolve_tag(checkpoint_dir: str, tag: Optional[str]) -> str:
    if tag is None:
        latest = os.path.join(checkpoint_dir, "latest")
        if not os.path.exists(latest):
            raise FileNotFoundError(f"no 'latest' file in {checkpoint_dir}; pass tag explicitly")
        with open(latest) as f:
            tag = f.read().strip()
    return tag


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir: str, tag: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Reference entry point of the same name: returns a flat
    {'path/to/param': fp32 ndarray} dict from a training checkpoint."""
    import orbax.checkpoint as ocp

    checkpoint_dir = os.path.abspath(checkpoint_dir)
    state_dir = os.path.join(checkpoint_dir, _resolve_tag(checkpoint_dir, tag), "state")
    ckptr = ocp.PyTreeCheckpointer()
    meta = ckptr.metadata(state_dir)
    meta_params = meta["params"] if isinstance(meta, dict) else meta.item_metadata.tree["params"]
    target = {
        "params": jax.tree.map(
            lambda m: np.zeros(m.shape, np.float32), meta_params, is_leaf=lambda m: hasattr(m, "shape")
        )
    }
    try:
        restored = ckptr.restore(
            state_dir, args=ocp.args.PyTreeRestore(item=target, partial_restore=True)
        )
    except TypeError:
        # older orbax has no partial_restore kwarg: read the whole tree
        # (host arrays) and keep the params subtree
        restored = {"params": ckptr.restore(state_dir)["params"]}

    flat: Dict[str, np.ndarray] = {}

    def visit(path, leaf):
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        flat[key] = np.asarray(leaf, np.float32)

    jax.tree_util.tree_map_with_path(visit, restored["params"])
    return flat


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir: str, output_file: str, tag: Optional[str] = None) -> None:
    """Reference entry point of the same name: write the consolidated
    weights to ``output_file`` (.npz, or .pt when torch is available)."""
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=tag)
    n_params = sum(v.size for v in sd.values())
    if output_file.endswith(".pt") or output_file.endswith(".bin"):
        import torch

        torch.save({k: torch.from_numpy(v.copy()) for k, v in sd.items()}, output_file)
    else:
        np.savez(output_file, **{k.replace("/", "::"): v for k, v in sd.items()})
    logger.info(f"saved {len(sd)} tensors ({n_params / 1e6:.1f}M params) to {output_file}")


def main():
    parser = argparse.ArgumentParser(description="consolidate a sharded checkpoint into full fp32 weights")
    parser.add_argument("checkpoint_dir", help="training checkpoint dir (contains 'latest')")
    parser.add_argument("output_file", help=".npz / .pt output path")
    parser.add_argument("-t", "--tag", default=None)
    args = parser.parse_args()
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir, args.output_file, tag=args.tag)


if __name__ == "__main__":
    main()
