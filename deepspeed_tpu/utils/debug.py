"""Debug helpers.

Reference: ``utils/debug.py`` — rank-interleaved printing with a file
lock (:61-118) plus tensor fingerprinting used when chasing divergence
across ranks.
"""
from __future__ import annotations

import fcntl
import os
import sys
from typing import Any

import numpy as np

_LOCK_PATH = "/tmp/deepspeed_tpu_debug.lock"


def _rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return int(os.environ.get("RANK", 0))


def print_rank_0(message: str) -> None:
    if _rank() == 0:
        print(message, flush=True)


def printflock(*msgs: Any) -> None:
    """Serialized cross-process print (reference ``printflock``): takes a
    file lock so concurrent ranks don't interleave lines."""
    with open(_LOCK_PATH, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            print(f"[rank {_rank()}]", *msgs, flush=True)
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def log_rank_file(*msgs: Any, path_template: str = "/tmp/ds_tpu_debug_rank{rank}.txt") -> None:
    """Per-rank debug files (reference ``log_rank_file``)."""
    with open(path_template.format(rank=_rank()), "a") as f:
        print(*msgs, file=f, flush=True)


def tensor_fingerprint(x: Any) -> str:
    """Small stable summary for divergence hunts: shape/dtype/norm/head."""
    arr = np.asarray(x)
    # f64 on purpose: fingerprints must not collide at f32 rounding
    flat = arr.reshape(-1).astype(np.float64) if arr.size else arr.reshape(-1)  # ds-lint: disable=float64-promotion
    head = np.array2string(flat[:4], precision=5) if arr.size else "[]"
    norm = float(np.linalg.norm(flat)) if arr.size else 0.0
    return f"shape={arr.shape} dtype={arr.dtype} l2={norm:.6g} head={head}"
