"""Training metrics monitor (TensorBoard).

Reference: the engine's tensorboardX summary-writer integration
(``engine.py:285-320`` config, ``:1178-1188`` loss events, ``:1356-1382``
lr/scale events; writer only on global rank 0) emitting
``Train/Samples/{train_loss,lr,loss_scale,elapsed_time_ms_*}``.

Uses ``torch.utils.tensorboard`` when available (torch-cpu ships in the
image); otherwise falls back to a JSONL event log with the same tags so
metrics are never silently dropped.
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional

from deepspeed_tpu.utils.logging import logger


class TensorBoardMonitor:
    def __init__(self, output_path: str = "", job_name: str = "DeepSpeedJobName", enabled: bool = True, rank: int = 0):
        self.enabled = enabled and rank == 0
        self._writer = None
        self._jsonl = None
        if not self.enabled:
            return
        out_dir = os.path.join(output_path or "runs", job_name)
        os.makedirs(out_dir, exist_ok=True)
        try:
            from torch.utils.tensorboard import SummaryWriter

            self._writer = SummaryWriter(log_dir=out_dir)
        except Exception as e:
            self._jsonl = open(os.path.join(out_dir, "events.jsonl"), "a")
            logger.warning(f"monitor: tensorboard unavailable ({e}); writing JSONL events to {out_dir}")

    def add_scalar(self, tag: str, value: float, global_step: int) -> None:
        if not self.enabled:
            return
        if self._writer is not None:
            self._writer.add_scalar(tag, float(value), int(global_step))
        elif self._jsonl is not None:
            self._jsonl.write(json.dumps({"tag": tag, "value": float(value), "step": int(global_step), "ts": time.time()}) + "\n")
            self._jsonl.flush()

    def write_events(self, events, global_step: int) -> None:
        """``events``: [(tag, value), ...] — reference summary_events shape."""
        for tag, value in events:
            self.add_scalar(tag, value, global_step)

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
        if self._jsonl is not None:
            self._jsonl.close()
