"""Training metrics monitor (TensorBoard).

Reference: the engine's tensorboardX summary-writer integration
(``engine.py:285-320`` config, ``:1178-1188`` loss events, ``:1356-1382``
lr/scale events; writer only on global rank 0) emitting
``Train/Samples/{train_loss,lr,loss_scale,elapsed_time_ms_*}``.

Uses ``torch.utils.tensorboard`` when available (torch-cpu ships in the
image); otherwise falls back to a JSONL event log with the same tags so
metrics are never silently dropped.

Lifecycle: every constructed monitor registers an atexit flush+close —
the JSONL fallback handle (and a buffering SummaryWriter) must not be
dropped unflushed when the process dies between report cadences.
``close()`` is idempotent and unregisters the hook.

In the telemetry plane (docs/telemetry.md) this class is a *sink*: the
engine publishes through the metrics registry and the manager forwards
the reference ``Train/Samples/*`` tags here; ``ds_lint``'s
``raw-metric-emit`` rule keeps new direct ``add_scalar`` call sites
from growing outside ``telemetry/``.
"""
from __future__ import annotations

import atexit
import json
import os
import time
from typing import Optional

from deepspeed_tpu.utils.logging import logger


class TensorBoardMonitor:
    def __init__(self, output_path: str = "", job_name: str = "DeepSpeedJobName", enabled: bool = True, rank: int = 0):
        self.enabled = enabled and rank == 0
        self._writer = None
        self._jsonl = None
        self._closed = False
        if not self.enabled:
            return
        out_dir = os.path.join(output_path or "runs", job_name)
        os.makedirs(out_dir, exist_ok=True)
        try:
            from torch.utils.tensorboard import SummaryWriter

            self._writer = SummaryWriter(log_dir=out_dir)
        except Exception as e:
            self._jsonl = open(os.path.join(out_dir, "events.jsonl"), "a")
            logger.warning(f"monitor: tensorboard unavailable ({e}); writing JSONL events to {out_dir}")
        atexit.register(self.close)

    def add_scalar(self, tag: str, value: float, global_step: int) -> None:
        if not self.enabled:
            return
        if self._writer is not None:
            self._writer.add_scalar(tag, float(value), int(global_step))
        elif self._jsonl is not None:
            self._jsonl.write(json.dumps({"tag": tag, "value": float(value), "step": int(global_step), "ts": time.time()}) + "\n")
            self._jsonl.flush()

    def write_events(self, events, global_step: int) -> None:
        """``events``: [(tag, value), ...] — reference summary_events shape."""
        for tag, value in events:
            self.add_scalar(tag, value, global_step)

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()
        elif self._jsonl is not None and not self._jsonl.closed:
            self._jsonl.flush()

    def close(self) -> None:
        """Flush + close both backends; idempotent (called by the
        engine, the telemetry shutdown, AND atexit — whichever first)."""
        if self._closed:
            return
        self._closed = True
        try:
            atexit.unregister(self.close)
        except Exception:  # pragma: no cover - interpreter teardown
            pass
        if self._writer is not None:
            self._writer.close()
        if self._jsonl is not None and not self._jsonl.closed:
            self._jsonl.close()
