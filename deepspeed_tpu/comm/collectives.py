"""Collective primitives — the one sanctioned home of raw ``lax.*``
collective call sites outside shard-level libraries.

Every engine-level gradient/activation exchange routes through this
module (or :mod:`deepspeed_tpu.comm.strategy`, which picks between the
implementations here); the ds_lint tier-B rule
``raw-collective-outside-comm-layer`` flags new direct
``lax.psum/psum_scatter/all_gather/...`` call sites elsewhere.  This is
the seam the reference's ``runtime/comm/{nccl,mpi}.py`` compressed
collectives occupied — here it also hosts the EQuARX-style quantized
allreduce (*EQuARX: Efficient Quantized AllReduce in XLA*, PAPERS.md):
int8 per-chunk scales with stochastic rounding, quantized at BOTH the
reduce-scatter and all-gather phases, so a ring exchange moves ~2
bytes/element instead of the dense fp32 allreduce's ~8.

Three wire tiers (see docs/comm.md for the byte model):

* ``dense``  — plain ``psum``/``psum_scatter``/``all_gather`` (GSPMD or
  explicit); ~8 B/param for a ring fp32 allreduce.
* ``int8``   — :func:`quantized_allreduce_replicated`; ~2 B/param, no
  state, unbiased under stochastic rounding.
* ``onebit`` — the error-feedback sign+L1-scale exchange
  (:mod:`deepspeed_tpu.comm.compressed`, re-exported here); ~2 B/param
  on TPU (int8 is the densest ICI-native format) with a persistent
  residual that bounds the long-run bias.
"""
# The primitives below run INSIDE shard_map bodies (or build them):
# layouts are pinned by the callers' in_specs/out_specs, not here.
# ds-lint: disable-file=missing-sharding-constraint
from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from deepspeed_tpu.comm.compressed import (  # noqa: F401  (re-exports: the 1-bit tier)
    _shard_map,
    _sm_flags,
    compress_chunks,
    compressed_allreduce,
    compressed_allreduce_compressed_out,
    compressed_allreduce_replicated,
    decompress_chunks,
)

AxisName = Union[str, Tuple[str, ...]]


def shard_map_manual(fn, mesh, in_specs, out_specs, manual_axes):
    """Version-compat ``shard_map`` with only ``manual_axes`` mapped
    manually (every other mesh axis stays automatic/GSPMD) and the
    replication check off.  Newer jax spells this ``axis_names=...`` +
    ``check_vma``; older jax spells it ``auto=<complement>`` +
    ``check_rep`` — the pipeline engine's per-stage bodies need it to
    run on both."""
    import inspect

    sm = _shard_map()
    params = inspect.signature(sm).parameters
    kw = dict(_sm_flags())
    if "axis_names" in params:
        kw["axis_names"] = set(manual_axes)
    elif "auto" in params:
        kw["auto"] = frozenset(a for a in mesh.axis_names if a not in manual_axes)
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


# ---------------------------------------------------------------------------
# in-axis primitives (usable inside shard_map bodies)
# ---------------------------------------------------------------------------

def axis_size(axis_name: AxisName):
    """Traced size of one (or a tuple of) mapped mesh axes."""
    return jax.lax.psum(1, axis_name)


def static_axis_size(axis_name: AxisName) -> int:
    """STATIC size of a mapped axis, usable to build ppermute perm
    lists inside a shard_map body.  Newer jax has ``lax.axis_size``;
    older jax constant-folds ``psum(1, axis)`` to the same value."""
    if hasattr(jax.lax, "axis_size"):
        return int(jax.lax.axis_size(axis_name))
    return int(jax.lax.psum(1, axis_name))


def flat_axis_index(axis_name: AxisName):
    """Flat mesh-major rank index over one axis or a tuple of axes —
    row ``i`` of an ``(n, M)`` exchange grid sharded ``P(axes)`` lives on
    the rank whose flat index is ``i``."""
    if isinstance(axis_name, (tuple, list)):
        idx = jnp.int32(0)
        for a in axis_name:
            idx = idx * jax.lax.psum(1, a) + jax.lax.axis_index(a)
        return idx
    return jax.lax.axis_index(axis_name)


def all_reduce(x, axis_name: AxisName):
    """Sum over the mapped axis (``lax.psum``)."""
    return jax.lax.psum(x, axis_name)


def all_reduce_mean(x, axis_name: AxisName):
    return jax.lax.pmean(x, axis_name)


def reduce_scatter(x, axis_name: AxisName, scatter_dimension: int = 0, tiled: bool = True):
    return jax.lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled
    )


def all_gather(x, axis_name: AxisName, **kw):
    return jax.lax.all_gather(x, axis_name, **kw)


def all_to_all(x, axis_name: AxisName, split_axis: int, concat_axis: int, tiled: bool = False):
    return jax.lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def p2p_shift(x, axis_name: str, n: int, shift: int = 1):
    """Ring point-to-point: every rank sends ``x`` to ``(i + shift) % n``
    (``lax.ppermute`` = XLA collective-permute riding ICI) — the pipeline
    engine's activation/cotangent rotation."""
    return jax.lax.ppermute(x, axis_name, [(i, (i + shift) % n) for i in range(n)])


def host_allgather(x):
    """Host-side cross-process allgather (the ZeRO-Offload masters
    reassembly / checkpoint flag-sync site).  Blocking on every process:
    keep call sites inside a supervision-armed region (the ds_lint
    ``unguarded-collective-barrier`` rule counts this wrapper as a
    blocking sync)."""
    from jax.experimental import multihost_utils

    # definition site of the wrapper itself — the barrier rule tracks
    # 'host_allgather' at CALL sites, where the armed region must live
    return multihost_utils.process_allgather(x)  # ds-lint: disable=unguarded-collective-barrier


# ---------------------------------------------------------------------------
# EQuARX-style int8 quantized allreduce
# ---------------------------------------------------------------------------

def _quantize_chunks_int8(xc: jnp.ndarray, key) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 quantization with one fp32 scale per leading chunk
    (``xc``: (k, chunk)).  ``key`` enables unbiased stochastic rounding
    (``floor(y + u)``, u ~ U[0,1)); None rounds to nearest."""
    amax = jnp.max(jnp.abs(xc), axis=1)
    scale = jnp.where(amax > 0, amax / 127.0, jnp.float32(1.0))
    y = xc / scale[:, None]
    if key is not None:
        q = jnp.floor(y + jax.random.uniform(key, y.shape, jnp.float32))
    else:
        q = jnp.rint(y)
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def _int8_body(x, key, *, axis_name: AxisName, stochastic: bool):
    """Per-rank body under shard_map: two-phase quantized allreduce-mean.

    Phase 1 (reduce-scatter shaped): quantize each destination chunk
    int8 with its own scale, exchange chunks via all_to_all; rank j
    dequantizes and averages the j-th chunk from every source.  Phase 2
    (all-gather shaped): re-quantize the served partial int8 and
    all-gather it back.  Wire: ~2 int8 bytes/element + 2 fp32
    scales/chunk — vs ~8 bytes/element for a dense fp32 ring allreduce.
    """
    n = jax.lax.psum(1, axis_name)
    xv = x[0]
    chunk = xv.shape[0] // n
    k1 = k2 = None
    if stochastic:
        kr = jax.random.fold_in(key, flat_axis_index(axis_name))
        k1, k2 = jax.random.split(kr)
    q, scale = _quantize_chunks_int8(xv.reshape(n, chunk), k1)
    served = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=False)
    served_scales = jax.lax.all_to_all(
        scale.reshape(n, 1), axis_name, split_axis=0, concat_axis=0, tiled=False
    )  # (n, 1): source i's scale for THIS rank's chunk
    partial = jnp.mean(served.astype(jnp.float32) * served_scales, axis=0)  # (chunk,)
    q2, scale2 = _quantize_chunks_int8(partial[None, :], k2)
    all_q = jax.lax.all_gather(q2[0], axis_name)  # (n, chunk)
    all_s = jax.lax.all_gather(scale2[0], axis_name)  # (n,)
    return (all_q.astype(jnp.float32) * all_s[:, None]).reshape(-1)


def quantized_allreduce_replicated(
    x_rows, mesh, axis_name: AxisName = "data", key=None, stochastic: bool = True
):
    """EQuARX-style int8 allreduce-mean over exchange rows.

    ``x_rows``: (n, M) — row i is rank i's local tensor, sharded
    ``P(axis_name)`` (M divisible by n).  Returns the replicated (M,)
    mean.  ``axis_name`` may be a tuple of mesh axes (the ZeRO-composed
    exchange over the whole dp grid, like
    :func:`~deepspeed_tpu.comm.compressed.compressed_allreduce`).
    ``stochastic`` + ``key``: unbiased stochastic rounding — required
    for convergence parity over many steps (nearest rounding carries a
    systematic sub-LSB bias).
    """
    from deepspeed_tpu.sharding.layout import dp_rows_spec, replicated_pspec

    n, m = x_rows.shape
    if m % n:
        raise ValueError(f"tensor length {m} not divisible by axis size {n}")
    stoch = bool(stochastic) and key is not None
    if key is None:
        key = jax.random.PRNGKey(0)  # unused when stoch is False

    def body(x, k):
        return _int8_body(x, k, axis_name=axis_name, stochastic=stoch)

    mapped = _shard_map()(
        body,
        mesh=mesh,
        in_specs=(dp_rows_spec(axis_name), replicated_pspec()),
        out_specs=replicated_pspec(),
        **_sm_flags(),
    )
    return mapped(x_rows, key)


def dense_allreduce_replicated(x_rows, mesh, axis_name: AxisName = "data"):
    """Full-precision allreduce-mean over exchange rows — the dense
    rung of the same (n, M)-rows interface, for A/B measurement."""
    from deepspeed_tpu.sharding.layout import dp_rows_spec, replicated_pspec

    def body(x):
        return jax.lax.pmean(x[0], axis_name)

    mapped = _shard_map()(
        body, mesh=mesh, in_specs=(dp_rows_spec(axis_name),), out_specs=replicated_pspec(),
        **_sm_flags(),
    )
    return mapped(x_rows)
