"""Named-axis cartesian rank grid.

Pure-logic port-equivalent of the reference's ``runtime/pipe/topology.py``
(``ProcessTopology`` :12, ``PipeDataParallelTopology`` :235,
``PipeModelDataParallelTopology`` :246).  On TPU, process groups are
replaced by mesh axis names, but the rank-grid bookkeeping is still needed
by the pipeline engine (stage ids, p2p neighbors) and by checkpoint naming
— and it is cheap pure Python, so the API is kept essentially intact.
"""
from __future__ import annotations

import itertools
from collections import namedtuple
from typing import Dict, List, Sequence, Tuple


class ProcessTopology:
    """Maps an N-dim cartesian coordinate (named axes) <-> flat rank.

    Axes are ordered outermost-first: ranks increment fastest along the
    *last* axis (same convention as the reference, topology.py:12-46).
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must align")
        self.axes = list(axes)
        self.dims = list(int(d) for d in dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self.mapping: Dict[Tuple[int, ...], int] = {}
        for rank, coord in enumerate(itertools.product(*[range(d) for d in self.dims])):
            self.mapping[coord] = rank

    def get_rank(self, **coord_kwargs) -> int:
        if sorted(coord_kwargs.keys()) != sorted(self.axes):
            raise ValueError(f"get_rank() requires all axes {self.axes}")
        key = tuple(coord_kwargs[a] for a in self.axes)
        if key not in self.mapping:
            raise ValueError(f"coord {coord_kwargs} out of range for dims {self.dims}")
        return self.mapping[key]

    def get_axis_names(self) -> List[str]:
        return list(self.axes)

    def get_rank_repr(self, rank: int, omit_axes: Sequence[str] = ("data", "pipe"), inner_sep: str = "_", outer_sep: str = "-") -> str:
        omit = set(omit_axes)
        coord = self.get_coord(rank)
        parts = []
        for axis in self.axes:
            if axis in omit:
                continue
            parts.append(f"{axis}{inner_sep}{getattr(coord, axis):02d}")
        return outer_sep.join(parts)

    def get_dim(self, axis: str) -> int:
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank: int):
        for coord, r in self.mapping.items():
            if r == rank:
                return self.ProcessCoord(*coord)
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """All rank-lists that vary only along ``axis`` (the reference's
        per-axis process groups, topology.py:131)."""
        if axis not in self.axes:
            return []
        idx = self.axes.index(axis)
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for other_coords in itertools.product(*[range(self.get_dim(a)) for a in other_axes]):
            ranks = []
            for axis_val in range(self.dims[idx]):
                coord = dict(zip(other_axes, other_coords))
                coord[axis] = axis_val
                ranks.append(self.get_rank(**coord))
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        def matches(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())

        return [rank for coord_t, rank in self.mapping.items() if matches(self.ProcessCoord(*coord_t))]

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return self.filter_match(**{axis: idx})

    @property
    def world_size(self) -> int:
        out = 1
        for d in self.dims:
            out *= d
        return out

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeDataParallelTopology(ProcessTopology):
    """[pipe, data] grid (reference topology.py:235-245): loading batches is
    cheaper than inter-stage comm, so data is the inner (fast) axis."""

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """[pipe, data, model] grid for 3D parallelism (reference :246-249)."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])
