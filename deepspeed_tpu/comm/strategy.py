"""Strategy-selected collectives: one comm layer for every exchange.

The reference hand-rolled its comm volume per subsystem (ZeRO bucketed
reduce-scatter, pipeline broadcast p2p, 1-bit Adam's compressed
``runtime/comm/nccl.py``).  Here every engine exchange routes through a
:class:`CommLayer`, which picks a wire strategy **per (tensor size,
dtype, axis/topology) at trace time** — the selection is ordinary
Python over static shapes, so switching strategies never recompiles and
every strategy compiles to exactly one executable.

Strategies (docs/comm.md):

* ``dense``  — full-precision; GSPMD sharding constraints for the grad
  path (psum / psum_scatter inserted by the partitioner), explicit
  ``lax`` collectives elsewhere.  ~8 B/param ring allreduce.
* ``int8``   — EQuARX-style quantized allreduce (per-chunk scale +
  stochastic rounding, quantized at both phases;
  :func:`~deepspeed_tpu.comm.collectives.quantized_allreduce_replicated`).
  ~2 B/param, stateless, unbiased.
* ``onebit`` — error-feedback sign + L1-scale compression generalized
  from 1-bit Adam's exchange (:mod:`deepspeed_tpu.comm.compressed`);
  ~2 B/param on TPU with a persistent residual carried in engine state.

The policy (:func:`select_strategy`) resolves ``comm.strategy = auto``
by size/dtype/topology; explicit ``dense``/``int8``/``onebit`` override
it (still subject to the dense floor: sub-threshold tensors, non-float
dtypes and single-rank axes never quantize).  Every decision lands in
``CommLayer.decisions`` — the table ds_report prints.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.comm import collectives
from deepspeed_tpu.config import constants as C
from deepspeed_tpu.utils.logging import logger

STRATEGY_AUTO = C.COMM_STRATEGY_AUTO
STRATEGY_DENSE = C.COMM_STRATEGY_DENSE
STRATEGY_INT8 = C.COMM_STRATEGY_INT8
STRATEGY_ONEBIT = C.COMM_STRATEGY_ONEBIT


@dataclass(frozen=True)
class Decision:
    """One policy-table row: which strategy a site got, and why."""

    strategy: str
    reason: str


def select_strategy(cfg, nbytes: int, dtype, n_ranks: int, link: str = "ici") -> Decision:
    """Pure policy: strategy for one exchange of ``nbytes`` bytes of
    ``dtype`` across ``n_ranks`` ranks riding ``link`` (``"ici"``,
    ``"dcn"``, or ``"ici+dcn"`` from the mesh topology descriptor),
    under a ``CommConfig``.

    The dense floor applies to every strategy request: quantization of
    integer/bool payloads is meaningless, a single-rank axis moves no
    bytes, and sub-threshold tensors are latency- (not bandwidth-)
    bound, where the quantize/dequantize round trip only adds steps.
    DCN-crossing exchanges hit the bandwidth wall ~25x sooner (per-link
    GB/s gap), so their dense floor is ``comm.dcn_threshold_bytes`` and
    ``auto`` compresses them aggressively (EQuARX motivation: topology
    is a first-class input to comm decisions)."""
    import jax.numpy as jnp

    crosses_dcn = link != "ici"
    threshold = cfg.dcn_threshold_bytes if crosses_dcn else cfg.threshold_bytes
    if n_ranks <= 1:
        return Decision(STRATEGY_DENSE, "axis size 1 — nothing crosses the wire")
    if not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        return Decision(STRATEGY_DENSE, f"dtype {jnp.dtype(dtype).name} is not a float — quantized exchange undefined")
    if nbytes < threshold:
        knob = "comm.dcn_threshold_bytes" if crosses_dcn else "comm.threshold_bytes"
        return Decision(
            STRATEGY_DENSE,
            f"{nbytes} B < {knob} ({threshold}) on {link} — latency-bound, dense wins",
        )
    want = cfg.strategy
    if want == STRATEGY_DENSE:
        if crosses_dcn:
            return Decision(
                STRATEGY_DENSE,
                f"comm.strategy = dense (explicit; NOTE: {link} link — "
                "strategy 'auto' would compress the inter-slice hops)",
            )
        return Decision(STRATEGY_DENSE, "comm.strategy = dense")
    if want == STRATEGY_INT8:
        return Decision(STRATEGY_INT8, f"comm.strategy = int8 ({link})")
    if want == STRATEGY_ONEBIT:
        ef = "with" if cfg.error_feedback else "WITHOUT"
        return Decision(STRATEGY_ONEBIT, f"comm.strategy = onebit ({ef} error feedback, {link})")
    # auto: bandwidth-bound float exchange on a multi-rank grid → int8
    # (stateless + unbiased; onebit needs the residual rows, so it stays
    # an explicit opt-in — its win over int8 is marginal on TPU, where
    # signs ride ICI as int8 anyway; see docs/comm.md)
    if crosses_dcn:
        return Decision(
            STRATEGY_INT8,
            f"auto policy: {nbytes} B float over {n_ranks} ranks crosses DCN "
            f"({link}) — compressed inter-slice exchange",
        )
    return Decision(
        STRATEGY_INT8,
        f"auto policy: {nbytes} B float over {n_ranks} ranks is bandwidth-bound",
    )


def strategy_wire_bytes_per_param(strategy: str, grad_bytes: int = 4) -> float:
    """First-order ring-traffic bytes/param of ONE gradient exchange
    (the utils/hlo.py convention: all-reduce counts 2x its payload).

    dense: ring allreduce of fp32 grads = 2 x 4 B.  int8/onebit: int8
    payload crosses twice (scatter-shaped all_to_all + gather-shaped
    all_gather) = 2 x 1 B, plus per-chunk fp32 scales (epsilon).
    """
    if strategy == STRATEGY_DENSE:
        return 2.0 * grad_bytes
    if strategy in (STRATEGY_INT8, STRATEGY_ONEBIT):
        return 2.0
    raise ValueError(f"unknown comm strategy {strategy!r}")


def step_comm_bytes(
    n_params: int,
    mesh_sizes: Dict[str, int],
    stage: int,
    gas: int = 1,
    strategy: str = STRATEGY_DENSE,
    param_bytes: int = 2,
    grad_bytes: int = 4,
    reduce_scatter: bool = True,
    topology=None,
) -> Dict[str, Any]:
    """Per-train-step collective-byte model extending
    :func:`~deepspeed_tpu.runtime.zero.stages.zero_step_comm_model` with
    the strategy-dependent gradient-exchange term.

    The ZeRO model covers the ``fsdp``-axis traffic (param gathers +
    grad reduce-scatter).  This adds the data-parallel grad exchange:
    dense runs per micro batch inside the accumulation scan (GSPMD
    reduces into the sharded accumulator), while the explicit
    compressed strategies accumulate per-rank rows locally and exchange
    ONCE per step — so their byte advantage grows with ``gas``.

    ``topology`` (a :class:`~deepspeed_tpu.sharding.mesh.MeshTopology`)
    splits the grad-exchange term into intra-slice (ICI) and
    inter-slice (DCN) rows when the exchange's grid spans slices.  The
    split is pure *attribution* — ``grad-exchange`` and ``total`` are
    unchanged (the runtime executes one flat exchange): the DCN row —
    the scarce-bandwidth one the policy table keys on — carries 1/ici
    of the ring weight and is gas-independent for the compressed
    strategies.
    """
    from deepspeed_tpu.runtime.zero.stages import zero_step_comm_model

    fsdp = mesh_sizes.get("fsdp", 1)
    data = mesh_sizes.get("data", 1)
    dp = data * fsdp
    base = zero_step_comm_model(
        n_params, fsdp, stage, gas=gas,
        param_bytes=param_bytes, grad_bytes=grad_bytes,
        reduce_scatter=reduce_scatter,
    )
    out = dict(base)
    dp_axes = ("data", "fsdp")
    dcn_ranks = topology.dcn_ranks(dp_axes) if topology is not None else 1
    if dp <= 1:
        ge = 0
    elif strategy == STRATEGY_DENSE:
        # the fsdp-axis share is already in `base`; add the data-axis
        # all-reduce when a pure-data axis exists (per micro batch)
        ge = 2 * n_params * grad_bytes * gas if data > 1 else 0
    else:
        # one whole-grid compressed exchange per step (rows accumulate
        # locally across micro batches): int8 payload both ways + the
        # fp32 scale vectors.  The explicit path replaces GSPMD grad
        # reduction ENTIRELY — grads never hit the base model's
        # reduce-scatter/all-reduce terms (the post-exchange constraint
        # on the replicated mean is a slice, not a reduce), so zero them
        out["reduce-scatter"] = 0
        out["all-reduce"] = 0
        ge = 2 * n_params + 8 * dp
    if ge > 0 and topology is not None:
        # link-tier attribution of the SAME flat exchange (the runtime
        # executes one flat ring — the split does not change `ge` or
        # `total`, it only names where the bytes ride): a ring over a
        # grid spanning `split_dcn` slices crosses DCN on split_dcn of
        # its hops, so the DCN row carries 1/ici of the ring weight —
        # the scarce-bandwidth row the policy table keys on, and
        # gas-independent for the compressed strategies (their flat ge
        # is).  Dense with data==1 has ge==0 (its fsdp share lives in
        # `base`), so no rows are fabricated for it.
        split_axes = ("data",) if strategy == STRATEGY_DENSE else dp_axes
        grid = data if strategy == STRATEGY_DENSE else dp
        split_dcn = topology.dcn_ranks(split_axes)
        if split_dcn > 1:
            inter = ge * split_dcn // grid  # == ge / ici ranks
            out["grad-exchange-ici"] = int(ge - inter)
            out["grad-exchange-dcn"] = int(inter)
    out["grad-exchange"] = int(ge)
    out["strategy"] = strategy
    out["total"] = int(out["all-gather"] + out["reduce-scatter"] + out["all-reduce"] + ge)
    return out


class CommLayer:
    """Per-engine comm facade: policy decisions + the exchange entry
    points.  Construction is cheap; everything here is trace-time."""

    def __init__(self, mesh, mesh_info, config, zero_config=None, topology=None):
        self.mesh = mesh
        self.mesh_info = mesh_info
        self.config = config
        self.zero_config = zero_config
        # ICI×DCN topology descriptor (sharding/mesh.py); None = assume
        # single-slice all-ICI (the pre-multi-slice behavior)
        self.topology = topology
        # site -> Decision: the active strategy table (ds_report rows)
        self.decisions: Dict[str, Decision] = {}

    # -- policy ---------------------------------------------------------
    def _axis_ranks(self, axes) -> int:
        names = axes if isinstance(axes, (tuple, list)) else (axes,)
        return int(np.prod([self.mesh_info.sizes.get(a, 1) for a in names]))

    def _axis_link(self, axes) -> str:
        """The link kind an exchange over ``axes`` rides (topology row
        key: ici / dcn / ici+dcn)."""
        if self.topology is None:
            return "ici"
        names = axes if isinstance(axes, (tuple, list)) else (axes,)
        links = {self.topology.link(a) for a in names if self.mesh_info.sizes.get(a, 1) > 1}
        if not links or links == {"ici"}:
            return "ici"
        if links == {"dcn"}:
            return "dcn"
        return "ici+dcn"

    def select(self, nbytes: int, dtype, axes, site: str) -> str:
        """Pick + record the strategy for one exchange site, keyed on
        the (size, dtype, rank-count, link) row of the policy table."""
        d = select_strategy(
            self.config, int(nbytes), dtype, self._axis_ranks(axes),
            link=self._axis_link(axes),
        )
        self.decisions[site] = d
        self._publish_decision(site, d.strategy)
        if d.strategy == STRATEGY_DENSE and self.config.strategy in (STRATEGY_INT8, STRATEGY_ONEBIT):
            logger.info(f"comm: site '{site}' stays dense ({d.reason})")
        return d.strategy

    def note(self, site: str, strategy: str, reason: str) -> None:
        """Record a decision made elsewhere (e.g. the engine's blocker
        fallbacks, or the 1-bit optimizer's momentum exchange)."""
        self.decisions[site] = Decision(strategy, reason)
        self._publish_decision(site, strategy)

    def _publish_decision(self, site: str, strategy: str) -> None:
        """Per-site strategy decisions into the telemetry registry +
        trace (docs/telemetry.md).  Trace-time only — decisions happen
        at engine build / first lowering, never per step."""
        from deepspeed_tpu.telemetry import get_registry, get_tracer

        reg = get_registry()
        if reg.enabled:
            reg.counter("comm/decisions", site=site, strategy=strategy).inc()
        tracer = get_tracer()
        if tracer.enabled:
            tracer.add_instant("comm_decision", "comm",
                               args={"site": site, "strategy": strategy})

    # -- dense (GSPMD) grad path ---------------------------------------
    def constrain_grads(self, grads, shardings, site: str = "grad-exchange"):
        """The dense gradient-exchange site: the sharding constraint is
        what makes GSPMD insert the grad psum (replicated spec) or
        psum_scatter (fsdp-sharded spec, ZeRO >= 2) when it partitions
        the step — there is no host-visible collective to call."""
        import jax

        if site not in self.decisions:
            self.decisions[site] = Decision(
                STRATEGY_DENSE, "GSPMD-inserted psum/psum_scatter from grad sharding constraints"
            )
        return jax.lax.with_sharding_constraint(grads, shardings)

    # -- explicit rows exchange ----------------------------------------
    def exchange_rows(
        self,
        rows,
        axes,
        strategy: str,
        rng=None,
        residuals: Optional[Tuple[Any, Any]] = None,
    ):
        """Allreduce-mean of per-rank rows ``(n, M)`` sharded over
        ``axes`` under the given strategy.  Returns ``(mean (M,)
        replicated, new_residuals | None)``; only ``onebit`` with error
        feedback consumes/produces residuals."""
        import jax.numpy as jnp

        if strategy == STRATEGY_DENSE:
            return collectives.dense_allreduce_replicated(rows, self.mesh, axes), None
        if strategy == STRATEGY_INT8:
            out = collectives.quantized_allreduce_replicated(
                rows, self.mesh, axes, key=rng,
                stochastic=self.config.stochastic_rounding,
            )
            return out, None
        if strategy == STRATEGY_ONEBIT:
            n, m = rows.shape
            if residuals is None:
                # EF disabled: stateless sign+scale exchange (biased per
                # step; the residual that would carry the bias forward is
                # dropped) — the measurement rung for "EF off"
                werr = jnp.zeros((n, m), jnp.float32)
                serr = jnp.zeros((n, m // n), jnp.float32)
                out, _, _ = collectives.compressed_allreduce_replicated(
                    rows, werr, serr, self.mesh, axes
                )
                return out, None
            werr, serr = residuals
            out, new_werr, new_serr = collectives.compressed_allreduce_replicated(
                rows, werr, serr, self.mesh, axes
            )
            return out, (new_werr, new_serr)
        raise ValueError(f"unknown comm strategy {strategy!r}")

    # -- p2p / host -----------------------------------------------------
    def p2p_shift(self, x, axis_name: str, n: int, shift: int = 1, site: str = "pipe-p2p"):
        if site not in self.decisions:
            self.decisions[site] = Decision(STRATEGY_DENSE, "activation p2p rides ICI dense (quantized p2p: future)")
        return collectives.p2p_shift(x, axis_name, n, shift)

    def host_allgather(self, x, site: str = "offload-masters-allgather"):
        if site not in self.decisions:
            self.decisions[site] = Decision(STRATEGY_DENSE, "host-side process allgather (fp32 master slices)")
        # passthrough — callers hold the supervision-armed region
        return collectives.host_allgather(x)  # ds-lint: disable=unguarded-collective-barrier

    # -- reporting ------------------------------------------------------
    def table(self) -> Dict[str, Tuple[str, str]]:
        return {site: (d.strategy, d.reason) for site, d in sorted(self.decisions.items())}
