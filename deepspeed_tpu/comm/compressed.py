"""Error-feedback 1-bit compressed collectives.

TPU-native port of the reference's compressed allreduce algorithm
(``runtime/comm/nccl.py:47-186``; same algorithm over MPI in
``comm/mpi.py``): each rank adds its error-feedback residual, compresses
to sign bits + an L1 scale, exchanges chunks (all_to_all), every rank
averages the signs it "serves", re-compresses with a server-side
residual, and all-gathers the result.  cupy bit-packing + NCCL
primitives become pure XLA ops inside ``shard_map`` over a named mesh
axis — on TPU the sign tensors ride ICI as int8 (XLA has no bit-packed
dtype; volume saving is 4× vs fp32 rather than the reference's ~32×,
but the error-feedback math and convergence behavior are identical,
and int8 is the densest ICI-native exchange format).

State (worker_error, server_error) lives in the optimizer state.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def _shard_map():
    sm = getattr(jax, "shard_map", None)
    if sm is None:  # older jax fallback
        from jax.experimental.shard_map import shard_map as sm
    return sm


def _sm_flags() -> dict:
    """Replication-check opt-out kwarg across jax versions: newer
    shard_map spells it ``check_vma``, older ``check_rep``."""
    import inspect

    params = inspect.signature(_shard_map()).parameters
    if "check_vma" in params:
        return {"check_vma": False}
    if "check_rep" in params:
        return {"check_rep": False}
    return {}


def _sign_compress(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Compress to {-1,+1} int8 signs + scalar L1 scale (reference
    nccl.py:76-86: scale = |x|.mean(); sign with 0→+1)."""
    scale = jnp.mean(jnp.abs(x))
    signs = jnp.where(x >= 0, jnp.int8(1), jnp.int8(-1))
    return signs, scale


def _decompress(signs: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    return signs.astype(jnp.float32) * scale


def _body(x, worker_error, server_error, *, axis_name: str):
    """Per-rank body under shard_map.  Shapes (leading mapped dim of 1):
    x, worker_error: (1, M); server_error: (1, M//n).  Returns the
    averaged tensor (1, M) (identical on every rank) + new errors."""
    n = jax.lax.psum(1, axis_name)
    x = x[0]
    werr = worker_error[0]
    serr = server_error[0]
    m = x.shape[0]
    chunk = m // n

    corrected = x + werr
    signs, scale = _sign_compress(corrected)
    new_werr = corrected - _decompress(signs, scale)

    # Phase 1 — scatter: rank j receives chunk j from every rank
    # (reference's all_to_all_single, nccl.py:99) + scales via all_gather.
    served = jax.lax.all_to_all(signs.reshape(n, chunk), axis_name, split_axis=0, concat_axis=0, tiled=False)
    scales = jax.lax.all_gather(scale, axis_name)  # (n,)
    avg = jnp.mean(served.astype(jnp.float32) * scales[:, None], axis=0)  # (chunk,)

    # Phase 2 — server-side re-compress with server error feedback
    # (nccl.py:120-150).
    corrected_srv = avg + serr
    srv_signs, srv_scale = _sign_compress(corrected_srv)
    new_serr = corrected_srv - _decompress(srv_signs, srv_scale)

    # Phase 3 — allgather the served slices back (nccl.py:152-170).
    all_signs = jax.lax.all_gather(srv_signs, axis_name)  # (n, chunk)
    all_scales = jax.lax.all_gather(srv_scale, axis_name)  # (n,)
    out = (all_signs.astype(jnp.float32) * all_scales[:, None]).reshape(-1)
    return out[None], new_werr[None], new_serr[None]


def _exchange(x_per_rank, worker_error, server_error, mesh, axis_name, replicated_out: bool):
    # per-rank exchange rows resolve through the partition-rule engine's
    # layout helpers (one row per rank of the exchange grid)
    from deepspeed_tpu.sharding.layout import dp_rows_spec, replicated_pspec

    n, m = x_per_rank.shape
    if m % n:
        raise ValueError(f"tensor length {m} not divisible by axis size {n}")

    rows = dp_rows_spec(axis_name)

    def body(x, werr, serr):
        out, new_werr, new_serr = _body(x, werr, serr, axis_name=axis_name)
        return (out[0] if replicated_out else out), new_werr, new_serr

    mapped = _shard_map()(
        body,
        mesh=mesh,
        in_specs=(rows, rows, rows),
        out_specs=(replicated_pspec() if replicated_out else rows, rows, rows),
        **_sm_flags(),
    )
    return mapped(x_per_rank, worker_error, server_error)


def compressed_allreduce(x_per_rank, worker_error, server_error, mesh, axis_name="data"):
    """1-bit error-feedback averaged allreduce.

    ``x_per_rank``: (n, M) — row i is rank i's local tensor (M divisible
    by n).  ``worker_error``: (n, M).  ``server_error``: (n, M // n).
    Returns (avg (n, M) — every row identical, new_worker_error,
    new_server_error), all sharded over ``axis_name``.

    ``axis_name`` may be one mesh axis name or a TUPLE of axis names —
    e.g. ``("data", "fsdp")`` runs the exchange flat across the whole
    data-parallel grid, the ZeRO-composed form (n = product of the axis
    sizes; rank order is mesh-major).  The reference's 1-bit Adam never
    composes with ZeRO (onebit/adam.py:110 under FP16_UnfusedOptimizer
    only); here it is just a bigger ring.
    """
    return _exchange(x_per_rank, worker_error, server_error, mesh, axis_name, replicated_out=False)


def compressed_allreduce_replicated(x_per_rank, worker_error, server_error, mesh, axis_name="data"):
    """Like :func:`compressed_allreduce` but returns the averaged vector
    as a single replicated ``(M,)`` array — free, because phase 3's
    all-gather already leaves the full result on every rank; declaring
    the output replicated avoids a redundant broadcast at the engine
    boundary (this is the training-path entry point)."""
    return _exchange(x_per_rank, worker_error, server_error, mesh, axis_name, replicated_out=True)


def compressed_allreduce_compressed_out(
    x_per_rank, worker_error, server_error, mesh, axis_name="data"
):
    """Like :func:`compressed_allreduce_replicated` but returns the
    averaged vector in its COMPRESSED form — ``(signs (M,) int8,
    scales (n,) fp32)`` with ``out = decompress_chunks(signs, scales)``
    — instead of the decompressed fp32 vector.  Phase 3's all-gather
    already moves exactly these bytes; exposing them lets the caller
    STORE the synced momentum at 1 byte/param (it is exactly
    sign×chunk-scale by construction) and decompress transiently."""
    from deepspeed_tpu.sharding.layout import dp_rows_spec, replicated_pspec

    n, m = x_per_rank.shape
    if m % n:
        raise ValueError(f"tensor length {m} not divisible by axis size {n}")
    rows = dp_rows_spec(axis_name)

    def body(x, werr, serr):
        n_ = jax.lax.psum(1, axis_name)
        xv, we, se = x[0], werr[0], serr[0]
        chunk = xv.shape[0] // n_

        corrected = xv + we
        signs, scale = _sign_compress(corrected)
        new_werr = corrected - _decompress(signs, scale)

        served = jax.lax.all_to_all(
            signs.reshape(n_, chunk), axis_name, split_axis=0, concat_axis=0, tiled=False
        )
        scales = jax.lax.all_gather(scale, axis_name)
        avg = jnp.mean(served.astype(jnp.float32) * scales[:, None], axis=0)

        corrected_srv = avg + se
        srv_signs, srv_scale = _sign_compress(corrected_srv)
        new_serr = corrected_srv - _decompress(srv_signs, srv_scale)

        all_signs = jax.lax.all_gather(srv_signs, axis_name).reshape(-1)  # (M,)
        all_scales = jax.lax.all_gather(srv_scale, axis_name)  # (n,)
        return all_signs, all_scales, new_werr[None], new_serr[None]

    mapped = _shard_map()(
        body,
        mesh=mesh,
        in_specs=(rows, rows, rows),
        out_specs=(replicated_pspec(), replicated_pspec(), rows, rows),
        **_sm_flags(),
    )
    return mapped(x_per_rank, worker_error, server_error)


def decompress_chunks(signs: jnp.ndarray, scales: jnp.ndarray) -> jnp.ndarray:
    """Rebuild the fp32 vector from per-chunk sign compression:
    ``signs`` (M,) int8, ``scales`` (n,) — chunk i spans
    ``[i*M/n, (i+1)*M/n)`` (the all-to-all chunking)."""
    n = scales.shape[0]
    return (signs.reshape(n, -1).astype(jnp.float32) * scales[:, None]).reshape(-1)


def compress_chunks(x: jnp.ndarray, n: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-chunk sign compression of a flat vector (the server-side
    granularity): returns (signs (M,) int8, scales (n,))."""
    xc = x.reshape(n, -1)
    scales = jnp.mean(jnp.abs(xc), axis=1)
    signs = jnp.where(xc >= 0, jnp.int8(1), jnp.int8(-1)).reshape(-1)
    return signs, scales
