"""Named-axis SPMD device mesh.

TPU-native replacement for the reference's process-group plumbing
(``runtime/pipe/topology.py`` grids + ``torch.distributed`` groups,
SURVEY.md §2.6): one ``jax.sharding.Mesh`` with named axes replaces every
process group.  Axis names:

* ``pipe``   — pipeline stages (reference PP axis)
* ``data``   — pure data parallel (gradients all-reduced)
* ``fsdp``   — ZeRO/FSDP axis: params/grads/opt-state sharded here
               (reference's ZeRO partitioning over the DP group)
* ``seq``    — sequence/context parallel (ring attention)
* ``model``  — tensor parallel (reference's mpu "model"/"slice" axis)
* ``expert`` — expert parallel (MoE)

The reference's ZeRO partitions over the *entire* DP group; here the DP
group is factored into ``data × fsdp`` so ZeRO stage selection is a
sharding-rule choice, not a different optimizer class.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.utils.logging import logger

# Canonical axis order: outermost (slowest-varying, most DCN-tolerant) first.
# pipe and data tolerate slower links; model/seq need the fastest ICI, so they
# are innermost (adjacent device ids share a physical link on TPU slices).
MESH_AXES: Tuple[str, ...] = ("pipe", "data", "fsdp", "seq", "model", "expert")


def resolve_mesh_shape(cfg: MeshConfig, n_devices: int) -> Dict[str, int]:
    """Fill in the -1 ("remaining") axis and validate the product."""
    sizes = {ax: int(getattr(cfg, ax)) for ax in MESH_AXES}
    free = [ax for ax, s in sizes.items() if s == -1]
    if len(free) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {free}")
    fixed = 1
    for ax, s in sizes.items():
        if s != -1:
            if s < 1:
                raise ValueError(f"mesh axis {ax} must be >=1 or -1, got {s}")
            fixed *= s
    if free:
        rem, mod = divmod(n_devices, fixed)
        if mod:
            raise ValueError(f"{n_devices} devices not divisible by fixed axes product {fixed}")
        sizes[free[0]] = rem
    total = int(np.prod(list(sizes.values())))
    if total != n_devices:
        raise ValueError(f"Mesh {sizes} covers {total} devices but {n_devices} are available")
    return sizes


def make_mesh(cfg: Optional[MeshConfig] = None, devices: Optional[Sequence] = None):
    """Build the framework mesh over the given (default: all) devices."""
    import jax
    from jax.sharding import Mesh

    if cfg is None:
        cfg = MeshConfig()
    if devices is None:
        devices = jax.devices()
    sizes = resolve_mesh_shape(cfg, len(devices))
    shape = tuple(sizes[ax] for ax in MESH_AXES)
    dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, MESH_AXES)
    logger.info(
        "mesh: " + " × ".join(f"{ax}={sizes[ax]}" for ax in MESH_AXES if sizes[ax] > 1 or ax == "data")
    )
    return mesh


@dataclasses.dataclass(frozen=True)
class MeshInfo:
    """Cheap axis-size accessors mirroring the reference's grid API
    (``PipelineParallelGrid.get_*_parallel_world_size``, topology.py:252+)."""

    sizes: Dict[str, int]

    @classmethod
    def from_mesh(cls, mesh) -> "MeshInfo":
        return cls(sizes=dict(zip(mesh.axis_names, mesh.devices.shape)))

    @property
    def dp_world_size(self) -> int:
        # The reference's "data parallel world size" = everything ZeRO
        # partitions over = data × fsdp here.
        return self.sizes.get("data", 1) * self.sizes.get("fsdp", 1)

    @property
    def fsdp_world_size(self) -> int:
        return self.sizes.get("fsdp", 1)

    @property
    def model_parallel_world_size(self) -> int:
        return self.sizes.get("model", 1)

    @property
    def pipe_parallel_world_size(self) -> int:
        return self.sizes.get("pipe", 1)

    @property
    def seq_parallel_world_size(self) -> int:
        return self.sizes.get("seq", 1)

    @property
    def expert_parallel_world_size(self) -> int:
        return self.sizes.get("expert", 1)

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.sizes.values())))


# ---------------------------------------------------------------------------
# Standard sharding specs
# ---------------------------------------------------------------------------

def batch_pspec(ndim: int = 2, seq_dim: Optional[int] = 1, seq_sharded: bool = False):
    """PartitionSpec for a batch input: dim 0 sharded over (data, fsdp)
    — fsdp ranks see distinct micro-slices (the fsdp axis is part of the
    DP group, matching ZeRO's partitioning over the whole DP world) — and
    optionally the sequence dim over ``seq`` for context parallelism."""
    from jax.sharding import PartitionSpec as P

    spec = [None] * ndim
    spec[0] = ("data", "fsdp")
    if seq_sharded and seq_dim is not None and ndim > seq_dim:
        spec[seq_dim] = "seq"
    return P(*spec)


def replicated_pspec():
    from jax.sharding import PartitionSpec as P

    return P()
