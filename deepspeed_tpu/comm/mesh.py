"""Named-axis SPMD device mesh.

TPU-native replacement for the reference's process-group plumbing
(``runtime/pipe/topology.py`` grids + ``torch.distributed`` groups,
SURVEY.md §2.6): one ``jax.sharding.Mesh`` with named axes replaces every
process group.  Axis names:

* ``pipe``   — pipeline stages (reference PP axis)
* ``data``   — pure data parallel (gradients all-reduced)
* ``fsdp``   — ZeRO/FSDP axis: params/grads/opt-state sharded here
               (reference's ZeRO partitioning over the DP group)
* ``seq``    — sequence/context parallel (ring attention)
* ``model``  — tensor parallel (reference's mpu "model"/"slice" axis)
* ``expert`` — expert parallel (MoE)

The reference's ZeRO partitions over the *entire* DP group; here the DP
group is factored into ``data × fsdp`` so ZeRO stage selection is a
sharding-rule choice, not a different optimizer class.

Mesh construction and the ICI×DCN topology machinery live in
:mod:`deepspeed_tpu.sharding.mesh` (the partition-rule engine's home);
this module keeps the historical entry points (``make_mesh``,
``batch_pspec``) and the cheap :class:`MeshInfo` accessors.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import numpy as np

# canonical definitions now live in the sharding package; re-exported
# here for the historical import paths
from deepspeed_tpu.sharding.layout import batch_pspec, replicated_pspec  # noqa: F401
from deepspeed_tpu.sharding.mesh import (  # noqa: F401
    MESH_AXES,
    build_mesh,
    resolve_mesh_shape,
    split_dcn_ici,
)


def make_mesh(cfg=None, devices: Optional[Sequence] = None):
    """Build the framework mesh over the given (default: all) devices.

    Multi-host / multi-slice device sets get the 2-level hybrid ICI×DCN
    arrangement so only DCN-tolerant outer axes cross slow links (see
    :func:`deepspeed_tpu.sharding.mesh.build_mesh`, which also returns
    the topology descriptor)."""
    mesh, _ = build_mesh(cfg, devices)
    return mesh


@dataclasses.dataclass(frozen=True)
class MeshInfo:
    """Cheap axis-size accessors mirroring the reference's grid API
    (``PipelineParallelGrid.get_*_parallel_world_size``, topology.py:252+)."""

    sizes: Dict[str, int]

    @classmethod
    def from_mesh(cls, mesh) -> "MeshInfo":
        return cls(sizes=dict(zip(mesh.axis_names, mesh.devices.shape)))

    @property
    def dp_world_size(self) -> int:
        # The reference's "data parallel world size" = everything ZeRO
        # partitions over = data × fsdp here.
        return self.sizes.get("data", 1) * self.sizes.get("fsdp", 1)

    @property
    def fsdp_world_size(self) -> int:
        return self.sizes.get("fsdp", 1)

    @property
    def model_parallel_world_size(self) -> int:
        return self.sizes.get("model", 1)

    @property
    def pipe_parallel_world_size(self) -> int:
        return self.sizes.get("pipe", 1)

    @property
    def seq_parallel_world_size(self) -> int:
        return self.sizes.get("seq", 1)

    @property
    def expert_parallel_world_size(self) -> int:
        return self.sizes.get("expert", 1)

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.sizes.values())))
