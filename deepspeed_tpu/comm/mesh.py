"""Named-axis SPMD device mesh.

TPU-native replacement for the reference's process-group plumbing
(``runtime/pipe/topology.py`` grids + ``torch.distributed`` groups,
SURVEY.md §2.6): one ``jax.sharding.Mesh`` with named axes replaces every
process group.  Axis names:

* ``pipe``   — pipeline stages (reference PP axis)
* ``data``   — pure data parallel (gradients all-reduced)
* ``fsdp``   — ZeRO/FSDP axis: params/grads/opt-state sharded here
               (reference's ZeRO partitioning over the DP group)
* ``seq``    — sequence/context parallel (ring attention)
* ``model``  — tensor parallel (reference's mpu "model"/"slice" axis)
* ``expert`` — expert parallel (MoE)

The reference's ZeRO partitions over the *entire* DP group; here the DP
group is factored into ``data × fsdp`` so ZeRO stage selection is a
sharding-rule choice, not a different optimizer class.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.utils.logging import logger

# Canonical axis order: outermost (slowest-varying, most DCN-tolerant) first.
# pipe and data tolerate slower links; model/seq need the fastest ICI, so they
# are innermost (adjacent device ids share a physical link on TPU slices).
MESH_AXES: Tuple[str, ...] = ("pipe", "data", "fsdp", "seq", "model", "expert")


def resolve_mesh_shape(cfg: MeshConfig, n_devices: int) -> Dict[str, int]:
    """Fill in the -1 ("remaining") axis and validate the product."""
    sizes = {ax: int(getattr(cfg, ax)) for ax in MESH_AXES}
    free = [ax for ax, s in sizes.items() if s == -1]
    if len(free) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {free}")
    fixed = 1
    for ax, s in sizes.items():
        if s != -1:
            if s < 1:
                raise ValueError(f"mesh axis {ax} must be >=1 or -1, got {s}")
            fixed *= s
    if free:
        rem, mod = divmod(n_devices, fixed)
        if mod:
            raise ValueError(f"{n_devices} devices not divisible by fixed axes product {fixed}")
        sizes[free[0]] = rem
    total = int(np.prod(list(sizes.values())))
    if total != n_devices:
        raise ValueError(f"Mesh {sizes} covers {total} devices but {n_devices} are available")
    return sizes


def split_dcn_ici(sizes: Dict[str, int], n_processes: int) -> Optional[Tuple[Dict[str, int], Dict[str, int]]]:
    """Factor each axis into (DCN, ICI) parts for a multi-host mesh: the
    process count is absorbed by the outermost (most DCN-tolerant) axes
    first — ``pipe`` and ``data`` ride the slow inter-host links, while
    ``model``/``seq`` stay inside a host's ICI domain (SURVEY §2.6 /
    scaling-book mesh recipe).  Returns (dcn_sizes, ici_sizes) or None
    when the process count cannot be factored into the axis sizes."""
    import math

    dcn = {ax: 1 for ax in sizes}
    ici = dict(sizes)
    left = n_processes
    for ax in MESH_AXES:  # outermost first
        if left == 1:
            break
        f = math.gcd(left, ici[ax])
        # absorb the largest factor of `left` that divides this axis
        while f > 1 and left % f == 0 and ici[ax] % f == 0:
            dcn[ax] *= f
            ici[ax] //= f
            left //= f
            f = math.gcd(left, ici[ax])
    return None if left != 1 else (dcn, ici)


def make_mesh(cfg: Optional[MeshConfig] = None, devices: Optional[Sequence] = None):
    """Build the framework mesh over the given (default: all) devices.

    Multi-host: devices are arranged with
    ``mesh_utils.create_hybrid_device_mesh`` so axis neighbors inside a
    host connect over ICI and only the DCN-tolerant outer axes cross
    hosts (the reference tunes NCCL hierarchies for the same reason,
    SURVEY §2.6)."""
    import jax
    from jax.sharding import Mesh

    if cfg is None:
        cfg = MeshConfig()
    if devices is None:
        devices = jax.devices()
    sizes = resolve_mesh_shape(cfg, len(devices))
    shape = tuple(sizes[ax] for ax in MESH_AXES)

    dev_array = None
    if jax.process_count() > 1 and len(devices) == jax.device_count():
        split = split_dcn_ici(sizes, jax.process_count())
        if split is not None:
            from jax.experimental import mesh_utils

            dcn, ici = split
            try:
                # process_is_granule: our dcn factors come from the
                # process count, so each process is one granule (the
                # default groups by slice_index, which only matches when
                # processes == slices)
                dev_array = mesh_utils.create_hybrid_device_mesh(
                    tuple(ici[ax] for ax in MESH_AXES),
                    tuple(dcn[ax] for ax in MESH_AXES),
                    devices=devices,
                    process_is_granule=True,
                )
                logger.info(
                    "hybrid mesh: dcn=" + "×".join(str(dcn[ax]) for ax in MESH_AXES)
                    + " ici=" + "×".join(str(ici[ax]) for ax in MESH_AXES)
                )
            except Exception as e:
                logger.warning(f"hybrid mesh construction failed ({e}); using flat device order")
        else:
            logger.warning(
                f"process count {jax.process_count()} does not factor into mesh {sizes}; "
                "using flat device order (cross-host collectives may ride slow links)"
            )
    if dev_array is None:
        dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, MESH_AXES)
    logger.info(
        "mesh: " + " × ".join(f"{ax}={sizes[ax]}" for ax in MESH_AXES if sizes[ax] > 1 or ax == "data")
    )
    return mesh


@dataclasses.dataclass(frozen=True)
class MeshInfo:
    """Cheap axis-size accessors mirroring the reference's grid API
    (``PipelineParallelGrid.get_*_parallel_world_size``, topology.py:252+)."""

    sizes: Dict[str, int]

    @classmethod
    def from_mesh(cls, mesh) -> "MeshInfo":
        return cls(sizes=dict(zip(mesh.axis_names, mesh.devices.shape)))

    @property
    def dp_world_size(self) -> int:
        # The reference's "data parallel world size" = everything ZeRO
        # partitions over = data × fsdp here.
        return self.sizes.get("data", 1) * self.sizes.get("fsdp", 1)

    @property
    def fsdp_world_size(self) -> int:
        return self.sizes.get("fsdp", 1)

    @property
    def model_parallel_world_size(self) -> int:
        return self.sizes.get("model", 1)

    @property
    def pipe_parallel_world_size(self) -> int:
        return self.sizes.get("pipe", 1)

    @property
    def seq_parallel_world_size(self) -> int:
        return self.sizes.get("seq", 1)

    @property
    def expert_parallel_world_size(self) -> int:
        return self.sizes.get("expert", 1)

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.sizes.values())))


# ---------------------------------------------------------------------------
# Standard sharding specs
# ---------------------------------------------------------------------------

def batch_pspec(ndim: int = 2, seq_dim: Optional[int] = 1, seq_sharded: bool = False):
    """PartitionSpec for a batch input: dim 0 sharded over (data, fsdp)
    — fsdp ranks see distinct micro-slices (the fsdp axis is part of the
    DP group, matching ZeRO's partitioning over the whole DP world) — and
    optionally the sequence dim over ``seq`` for context parallelism."""
    from jax.sharding import PartitionSpec as P

    spec = [None] * ndim
    spec[0] = ("data", "fsdp")
    if seq_sharded and seq_dim is not None and ndim > seq_dim:
        spec[seq_dim] = "seq"
    return P(*spec)


def replicated_pspec():
    from jax.sharding import PartitionSpec as P

    return P()
