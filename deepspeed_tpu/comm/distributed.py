"""Multi-host bootstrap.

Analog of the reference's ``deepspeed/utils/distributed.py``
(``init_distributed`` :12, ``mpi_discovery`` :54): maps environment/MPI
rank discovery onto ``jax.distributed.initialize``.  On a TPU pod the
runtime usually auto-discovers peers; env-var and MPI fallbacks cover
CPU/GPU clusters and manual launches.
"""
from __future__ import annotations

import os
from typing import Optional

from deepspeed_tpu.utils.logging import logger

_initialized = False


def is_initialized() -> bool:
    return _initialized


def init_distributed(
    dist_backend: str = "xla",
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto_mpi_discovery: bool = True,
    verbose: bool = True,
) -> None:
    """Initialize the JAX distributed runtime (idempotent).

    Single-process runs (num_processes==1, or no cluster env present) skip
    initialization entirely — SPMD over local devices needs none.
    """
    global _initialized
    if _initialized:
        return

    if coordinator_address is None:
        coordinator_address = os.environ.get("MASTER_ADDR")
        port = os.environ.get("MASTER_PORT", "29500")
        if coordinator_address is not None:
            coordinator_address = f"{coordinator_address}:{port}"
    if num_processes is None and "WORLD_SIZE" in os.environ:
        num_processes = int(os.environ["WORLD_SIZE"])
    if process_id is None and "RANK" in os.environ:
        process_id = int(os.environ["RANK"])

    if (num_processes is None or process_id is None) and auto_mpi_discovery:
        mpi = mpi_discovery()
        if mpi is not None:
            num_processes = num_processes or mpi["world_size"]
            process_id = process_id if process_id is not None else mpi["rank"]
            coordinator_address = coordinator_address or f"{mpi['master_addr']}:29500"

    import jax

    if num_processes is None or num_processes <= 1:
        # Single process: nothing to do; jax.devices() already works.
        _initialized = True
        if verbose:
            logger.info("init_distributed: single-process run, skipping jax.distributed")
        return

    # Coordinator races are the normal case at pod scale (workers come up
    # before rank 0's server listens); bounded retry with backoff instead
    # of dying on the first connection refusal.  DS_DIST_INIT_RETRIES
    # tunes the attempt budget (the config object doesn't exist yet here).
    #
    # The retry ladder honors a WATCHDOG DEADLINE instead of running
    # unbounded: DS_DIST_INIT_DEADLINE (seconds, default 300 — the
    # supervision sync-deadline default) caps the whole ladder AND each
    # individual initialize() attempt (via jax's initialization_timeout,
    # where supported), so a bad coordinator address surfaces as a loud
    # error naming the coordinator within the deadline instead of
    # silently burning the full backoff ladder.
    from deepspeed_tpu.resilience.policy import RetryError, RetryPolicy, retry_call

    deadline = float(os.environ.get("DS_DIST_INIT_DEADLINE", "300"))
    policy = RetryPolicy(
        max_attempts=int(os.environ.get("DS_DIST_INIT_RETRIES", "3")),
        backoff_seconds=float(os.environ.get("DS_DIST_INIT_BACKOFF", "2.0")),
        timeout_seconds=deadline if deadline > 0 else None,
        retry_on=(OSError, RuntimeError),
    )
    attempts = {"n": 0}

    def _supports_init_timeout() -> bool:
        # signature probe, NOT try/except TypeError around the call: a
        # TypeError raised from INSIDE initialize (bad argument types)
        # must not be misread as "older jax" and retried unbounded
        import inspect

        try:
            return "initialization_timeout" in inspect.signature(
                jax.distributed.initialize
            ).parameters
        except (TypeError, ValueError):
            return False

    def _initialize():
        attempts["n"] += 1
        kwargs = dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        if deadline > 0 and _supports_init_timeout():
            # bound the in-call wait too: a wrong coordinator address
            # otherwise blocks INSIDE initialize for jax's own default
            kwargs["initialization_timeout"] = max(1, int(deadline))
        return jax.distributed.initialize(**kwargs)

    try:
        retry_call(
            policy,
            _initialize,
            # per-process jitter seed: a shared seed would re-synchronize the
            # whole pod's retries into the very storm the jitter breaks
            seed=int(process_id or 0),
            on_retry=lambda attempt, e, pause: logger.warning(
                f"init_distributed attempt {attempt} failed ({e}); retrying in {pause:.1f}s"
            ),
        )
    except RetryError as e:
        raise RetryError(
            f"jax.distributed.initialize could not reach coordinator "
            f"{coordinator_address} (process {process_id}/{num_processes}) after "
            f"{attempts['n']} attempt(s) within the {deadline:g}s deadline "
            f"(tune DS_DIST_INIT_RETRIES / DS_DIST_INIT_DEADLINE): {e}"
        ) from e
    _initialized = True
    if verbose:
        logger.info(
            f"init_distributed: process {process_id}/{num_processes} via {coordinator_address} "
            f"({jax.device_count()} global devices)"
        )


def mpi_discovery() -> Optional[dict]:
    """Map OpenMPI/MVAPICH env vars to rank info (reference
    ``utils/distributed.py:54-96``), without importing mpi4py."""
    env = os.environ
    if "OMPI_COMM_WORLD_RANK" in env:
        return {
            "rank": int(env["OMPI_COMM_WORLD_RANK"]),
            "world_size": int(env["OMPI_COMM_WORLD_SIZE"]),
            "local_rank": int(env.get("OMPI_COMM_WORLD_LOCAL_RANK", 0)),
            "master_addr": env.get("MASTER_ADDR", "127.0.0.1"),
        }
    if "MV2_COMM_WORLD_RANK" in env:
        return {
            "rank": int(env["MV2_COMM_WORLD_RANK"]),
            "world_size": int(env["MV2_COMM_WORLD_SIZE"]),
            "local_rank": int(env.get("MV2_COMM_WORLD_LOCAL_RANK", 0)),
            "master_addr": env.get("MASTER_ADDR", "127.0.0.1"),
        }
    return None
