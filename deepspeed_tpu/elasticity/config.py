"""Elasticity config (reference ``elasticity/config.py``:
``ElasticityConfig`` :30 + error types)."""
from __future__ import annotations

from typing import Dict, List


class ElasticityError(Exception):
    """Base elasticity error (reference elasticity/config.py)."""


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Keys (reference docstring): enabled, max_train_batch_size,
    micro_batch_sizes, min_gpus, max_gpus, min_time, version,
    prefer_larger_batch, ignore_non_elastic_batch_info."""

    def __init__(self, param_dict: Dict):
        self.enabled = bool(param_dict.get("enabled", False))
        if "max_train_batch_size" not in param_dict:
            raise ElasticityConfigError("elasticity requires 'max_train_batch_size'")
        self.max_acceptable_batch_size = int(param_dict["max_train_batch_size"])
        if "micro_batch_sizes" not in param_dict:
            raise ElasticityConfigError("elasticity requires 'micro_batch_sizes'")
        self.micro_batches: List[int] = [int(m) for m in param_dict["micro_batch_sizes"]]
        if not self.micro_batches or any(m <= 0 for m in self.micro_batches):
            raise ElasticityConfigError(f"micro_batch_sizes must be positive, got {self.micro_batches}")
        self.min_gpus = int(param_dict.get("min_gpus", 1))
        self.max_gpus = int(param_dict.get("max_gpus", 10000))
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(f"invalid gpu range [{self.min_gpus}, {self.max_gpus}]")
        self.min_time = int(param_dict.get("min_time", 0))
        self.version = float(param_dict.get("version", 0.1))
        self.prefer_larger_batch_size = bool(param_dict.get("prefer_larger_batch", True))
        self.ignore_non_elastic_batch_info = bool(param_dict.get("ignore_non_elastic_batch_info", False))
