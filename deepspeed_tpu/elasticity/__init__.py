from deepspeed_tpu.elasticity.config import (
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
)
from deepspeed_tpu.elasticity.elasticity import (
    compute_elastic_config,
    elasticity_enabled,
    get_candidate_batch_sizes,
    get_best_candidates,
    get_valid_gpus,
    shrink_world_info,
    world_rank_map,
)
