"""Elastic training schedule math.

Reference: ``elasticity/elasticity.py`` — ahead-of-time batch-size
compatibility (SURVEY §5.3): given ``max_train_batch_size``, a menu of
``micro_batch_sizes`` and an accelerator-count range, pick the global
batch size valid for the *most* world sizes, so a preempted job can
resume at a different scale with identical training math
(``compute_elastic_config`` :226, candidate math :63-174).

The algorithm (re-derived from the documented behavior, not a port):

1. candidate global batch sizes = micro_batch × c for "highly composite"
   multipliers c (many divisors → many valid world sizes), capped at
   ``max_train_batch_size``;
2. a world size g is valid for batch b iff b == mb × gas × g for some
   menu micro-batch mb and integer gas ≥ 1, i.e. b % (mb·g) == 0;
3. score candidates by |valid world sizes| (ties → larger batch when
   ``prefer_larger_batch``);
4. at runtime, given the actual world size, pick the largest menu
   micro-batch compatible with the chosen batch.
"""
from __future__ import annotations

import collections
from typing import Dict, Iterable, List, Tuple

from deepspeed_tpu.elasticity.config import (
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
)

LATEST_ELASTICITY_VERSION = 0.1
MINIMUM_DEEPSPEED_VERSION = "0.3.8"

# divisor-rich multipliers (1..large): highly-composite-style ladder
_HCN = [1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 36, 48, 60, 64, 96, 120, 128,
        144, 180, 192, 240, 256, 360, 384, 480, 512, 720, 768, 960, 1024,
        1260, 1440, 1680, 2048, 2520, 4096, 5040, 7560, 10080]


def get_candidate_batch_sizes(micro_batches: List[int], max_acceptable_batch_size: int) -> List[int]:
    candidates = set()
    for mb in micro_batches:
        for c in _HCN:
            b = mb * c
            if b > max_acceptable_batch_size:
                break
            candidates.add(b)
    return sorted(candidates)


def get_valid_gpus(batch_size: int, micro_batches: List[int], min_valid_gpus: int, max_valid_gpus: int) -> List[int]:
    valid = []
    for g in range(min_valid_gpus, max_valid_gpus + 1):
        if any(batch_size % (mb * g) == 0 for mb in micro_batches):
            valid.append(g)
    return valid


def get_best_candidates(
    candidate_batch_sizes: List[int],
    micro_batches: List[int],
    min_gpus: int,
    max_gpus: int,
    prefer_larger: bool = True,
) -> Tuple[int, List[int]]:
    best_batch, best_gpus = -1, []
    for b in candidate_batch_sizes:
        gpus = get_valid_gpus(b, micro_batches, min_gpus, max_gpus)
        better = len(gpus) > len(best_gpus) or (
            len(gpus) == len(best_gpus) and ((b > best_batch) == prefer_larger) and b != best_batch
        )
        if better:
            best_batch, best_gpus = b, gpus
    return best_batch, best_gpus


def _compatible_micro_batch(final_batch_size: int, micro_batches: List[int], world_size: int) -> Tuple[int, int]:
    """Largest menu micro-batch (and its gas) compatible with the chosen
    global batch at this world size."""
    for mb in sorted(micro_batches, reverse=True):
        if final_batch_size % (mb * world_size) == 0:
            return mb, final_batch_size // (mb * world_size)
    raise ElasticityIncompatibleWorldSize(
        f"world size {world_size} is not valid for batch {final_batch_size} with micro-batch menu {micro_batches}"
    )


def _version_tuple(v: str) -> Tuple[int, ...]:
    out = []
    for part in v.split(".")[:3]:
        digits = "".join(ch for ch in part if ch.isdigit())
        out.append(int(digits) if digits else 0)
    return tuple(out)


def elasticity_enabled(ds_config: Dict) -> bool:
    return bool(ds_config.get("elasticity", {}).get("enabled", False))


def world_rank_map(active: Dict[str, List[int]]) -> List[Tuple[str, int]]:
    """Global rank -> (host, slot) in launcher order (hosts in dict
    order, slots within a host) — the SAME ordering
    ``launcher/launch.py`` assigns ``RANK`` with, so the two never
    drift."""
    out: List[Tuple[str, int]] = []
    for host, slots in active.items():
        for slot in slots:
            out.append((host, slot))
    return out


def shrink_world_info(
    active: Dict[str, List[int]], failed_ranks: Iterable[int]
) -> Dict[str, List[int]]:
    """The surviving active-resources map after dropping the slots of
    ``failed_ranks`` (global ranks, launcher ordering).  Hosts with no
    surviving slots disappear.  This is what the launcher's elastic
    restart (``--restarts``) relaunches with; pair it with
    :func:`compute_elastic_config` at the new world size to re-derive
    the batch schedule."""
    ranks = world_rank_map(active)
    dead = set()
    for r in failed_ranks:
        r = int(r)
        if not (0 <= r < len(ranks)):
            raise ValueError(f"failed rank {r} outside world of {len(ranks)}")
        dead.add(ranks[r])
    out: Dict[str, List[int]] = collections.OrderedDict()
    for host, slots in active.items():
        keep = [s for s in slots if (host, s) not in dead]
        if keep:
            out[host] = keep
    return out


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str, world_size: int = 0):
    """Reference ``compute_elastic_config`` (:226).

    Returns ``(final_batch_size, valid_gpus)`` — plus
    ``micro_batch_size`` when ``world_size`` > 0 (then also validates the
    world size).
    """
    if "elasticity" not in ds_config:
        raise ElasticityError("no 'elasticity' block in the config")
    cfg = ElasticityConfig(ds_config["elasticity"])
    if not cfg.enabled:
        raise ElasticityError("elasticity.enabled is false")
    if cfg.version > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"elasticity version {cfg.version} is newer than supported {LATEST_ELASTICITY_VERSION}"
        )
    if _version_tuple(target_deepspeed_version) < _version_tuple(MINIMUM_DEEPSPEED_VERSION):
        raise ElasticityError(
            f"elasticity requires version >= {MINIMUM_DEEPSPEED_VERSION}, got {target_deepspeed_version}"
        )
    if not cfg.ignore_non_elastic_batch_info:
        for key in ("train_batch_size", "train_micro_batch_size_per_gpu", "gradient_accumulation_steps"):
            if key in ds_config:
                raise ElasticityConfigError(
                    f"elasticity owns the batch schedule; remove '{key}' or set "
                    "elasticity.ignore_non_elastic_batch_info"
                )

    candidates = get_candidate_batch_sizes(cfg.micro_batches, cfg.max_acceptable_batch_size)
    final_batch_size, valid_gpus = get_best_candidates(
        candidates, cfg.micro_batches, cfg.min_gpus, cfg.max_gpus, cfg.prefer_larger_batch_size
    )
    if final_batch_size <= 0:
        raise ElasticityError(
            f"no valid batch size for micro-batches {cfg.micro_batches} under max "
            f"{cfg.max_acceptable_batch_size}"
        )
    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in valid set {valid_gpus} for batch {final_batch_size}"
            )
        mb, _gas = _compatible_micro_batch(final_batch_size, cfg.micro_batches, world_size)
        return final_batch_size, valid_gpus, mb
    return final_batch_size, valid_gpus
