"""Model-framework adapters.

The engine's native contract is a pure callable ``(params, batch, rng)
-> loss`` over a plain param pytree (SURVEY §7: the engine is a compiled
train step, not a module wrapper).  These helpers wrap the common JAX
model libraries into that contract so their users keep their module code
— the analog of the reference accepting any ``nn.Module``.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple


def from_flax(
    module: Any,
    loss_fn: Callable,
    init_batch: Any,
    seed: int = 0,
    mutable: bool = False,
    dropout_rng_name: str = "dropout",
):
    """Wrap a ``flax.linen.Module``.

    ``loss_fn(outputs, batch) -> scalar`` consumes the module's output.
    ``init_batch`` — one example batch used to initialize parameters
    (its array shapes matter, not its values).

    Returns ``(model_fn, params)`` ready for
    ``deepspeed_tpu.initialize(model=model_fn, model_parameters=params,
    loss_fn=None)`` — the loss is already folded in.

    Example::

        model = MyFlaxTransformer(...)
        model_fn, params = from_flax(model, xent, {"input_ids": ids})
        engine, *_ = deepspeed_tpu.initialize(model=model_fn,
                                              model_parameters=params,
                                              config=cfg)
    """
    import jax

    variables = module.init(jax.random.PRNGKey(seed), _module_input(init_batch))
    params = variables["params"]
    if mutable and len(variables) > 1:
        raise ValueError(
            "module has non-param collections (batch_stats?); carry them in the "
            "batch or freeze them — the engine state holds params only"
        )

    def model_fn(p, batch, rng):
        rngs = {dropout_rng_name: rng} if rng is not None else {}
        out = module.apply({"params": p}, _module_input(batch), rngs=rngs)
        return loss_fn(out, batch)

    return model_fn, params


def from_haiku(transformed: Any, loss_fn: Callable, init_batch: Any, seed: int = 0):
    """Wrap a ``haiku.transform``-ed function pair.  Returns
    ``(model_fn, params)`` like :func:`from_flax`."""
    import jax

    params = transformed.init(jax.random.PRNGKey(seed), _module_input(init_batch))

    def model_fn(p, batch, rng):
        out = transformed.apply(p, rng, _module_input(batch))
        return loss_fn(out, batch)

    return model_fn, params


def _module_input(batch: Any) -> Any:
    """Models usually take the input tensor, not the whole batch dict —
    pull the conventional key when present."""
    if isinstance(batch, dict):
        for key in ("input_ids", "inputs", "x", "images"):
            if key in batch:
                return batch[key]
    return batch
