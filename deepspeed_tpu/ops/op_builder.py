"""Native (C++) op JIT builder.

The TPU-native remnant of the reference's ``op_builder/`` ninja JIT
(``builder.py:349-390``): device compute needs no build step (XLA/Pallas
compile at trace time), so the only native code left is **host-side** —
the async disk I/O engine (``csrc/aio``) and the SIMD host optimizer
(``csrc/adam``) used by ZeRO-Offload/Infinity.  Those are compiled here
with g++ at first use into a shared library loaded via ctypes, cached by
source hash (rebuild on source change), mirroring the reference's
compile-at-first-use contract without torch cpp_extension.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import List, Optional

from deepspeed_tpu.utils.logging import logger

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CSRC_DIR = os.path.join(os.path.dirname(_PKG_DIR), "csrc")
BUILD_DIR = os.path.join(CSRC_DIR, "build")

BASE_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp", "-Wall"]
ARCH_FLAGS = ["-march=native", "-funroll-loops"]


def _source_hash(paths: List[str], flags: List[str]) -> str:
    h = hashlib.sha256()
    for p in sorted(paths):
        with open(p, "rb") as f:
            h.update(f.read())
    h.update(" ".join(flags).encode())
    return h.hexdigest()[:16]


def has_compiler() -> bool:
    try:
        subprocess.run(["g++", "--version"], capture_output=True, check=True)
        return True
    except Exception:
        return False


def build_native(name: str, sources: List[str], extra_flags: Optional[List[str]] = None) -> str:
    """Compile ``sources`` (paths relative to csrc/) into
    ``csrc/build/<name>-<hash>.so`` and return the path.  Raises on
    compiler failure — callers fall back to their Python implementation
    (the reference's ``is_compatible`` contract)."""
    srcs = [s if os.path.isabs(s) else os.path.join(CSRC_DIR, s) for s in sources]
    flags = BASE_FLAGS + ARCH_FLAGS + (extra_flags or [])
    tag = _source_hash(srcs, flags)
    out = os.path.join(BUILD_DIR, f"{name}-{tag}.so")
    if os.path.exists(out):
        return out
    os.makedirs(BUILD_DIR, exist_ok=True)
    cmd = ["g++", *flags, *srcs, "-o", out]
    try:
        subprocess.run(cmd, capture_output=True, check=True, text=True)
    except subprocess.CalledProcessError as e:
        # -march=native can fail in emulated/cross environments; retry portable
        logger.warning(f"native build of '{name}' failed with arch flags, retrying portable: {e.stderr[-500:]}")
        flags = BASE_FLAGS + (extra_flags or [])
        tag = _source_hash(srcs, flags)
        out = os.path.join(BUILD_DIR, f"{name}-{tag}.so")
        if not os.path.exists(out):
            cmd = ["g++", *flags, *srcs, "-o", out]
            res = subprocess.run(cmd, capture_output=True, text=True)
            if res.returncode != 0:
                raise RuntimeError(f"native build of '{name}' failed:\n{res.stderr[-2000:]}") from None
    logger.info(f"built native op '{name}' -> {out}")
    return out


def load_native(name: str, sources: List[str], extra_flags: Optional[List[str]] = None) -> ctypes.CDLL:
    return ctypes.CDLL(build_native(name, sources, extra_flags))
