"""Fused LAMB.

TPU-native equivalent of the reference's fused LAMB CUDA kernel
(``csrc/lamb/fused_lamb_cuda_kernel.cu``; wrapper ``ops/lamb/fused_lamb.py:12``).
Per-tensor trust ratios are computed with on-device norm reductions; under
ZeRO sharding each norm is a sharded reduction that XLA lowers to a
psum over the fsdp axis automatically.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.adam.fused_adam import _map_multi
from deepspeed_tpu.ops.registry import register_op


class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any


class FusedLamb:
    name = "lamb"

    def __init__(
        self,
        lr: float = 1e-3,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        bias_correction: bool = True,
        max_coeff: float = 10.0,
        min_coeff: float = 0.01,
    ):
        """``max_coeff``/``min_coeff`` clamp the trust ratio, matching the
        reference's defaults (``ops/lamb/fused_lamb.py:25-45``)."""
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff

    def init(self, params: Any) -> LambState:
        zeros = lambda: jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return LambState(step=jnp.zeros((), jnp.int32), exp_avg=zeros(), exp_avg_sq=zeros())

    def update(self, grads: Any, state: LambState, params: Any, lr: Optional[jnp.ndarray] = None):
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.b1, self.b2
        if self.bias_correction:
            c1 = 1.0 - b1 ** step.astype(jnp.float32)
            c2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            c1 = c2 = jnp.float32(1.0)

        def one(g, m, v, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * g * g
            update_dir = (m_new / c1) / (jnp.sqrt(v_new / c2) + self.eps)
            if self.weight_decay > 0.0:
                update_dir = update_dir + self.weight_decay * p32
            w_norm = jnp.linalg.norm(p32.reshape(-1))
            u_norm = jnp.linalg.norm(update_dir.reshape(-1))
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                jnp.float32(1.0),
            )
            return -lr * trust * update_dir, m_new, v_new

        updates, m, v = _map_multi(one, 3, grads, state.exp_avg, state.exp_avg_sq, params)
        return updates, LambState(step=step, exp_avg=m, exp_avg_sq=v)


@register_op("fused_lamb", "xla", "Fused LAMB; trust ratios via sharded on-device norm reductions")
def _load_fused_lamb():
    return FusedLamb
