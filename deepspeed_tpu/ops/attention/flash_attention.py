"""Flash attention — the TPU-native replacement for the reference's fused
attention CUDA kernels (``csrc/transformer/softmax_kernels.cu`` +
strided-batch GEMMs in ``csrc/transformer/ds_transformer_cuda.cpp``; and
the inference decode path in ``csrc/transformer/inference/csrc/softmax.cu``).

Design:
* **Forward**: Pallas TPU kernel, online-softmax over KV blocks held in
  VMEM, grid over (batch×heads, q-blocks).  Dots run in the input dtype
  (bf16 on the training path — the MXU's native rate; fp32 operands
  decompose into multiple MXU passes and measured ~4× slower) with fp32
  accumulation and fp32 softmax state.
* **Backward**: Pallas FA-2-style kernels (dq, then dk/dv) recomputing P
  from (Q, K, lse) — O(seq) memory; same bf16-dot/fp32-accumulate
  treatment.  ``_blockwise_xla`` remains as the interpretable
  long-sequence fallback used when shapes don't fit the kernel grid.
* On non-TPU backends the same kernel runs under ``interpret=True`` so
  unit tests execute on the CPU mesh.

Layout convention: ``(batch, heads, seq, head_dim)``.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.kernels.compat import tpu_compiler_params
from deepspeed_tpu.ops.registry import register_op
from deepspeed_tpu.utils.logging import logger

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Reference implementation (tests + tiny shapes)
# ---------------------------------------------------------------------------

def mha_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    bias: Optional[jnp.ndarray] = None,
    dropout_mask: Optional[jnp.ndarray] = None,
    keep_prob: float = 1.0,
) -> jnp.ndarray:
    """Plain XLA attention; numerics ground truth for the Pallas kernel.
    ``dropout_mask``: (B, H, Tq, Tk) {0,1}, applied to the softmax output
    (softmax-then-dropout, matching the fused kernels)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_mask is not None:
        p = p * (dropout_mask.astype(jnp.float32) / keep_prob)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


# Crossover measured on v5e (fwd+bwd, d=64, tokens held constant).
# r3 (two-pass bwd): T=128 dense 2.31ms vs kernel 2.82ms; T=256 dense
# 2.97ms vs kernel 2.64ms.  r4 re-measured with the fused single-pass
# backward: T=128 dense 2.13ms vs kernel 3.69ms (1.73x), T=256 ~parity.
# The bound is structural, not a missing optimization: at T=128 the
# grid runs one program per (batch·head) — B=64·H=16 ⇒ 1024 programs of
# a single 128-row block, so the per-program fixed cost (DMA prologue,
# pipeline fill) dominates a compute body that the dense path executes
# as a handful of large fused MXU launches with identical exp counts;
# shrinking blocks can't help (128 is the minimum useful q-block) and
# the O(T²) memory the kernel exists to avoid is only ~64MB here.
# Below ~128x128 scores the materializing bf16 path is simply the
# right program shape (BERT seq128 — the reference's own record shape).
SMALL_SEQ_DENSE_SCORES = 128 * 128


def mha_dense(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    bias: Optional[jnp.ndarray] = None,
    dropout_mask: Optional[jnp.ndarray] = None,
    keep_prob: float = 1.0,
) -> jnp.ndarray:
    """Materializing attention with input-dtype (MXU-rate) dots and fp32
    softmax — the fast path at short sequence, where the Pallas grid's
    per-program overhead exceeds the O(T^2) memory cost it avoids.  Same
    numerics class as the kernel (bf16 dots, fp32 accumulate/softmax);
    fp32 inputs stay fp32 end-to-end."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        qp = jnp.arange(qlen)[:, None] + (klen - qlen)
        s = jnp.where(qp >= jnp.arange(klen)[None, :], s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_mask is not None:
        p = p * (dropout_mask.astype(jnp.float32) / keep_prob)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v, preferred_element_type=jnp.float32).astype(q.dtype)


# ---------------------------------------------------------------------------
# In-kernel counter-based dropout PRNG
#
# Threefry-2x32 (20 rounds, the Random123 / jax.random schedule) written
# in pure uint32 add/xor/rotate — it lowers identically through Mosaic
# and the Pallas interpreter (pltpu.prng_random_bits is a TPU-only
# primitive and stubs to zeros in interpret mode), and the same pure
# function run host-side reproduces the exact keep-mask for the oracle
# and for the non-kernel fallback paths.  The counter is the score
# element's absolute (row·Tk + col, batch·head) position, so any block
# decomposition (fwd q-blocks, dkv kv-blocks) regenerates identical
# bits — the FA-2 backward never needs a stored mask.  Cost: ~80 VPU
# ops per score on the dropout path only — the same threefry work
# jax.random.bernoulli would do in XLA, minus the O(Tq·Tk) HBM
# round-trip the materialized mask paid.
# ---------------------------------------------------------------------------


def _rotl32(x, r):
    return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))


def _threefry2x32_bits(k0, k1, x0, x1):
    """First output word of 20-round Threefry-2x32 on counter (x0, x1)
    under key (k0, k1).  All inputs uint32 arrays/scalars."""
    ks0, ks1 = k0, k1
    ks2 = jnp.uint32(0x1BD11BDA) ^ k0 ^ k1
    x0 = x0 + ks0
    x1 = x1 + ks1
    rot_a = (13, 15, 26, 6)
    rot_b = (17, 29, 16, 24)

    def rounds4(x0, x1, rots):
        for r in rots:
            x0 = x0 + x1
            x1 = _rotl32(x1, r)
            x1 = x1 ^ x0
        return x0, x1

    for i, (rots, ka, kb) in enumerate((
        (rot_a, ks1, ks2), (rot_b, ks2, ks0), (rot_a, ks0, ks1),
        (rot_b, ks1, ks2), (rot_a, ks2, ks0),
    )):
        x0, x1 = rounds4(x0, x1, rots)
        x0 = x0 + ka
        x1 = x1 + kb + jnp.uint32(i + 1)
    return x0


def _drop_threshold(keep_prob: float) -> int:
    """keep iff bits < threshold (uint32 compare) ⇒ P(keep) = keep_prob."""
    return min(int(keep_prob * 4294967296.0), 4294967295)


def _check_dropout_counter_bound(sq: int, sk: int) -> None:
    """The position-keyed Threefry counter packs ``row*sk + col`` into
    one uint32 word; beyond 2**32 score positions the stream would
    repeat.  64k × 64k scores is far outside any supported score-matrix
    size (long-context runs route through sparse/ring attention), so
    refuse loudly rather than degrade silently."""
    if sq * sk >= 2**32:
        raise ValueError(
            f"attention dropout PRNG counter would wrap: sq*sk = {sq}*{sk} "
            ">= 2**32; use sparse or ring attention for scores this large"
        )


def _drop_keep_tile(k0, k1, bh, row0, col0, bq, bk, sk, keep_prob):
    """(bq, bk) bool keep-tile for score rows [row0, row0+bq) × cols
    [col0, col0+bk) of batch·head ``bh`` — pure function of the absolute
    element position, identical across fwd/dq/dkv block decompositions.

    Counter bound: the x0 word is ``row*sk + col`` in uint32, so score
    grids with sq*sk >= 2**32 (64k × 64k) would silently repeat
    keep-bits across distant positions — entry points assert the bound
    (``_check_dropout_counter_bound``) before handing a seed down."""
    rows = jnp.uint32(row0) + jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 0)
    cols = jnp.uint32(col0) + jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 1)
    x0 = rows * jnp.uint32(sk) + cols
    x1 = jnp.full((bq, bk), 1, jnp.uint32) * jnp.uint32(bh)
    bits = _threefry2x32_bits(jnp.uint32(k0), jnp.uint32(k1), x0, x1)
    return bits < jnp.uint32(_drop_threshold(keep_prob))


def dropout_keep_mask_host(seed_pair, b, h, sq, sk, keep_prob):
    """The full (b·h, sq, sk) uint8 keep-mask the kernels generate —
    host-graph-side twin of ``_drop_keep_tile`` for the oracle and the
    materializing fallback paths (dense short-seq / reference)."""
    _check_dropout_counter_bound(sq, sk)
    k0 = seed_pair[0].astype(jnp.uint32)
    k1 = seed_pair[1].astype(jnp.uint32)
    rows = jax.lax.broadcasted_iota(jnp.uint32, (sq, sk), 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, (sq, sk), 1)
    x0 = rows * jnp.uint32(sk) + cols
    bhs = jnp.arange(b * h, dtype=jnp.uint32)
    bits = jax.vmap(lambda bh: _threefry2x32_bits(k0, k1, x0, jnp.full((sq, sk), 1, jnp.uint32) * bh))(bhs)
    return (bits < jnp.uint32(_drop_threshold(keep_prob))).astype(jnp.uint8)


def _seed_pair(rng) -> jnp.ndarray:
    """(2,) uint32 key words from either a new-style typed PRNG key or a
    raw uint32[2] key."""
    try:
        if jnp.issubdtype(rng.dtype, jax.dtypes.prng_key):
            rng = jax.random.key_data(rng)
    except (TypeError, AttributeError):
        pass
    return jnp.asarray(rng).reshape(-1)[:2].astype(jnp.uint32)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, *rest, sm_scale: float, causal: bool, block_k: int,
    kbias: bool, fbias: bool, keep_prob: float, kdrop: bool = False,
):
    # optional trailing inputs: [bias], [drop-mask | prng-seed]; outputs:
    # o, [lse].  ``kdrop``: the dropout input is a (2,) uint32 SMEM seed
    # and the keep-mask is generated in-kernel (no O(Tq·Tk) HBM buffer).
    refs = list(rest)
    bias_ref = refs.pop(0) if (kbias or fbias) else None
    mask_ref = refs.pop(0) if keep_prob < 1.0 else None
    o_ref = refs.pop(0)
    maybe_lse_ref = refs

    block_q, d = q_ref.shape[1], q_ref.shape[2]
    seq_k = k_ref.shape[1]
    seq_q_total = pl.num_programs(1) * block_q
    q_idx = pl.program_id(1)
    bh_idx = pl.program_id(0)
    # End-aligned causal offset (queries are the LAST seq_q positions of
    # the kv sequence — decode convention, matches mha_reference's
    # tril(k=klen-qlen)).
    causal_offset = seq_k - seq_q_total

    # Keep q/k/v in the input dtype for the dots: the MXU multiplies
    # bf16×bf16 natively at full rate (fp32 operands decompose into
    # multiple passes — measured ~4× slower end-to-end); accumulation is
    # fp32 via preferred_element_type, and the softmax math stays fp32.
    q = q_ref[0]  # (block_q, d)

    num_kv = seq_k // block_k
    if causal:
        # Last KV block whose start can be <= this q block's end position.
        q_end = causal_offset + (q_idx + 1) * block_q
        hi = jax.lax.div(q_end + block_k - 1, block_k)
        hi = jnp.clip(hi, 0, num_kv)
    else:
        hi = num_kv

    def body(i, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, pl.dslice(i * block_k, block_k), :]
        v = v_ref[0, pl.dslice(i * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale  # (block_q, block_k) fp32
        if kbias:
            s = s + bias_ref[0, 0, pl.dslice(i * block_k, block_k)].astype(jnp.float32)[None, :]
        elif fbias:
            s = s + bias_ref[0, :, pl.dslice(i * block_k, block_k)].astype(jnp.float32)
        if causal:
            q_pos = causal_offset + q_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        m_cur = jnp.max(s, axis=1, keepdims=True)  # (block_q, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        # softmax statistics use the FULL p; dropout zeroes entries only
        # on the value path (reference softmax-then-dropout semantics,
        # csrc/transformer/dropout_kernels.cu)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        if keep_prob < 1.0:
            if kdrop:
                keep = _drop_keep_tile(
                    mask_ref[0], mask_ref[1], bh_idx,
                    q_idx * block_q, i * block_k, block_q, block_k, seq_k, keep_prob,
                )
            else:
                keep = mask_ref[0, :, pl.dslice(i * block_k, block_k)]
            p = p * (keep.astype(jnp.float32) / keep_prob)
        acc = acc * alpha + jnp.dot(p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    init = (
        jnp.zeros((block_q, d), jnp.float32),
        jnp.full((block_q, 1), -jnp.inf, jnp.float32),
        jnp.zeros((block_q, 1), jnp.float32),
    )
    acc, m, l = jax.lax.fori_loop(0, hi, body, init)
    lse = jnp.where(l[:, 0] == 0.0, jnp.inf, m[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-37)))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    if maybe_lse_ref:
        # per-row logsumexp of the SCALED scores (bwd input); stored with
        # an 8-sublane broadcast dim for TPU block-layout constraints.
        # Omitted on the inference-only path (no grad → no buffer).
        maybe_lse_ref[0][0] = jnp.broadcast_to(lse[None, :], (8, lse.shape[0]))


def _bias_mode(bias, b, h, sq, sk):
    """Classify/normalize an additive bias: (B,1,1,Tk) key-broadcast →
    ("kbias", (B, Tk)); anything broadcastable to (B,H,Tq,Tk) →
    ("fbias", (B*H, Tq, Tk))."""
    if bias is None:
        return None, None
    if bias.ndim != 4:
        raise ValueError(f"bias must be 4-D broadcastable to (B,H,Tq,Tk), got {bias.shape}")
    if bias.shape[1] == 1 and bias.shape[2] == 1 and bias.shape[3] == sk:
        # (B, 1, Tk): the middle singleton keeps the block's trailing two
        # dims equal to the array dims, which Mosaic requires when the
        # row count (B) isn't a multiple of 8
        return "kbias", bias.reshape(bias.shape[0], 1, sk)
    full = jnp.broadcast_to(bias, (b, h, sq, sk)).reshape(b * h, sq, sk)
    return "fbias", full


def _fwd_extra_specs(mode, bias2, mask, b, h, sq, sk, block_q, drop_seed=None):
    """in_specs + arrays for the optional bias/mask/seed inputs of the
    fwd/dq kernels (block over the q dim; the kv dim is sliced
    in-kernel).  ``drop_seed``: (2,) uint32 for in-kernel dropout —
    rides SMEM, mutually exclusive with ``mask``."""
    if drop_seed is not None:
        _check_dropout_counter_bound(sq, sk)
    from jax.experimental.pallas import tpu as pltpu

    specs, args = [], []
    if mode == "kbias":
        specs.append(pl.BlockSpec((1, 1, sk), lambda bh_, qi, h=h: (bh_ // h, 0, 0)))
        args.append(bias2)
    elif mode == "fbias":
        specs.append(pl.BlockSpec((1, block_q, sk), lambda bh_, qi: (bh_, qi, 0)))
        args.append(bias2)
    if mask is not None:
        specs.append(pl.BlockSpec((1, block_q, sk), lambda bh_, qi: (bh_, qi, 0)))
        args.append(mask)
    elif drop_seed is not None:
        specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(drop_seed)
    return specs, args


def _flash_fwd_pallas(
    q, k, v, causal: bool, sm_scale: float, block_q: int, block_k: int, interpret: bool,
    want_lse: bool = True, bias=None, mask=None, keep_prob: float = 1.0, drop_seed=None,
):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    qr = q.reshape(bh, sq, d)
    kr = k.reshape(bh, sk, d)
    vr = v.reshape(bh, sk, d)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, (sq, sk, block_q, block_k)
    mode, bias2 = _bias_mode(bias, b, h, sq, sk)

    grid = (bh, sq // block_q)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh_, qi: (bh_, qi, 0)),
        pl.BlockSpec((1, sk, d), lambda bh_, qi: (bh_, 0, 0)),
        pl.BlockSpec((1, sk, d), lambda bh_, qi: (bh_, 0, 0)),
    ]
    extra_specs, extra_args = _fwd_extra_specs(mode, bias2, mask, b, h, sq, sk, block_q, drop_seed)
    in_specs += extra_specs
    o_spec = pl.BlockSpec((1, block_q, d), lambda bh_, qi: (bh_, qi, 0))
    o_shape = jax.ShapeDtypeStruct((bh, sq, d), q.dtype)
    kern = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal, block_k=block_k,
        kbias=(mode == "kbias"), fbias=(mode == "fbias"), keep_prob=keep_prob,
        kdrop=(drop_seed is not None),
    )
    if not want_lse:
        # inference/eval path: skip the logsumexp output entirely
        out = pl.pallas_call(
            kern, grid=grid, in_specs=in_specs, out_specs=o_spec, out_shape=o_shape, interpret=interpret
        )(qr, kr, vr, *extra_args)
        return out.reshape(b, h, sq, d), None
    out, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[o_spec, pl.BlockSpec((1, 8, block_q), lambda bh_, qi: (bh_, 0, qi))],
        out_shape=[o_shape, jax.ShapeDtypeStruct((bh, 8, sq), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, *extra_args)
    return out.reshape(b, h, sq, d), lse[:, 0, :].reshape(b, h, sq)


# ---------------------------------------------------------------------------
# Blockwise XLA path (backward + long-sequence fallback): flash-style
# online softmax as a lax.scan over KV blocks, rematerialized.
# ---------------------------------------------------------------------------

def _blockwise_xla(q, k, v, causal: bool, sm_scale: float, block_k: int):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_k = min(block_k, sk)
    # Ragged sk: pad K/V up to a block multiple and mask the padded keys
    # (the l==0 guard below already handles fully-masked rows).
    pad = (-sk) % block_k
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    num_kv = (sk + pad) // block_k
    qf = q.astype(jnp.float32) * sm_scale
    kf = k.astype(jnp.float32).reshape(b, h, num_kv, block_k, d)
    vf = v.astype(jnp.float32).reshape(b, h, num_kv, block_k, d)

    @functools.partial(jax.checkpoint, prevent_cse=False)
    def block(carry, inputs):
        acc, m_prev, l_prev = carry
        kb, vb, kv_i = inputs
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb)
        # end-aligned causal positions (match mha_reference's
        # tril(k=klen-qlen)); generated in-body — a precomputed (sq, 1)
        # index constant was observed to land in SMEM and overflow it at
        # 16k sequences on TPU
        q_pos = (sk - sq) + jax.lax.broadcasted_iota(jnp.int32, (sq, 1), 0)
        k_pos = kv_i * block_k + jnp.arange(block_k)[None, :]
        if causal:
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        if pad:
            s = jnp.where(k_pos < sk, s, DEFAULT_MASK_VALUE)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
        return (acc, m_new, l_new), None

    init = (
        jnp.zeros((b, h, sq, d), jnp.float32),
        jnp.full((b, h, sq, 1), -jnp.inf, jnp.float32),
        jnp.zeros((b, h, sq, 1), jnp.float32),
    )
    kb = jnp.moveaxis(kf, 2, 0)  # (num_kv, b, h, block_k, d)
    vb = jnp.moveaxis(vf, 2, 0)
    (acc, m, l), _ = jax.lax.scan(block, init, (kb, vb, jnp.arange(num_kv)))
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas backward kernels (FlashAttention-2 style)
#
# With S = QKᵀ·sc, P = exp(S − lse), Δ = rowsum(dO ∘ O):
#   dV = Pᵀ dO
#   dS = P ∘ (dO Vᵀ − Δ)
#   dQ = dS K · sc          dK = dSᵀ Q · sc
# Both kernels recompute P from (Q, K, lse) — O(seq) memory like the
# forward; the fwd saves only O and the per-row logsumexp.
# ---------------------------------------------------------------------------


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    sm_scale, causal, block_k, kbias, fbias, keep_prob, kdrop=False,
):
    refs = list(rest)
    bias_ref = refs.pop(0) if (kbias or fbias) else None
    mask_ref = refs.pop(0) if keep_prob < 1.0 else None
    dq_ref = refs.pop(0)

    block_q, d = q_ref.shape[1], q_ref.shape[2]
    seq_k = k_ref.shape[1]
    seq_q_total = pl.num_programs(1) * block_q
    q_idx = pl.program_id(1)
    bh_idx = pl.program_id(0)
    causal_offset = seq_k - seq_q_total

    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, 0, :][:, None]
    delta = delta_ref[0, 0, :][:, None]

    num_kv = seq_k // block_k
    if causal:
        q_end = causal_offset + (q_idx + 1) * block_q
        hi = jnp.clip(jax.lax.div(q_end + block_k - 1, block_k), 0, num_kv)
    else:
        hi = num_kv

    def body(i, dq):
        k = k_ref[0, pl.dslice(i * block_k, block_k), :]
        v = v_ref[0, pl.dslice(i * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if kbias:
            s = s + bias_ref[0, 0, pl.dslice(i * block_k, block_k)].astype(jnp.float32)[None, :]
        elif fbias:
            s = s + bias_ref[0, :, pl.dslice(i * block_k, block_k)].astype(jnp.float32)
        if causal:
            q_pos = causal_offset + q_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        if keep_prob < 1.0:
            if kdrop:
                keep = _drop_keep_tile(
                    mask_ref[0], mask_ref[1], bh_idx,
                    q_idx * block_q, i * block_k, block_q, block_k, seq_k, keep_prob,
                )
            else:
                keep = mask_ref[0, :, pl.dslice(i * block_k, block_k)]
            dp = dp * (keep.astype(jnp.float32) / keep_prob)
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, hi, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    sm_scale, causal, block_q, kbias, fbias, keep_prob, kdrop=False,
):
    refs = list(rest)
    bias_ref = refs.pop(0) if (kbias or fbias) else None
    mask_ref = refs.pop(0) if keep_prob < 1.0 else None
    dk_ref, dv_ref = refs

    block_k, d = k_ref.shape[1], k_ref.shape[2]
    seq_q = q_ref.shape[1]
    seq_k_total = pl.num_programs(1) * block_k
    kv_idx = pl.program_id(1)
    bh_idx = pl.program_id(0)
    causal_offset = seq_k_total - seq_q

    k = k_ref[0]
    v = v_ref[0]

    num_q = seq_q // block_q
    if causal:
        # first q block whose end position reaches this kv block's start
        k_start = kv_idx * block_k
        lo = jnp.clip(jax.lax.div(k_start - causal_offset, block_q), 0, num_q)
    else:
        lo = 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.dslice(i * block_q, block_q), :]
        do = do_ref[0, pl.dslice(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.dslice(i * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.dslice(i * block_q, block_q)][:, None]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if kbias:
            s = s + bias_ref[0, 0].astype(jnp.float32)[None, :]
        elif fbias:
            s = s + bias_ref[0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        if causal:
            q_pos = causal_offset + i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)
        if keep_prob < 1.0:
            if kdrop:
                keep = _drop_keep_tile(
                    mask_ref[0], mask_ref[1], bh_idx,
                    i * block_q, kv_idx * block_k, block_q, block_k, seq_k_total, keep_prob,
                )
            else:
                keep = mask_ref[0, pl.dslice(i * block_q, block_q), :]
            scaled_keep = keep.astype(jnp.float32) / keep_prob
            d_mat = p * scaled_keep  # post-dropout probabilities
        else:
            d_mat = p
        dv = dv + jnp.dot(d_mat.astype(do.dtype).T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        if keep_prob < 1.0:
            dp = dp * scaled_keep
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    init = (jnp.zeros((block_k, d), jnp.float32), jnp.zeros((block_k, d), jnp.float32))
    dk, dv = jax.lax.fori_loop(lo, num_q, body, init)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_fused_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    sm_scale, causal, block_q, kbias, fbias, keep_prob, kdrop=False,
    q_row0=0, sq_full=None, sk_full=None,
):
    """Single-pass backward: one kernel computes dq, dk, dv together.

    Grid is (bh, kv-blocks) with the kv axis SEQUENTIAL ("arbitrary"
    semantics): every program loops the q blocks for its kv block,
    computing P = exp(S − lse) ONCE per score and feeding all three
    cotangents — the two-pass design (dq pass + dkv pass) pays that
    exp twice, and at d=64 the kernel is VPU-softmax-bound
    (ROUND3_NOTES "Known limits"), so the second exp is pure waste.
    dq accumulates across kv blocks by revisiting its (full-seq) output
    block, which stays resident in VMEM between sequential grid steps —
    this bounds one CALL to seqs where sq·d fp32 fits VMEM (~8k at
    d=64); longer sequences run as q-CHUNKED calls of this same kernel
    (``_flash_bwd_fused_chunked``) with ``q_row0``/``sq_full``/
    ``sk_full`` carrying the chunk's global position so causal masking
    and the position-keyed dropout counter are chunking-invariant."""
    refs = list(rest)
    bias_ref = refs.pop(0) if (kbias or fbias) else None
    mask_ref = refs.pop(0) if keep_prob < 1.0 else None
    dq_ref, dk_ref, dv_ref = refs

    block_k, d = k_ref.shape[1], k_ref.shape[2]
    seq_q = q_ref.shape[1]
    seq_k_total = pl.num_programs(1) * block_k
    skf = seq_k_total if sk_full is None else sk_full
    sqf = seq_q if sq_full is None else sq_full
    kv_idx = pl.program_id(1)
    bh_idx = pl.program_id(0)
    # global q position of local row r is q_row0 + r; causal compares
    # (skf - sqf) + global_q >= global_k
    causal_offset = skf - sqf + q_row0

    @pl.when(kv_idx == 0)
    def _zero_dq():
        dq_ref[0] = jnp.zeros((seq_q, d), dq_ref.dtype)

    k = k_ref[0]
    v = v_ref[0]

    num_q = seq_q // block_q
    if causal:
        k_start = kv_idx * block_k
        lo = jnp.clip(jax.lax.div(k_start - causal_offset, block_q), 0, num_q)
    else:
        lo = 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.dslice(i * block_q, block_q), :]
        do = do_ref[0, pl.dslice(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.dslice(i * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.dslice(i * block_q, block_q)][:, None]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if kbias:
            s = s + bias_ref[0, 0].astype(jnp.float32)[None, :]
        elif fbias:
            s = s + bias_ref[0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        if causal:
            q_pos = causal_offset + i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        if keep_prob < 1.0:
            if kdrop:
                keep = _drop_keep_tile(
                    mask_ref[0], mask_ref[1], bh_idx,
                    q_row0 + i * block_q, kv_idx * block_k, block_q, block_k, skf, keep_prob,
                )
            else:
                keep = mask_ref[0, pl.dslice(i * block_q, block_q), :]
            scaled_keep = keep.astype(jnp.float32) / keep_prob
            d_mat = p * scaled_keep
            dp = dp * scaled_keep
        else:
            d_mat = p
        dv = dv + jnp.dot(d_mat.astype(do.dtype).T, do, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        # dq accumulation: read-modify-write the resident dq block
        cur = dq_ref[0, pl.dslice(i * block_q, block_q), :]
        dq_ref[0, pl.dslice(i * block_q, block_q), :] = (
            cur + jnp.dot(ds, k, preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        )
        return dk, dv

    init = (jnp.zeros((block_k, d), jnp.float32), jnp.zeros((block_k, d), jnp.float32))
    dk, dv = jax.lax.fori_loop(lo, num_q, body, init)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_fused_pallas(
    q, k, v, out, lse, g, causal, sm_scale, block_q, block_k, interpret,
    bias=None, mask=None, keep_prob: float = 1.0, drop_seed=None,
    q_row0: int = 0, sq_full=None, sk_full=None,
):
    """Single-kernel backward (see ``_flash_bwd_fused_kernel``).  dq is
    accumulated in fp32 and cast at the end."""
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, sk, bh, block_q, block_k, qr, kr, vr, dor, lser, delta, mode, bias2, flags = (
        _bwd_prologue(q, k, v, out, lse, g, bias, block_q, block_k, keep_prob, drop_seed)
    )
    d = q.shape[3]
    extra_specs, extra_args = _kv_grid_extra_specs(mode, bias2, mask, h, sq, block_k, drop_seed)

    dq32, dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_fused_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
            q_row0=q_row0, sq_full=sq_full, sk_full=sk_full, **flags,
        ),
        grid=(bh, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda bh_, ki: (bh_, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, sq, d), lambda bh_, ki: (bh_, 0, 0)),
            pl.BlockSpec((1, 8, sq), lambda bh_, ki: (bh_, 0, 0)),
            pl.BlockSpec((1, 8, sq), lambda bh_, ki: (bh_, 0, 0)),
        ] + extra_specs,
        out_specs=[
            # dq: full-seq block revisited every kv step (accumulator)
            pl.BlockSpec((1, sq, d), lambda bh_, ki: (bh_, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ki: (bh_, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta, *extra_args)

    return (
        dq32.astype(q.dtype).reshape(q.shape),
        dk.reshape(k.shape),
        dv.reshape(v.shape),
    )


# VMEM bound for the fused backward's resident per-program state:
# q + do + dq(fp32) + k/v blocks, double-buffered — beyond this ONE
# call's worth; longer sequences run q-chunked calls of the same kernel
# (VERDICT r4 weak #3: 16k+ used to fall back to the two-pass kernels).
_FUSED_BWD_MAX_SQ_BYTES = 1 << 21  # sq * d * 4 (fp32 dq) per program


def _flash_bwd_fused_chunked(
    q, k, v, out, lse, g, causal, sm_scale, block_q, block_k, interpret,
    bias=None, mask=None, keep_prob: float = 1.0, drop_seed=None,
):
    """Fused single-pass backward for sequences whose fp32 dq exceeds a
    program's VMEM share: split the q axis into chunks that fit, run the
    fused kernel once per chunk (``q_row0``/``sq_full``/``sk_full`` keep
    causal masking and the dropout counter position-exact), sum the
    partial dk/dv in fp32.  Causal chunks slice their kv prefix — a
    chunk never visits kv blocks entirely above its diagonal — so total
    score work matches the monolithic kernel.  Explicit bias/mask
    tensors are not chunked (long-context runs are causal + in-kernel
    dropout); the dispatch sends those to the two-pass kernels."""
    assert bias is None and mask is None, "chunked fused bwd: bias/mask unsupported"
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    rows_max = _FUSED_BWD_MAX_SQ_BYTES // (d * 4)
    cs = max(bq, rows_max // bq * bq)
    dq_parts = []
    dk32 = jnp.zeros((b, h, sk, d), jnp.float32)
    dv32 = jnp.zeros((b, h, sk, d), jnp.float32)
    for c0 in range(0, sq, cs):
        ce = min(c0 + cs, sq)
        qs = slice(c0, ce)
        kv_hi = sk
        if causal:
            # highest k position this chunk can see: (sk - sq) + ce - 1
            kv_hi = min(sk, max(block_k, -((sk - sq + ce) // -block_k) * block_k))
        dq_c, dk_c, dv_c = _flash_bwd_fused_pallas(
            q[:, :, qs], k[:, :, :kv_hi], v[:, :, :kv_hi], out[:, :, qs],
            lse[:, :, qs], g[:, :, qs], causal, sm_scale, block_q, block_k,
            interpret, keep_prob=keep_prob, drop_seed=drop_seed,
            q_row0=c0, sq_full=sq, sk_full=sk,
        )
        dq_parts.append(dq_c)
        pad = sk - kv_hi
        dk_add = dk_c.astype(jnp.float32)
        dv_add = dv_c.astype(jnp.float32)
        if pad:
            dk32 = dk32.at[:, :, :kv_hi].add(dk_add)
            dv32 = dv32.at[:, :, :kv_hi].add(dv_add)
        else:
            dk32 = dk32 + dk_add
            dv32 = dv32 + dv_add
    return (
        jnp.concatenate(dq_parts, axis=2),
        dk32.astype(k.dtype),
        dv32.astype(v.dtype),
    )


def _bwd_prologue(q, k, v, out, lse, g, bias, block_q, block_k, keep_prob, drop_seed):
    """Shared backward-pass setup: (bh, seq, d) reshapes, 8-sublane
    lse/delta broadcasts (TPU block constraint: last two dims must be
    8/128-aligned or full), bias classification, kernel flags."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    qr, kr, vr = (t.reshape(bh, t.shape[2], d) for t in (q, k, v))
    dor = g.reshape(bh, sq, d)
    lser = jnp.broadcast_to(lse.reshape(bh, 1, sq), (bh, 8, sq))
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta.reshape(bh, 1, sq), (bh, 8, sq))
    mode, bias2 = _bias_mode(bias, b, h, sq, sk)
    flags = dict(
        kbias=(mode == "kbias"), fbias=(mode == "fbias"), keep_prob=keep_prob,
        kdrop=(drop_seed is not None),
    )
    return b, h, sq, sk, bh, block_q, block_k, qr, kr, vr, dor, lser, delta, mode, bias2, flags


def _kv_grid_extra_specs(mode, bias2, mask, h, sq, block_k, drop_seed):
    """in_specs + arrays for the optional bias/mask/seed inputs of the
    kv-gridded backward kernels (dkv pass + fused single-pass)."""
    from jax.experimental.pallas import tpu as pltpu

    specs, args = [], []
    if mode == "kbias":
        specs.append(pl.BlockSpec((1, 1, block_k), lambda bh_, ki, h=h: (bh_ // h, 0, ki)))
        args.append(bias2)
    elif mode == "fbias":
        specs.append(pl.BlockSpec((1, sq, block_k), lambda bh_, ki: (bh_, 0, ki)))
        args.append(bias2)
    if mask is not None:
        specs.append(pl.BlockSpec((1, sq, block_k), lambda bh_, ki: (bh_, 0, ki)))
        args.append(mask)
    elif drop_seed is not None:
        specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(drop_seed)
    return specs, args


def _flash_bwd_pallas(
    q, k, v, out, lse, g, causal, sm_scale, block_q, block_k, interpret,
    bias=None, mask=None, keep_prob: float = 1.0, drop_seed=None,
):
    b, h, sq, sk, bh, block_q, block_k, qr, kr, vr, dor, lser, delta, mode, bias2, flags = (
        _bwd_prologue(q, k, v, out, lse, g, bias, block_q, block_k, keep_prob, drop_seed)
    )
    d = q.shape[3]

    dq_extra_specs, dq_extra_args = _fwd_extra_specs(mode, bias2, mask, b, h, sq, sk, block_q, drop_seed)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, sm_scale=sm_scale, causal=causal, block_k=block_k, **flags),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, qi: (bh_, qi, 0)),
            pl.BlockSpec((1, sk, d), lambda bh_, qi: (bh_, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh_, qi: (bh_, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh_, qi: (bh_, qi, 0)),
            pl.BlockSpec((1, 8, block_q), lambda bh_, qi: (bh_, 0, qi)),
            pl.BlockSpec((1, 8, block_q), lambda bh_, qi: (bh_, 0, qi)),
        ] + dq_extra_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh_, qi: (bh_, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta, *dq_extra_args)

    # kv-blocked layouts for the dk/dv pass
    kv_extra_specs, kv_extra_args = _kv_grid_extra_specs(mode, bias2, mask, h, sq, block_k, drop_seed)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q, **flags),
        grid=(bh, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda bh_, ki: (bh_, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, sq, d), lambda bh_, ki: (bh_, 0, 0)),
            pl.BlockSpec((1, 8, sq), lambda bh_, ki: (bh_, 0, 0)),
            pl.BlockSpec((1, 8, sq), lambda bh_, ki: (bh_, 0, 0)),
        ] + kv_extra_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh_, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ki: (bh_, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta, *kv_extra_args)

    return dq.reshape(q.shape), dk.reshape(k.shape), dv.reshape(v.shape)


# ---------------------------------------------------------------------------
# Public API with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11, 12, 13))
def _flash_attention(q, k, v, bias, mask, drop_seed, causal, sm_scale, block_q, block_k, interpret, keep_prob, bwd_block_q=None, bwd_block_k=None):
    # non-differentiated primal (inference/eval): no lse buffer
    return _flash_fwd_pallas(
        q, k, v, causal, sm_scale, block_q, block_k, interpret,
        want_lse=False, bias=bias, mask=mask, keep_prob=keep_prob, drop_seed=drop_seed,
    )[0]


def _flash_fwd_rule(q, k, v, bias, mask, drop_seed, causal, sm_scale, block_q, block_k, interpret, keep_prob, bwd_block_q=None, bwd_block_k=None):
    out, lse = _flash_fwd_pallas(
        q, k, v, causal, sm_scale, block_q, block_k, interpret,
        bias=bias, mask=mask, keep_prob=keep_prob, drop_seed=drop_seed,
    )
    # Names for selective activation checkpointing: a remat policy that
    # saves "attn_o"/"attn_lse" keeps the kernel's residuals, so the
    # backward pass does NOT re-run the forward kernel to rebuild the
    # logsumexp (the policy-driven analog of the reference's fused
    # kernels persisting their softmax stats between fwd and bwd,
    # csrc/transformer/softmax_kernels.cu)
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "attn_o")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse, bias, mask, drop_seed)


def _bias_cotangent(q, k, v, out, lse, g, bias, mask, causal, sm_scale, keep_prob, drop_seed=None):
    """Exact dL/dbias = dS (pre-scale scores' cotangent) reduced over the
    bias' broadcast dims.  Deliberately a SEPARATE computation from the
    Pallas backward: when the caller's bias is a constant (padding mask —
    the common case) the returned cotangent is unused and XLA's DCE
    removes this entire block; a trainable bias (learned relative
    position / ALiBi) pays O(Tq·Tk) here, the same order as the bias
    tensor it owns."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * sm_scale
    s = s + jnp.broadcast_to(bias, (b, h, sq, sk)).astype(jnp.float32)
    if causal:
        qp = jnp.arange(sq)[:, None] + (sk - sq)
        s = jnp.where(qp >= jnp.arange(sk)[None, :], s, DEFAULT_MASK_VALUE)
    p = jnp.exp(s - lse[..., None])
    dp = jnp.einsum("bhqd,bhkd->bhqk", g.astype(jnp.float32), v.astype(jnp.float32))
    if mask is None and drop_seed is not None:
        # regenerate the kernels' keep-mask (host twin of the in-kernel
        # counter PRNG); only reached for a TRAINABLE bias under dropout
        mask = dropout_keep_mask_host(drop_seed, b, h, sq, sk, keep_prob)
    if mask is not None:
        dp = dp * (mask.reshape(b, h, sq, sk).astype(jnp.float32) / keep_prob)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    ds = p * (dp - delta[..., None])  # no sm_scale: bias enters post-scale
    # reduce over the dims the bias broadcast along
    reduce_axes = tuple(i for i in range(4) if bias.shape[i] == 1)
    db = jnp.sum(ds, axis=reduce_axes, keepdims=True) if reduce_axes else ds
    return db.astype(bias.dtype)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, interpret, keep_prob, bwd_block_q, bwd_block_k, res, g):
    q, k, v, out, lse, bias, mask, drop_seed = res
    # single-pass backward: one exp per score instead of two (the d=64
    # kernel is VPU-softmax-bound; measured ~20% faster bwd at GPT-2
    # shapes).  Sequences whose fp32 dq exceeds a program's VMEM share
    # run the same kernel q-CHUNKED (r5); only explicit bias/mask
    # tensors still take the two-pass FA-2 kernels at those sizes
    if q.shape[2] * q.shape[3] * 4 <= _FUSED_BWD_MAX_SQ_BYTES:
        bwd = _flash_bwd_fused_pallas
    elif bias is None and mask is None:
        bwd = _flash_bwd_fused_chunked
    else:
        bwd = _flash_bwd_pallas
    dq, dk, dv = bwd(
        q, k, v, out, lse, g, causal, sm_scale,
        bwd_block_q or block_q, bwd_block_k or block_k, interpret,
        bias=bias, mask=mask, keep_prob=keep_prob, drop_seed=drop_seed,
    )
    dbias = None if bias is None else _bias_cotangent(
        q, k, v, out, lse, g, bias, mask, causal, sm_scale, keep_prob,
        drop_seed=drop_seed,
    )
    dmask = None if mask is None else jnp.zeros_like(mask)
    dseed = None if drop_seed is None else jnp.zeros_like(drop_seed)
    return dq, dk, dv, dbias, dmask, dseed


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    bias: Optional[jnp.ndarray] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    # (512, 512) measured fastest for the FULL 774M train step on v5e
    # (47.6% MFU vs 44.4% at the isolated-microbench winner (1024, 256)
    # — the micro sweep's 4.18ms/layer did not survive composition with
    # remat + the rest of the step's VMEM pressure); pick() clamps to
    # sequence divisors
    block_q: int = 512,
    block_k: int = 512,
    # backward-pass blocks (None ⇒ same as forward); the fused bwd and
    # the fwd kernel prefer different shapes at some sizes
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention over ``(batch, heads, seq, head_dim)`` inputs.

    Differentiable; forward and backward both run Pallas kernels (FA-2
    style dq/dkv backward with P recomputed from Q, K, lse).  Shapes the
    kernel grid can't serve fall back to the blockwise-rematerialized
    XLA path (large) or ``mha_reference`` (small).  ``interpret``
    defaults to True off-TPU.

    ``bias``: additive score bias broadcastable to (B, H, Tq, Tk) — e.g.
    a (B, 1, 1, Tk) padding mask.  Fully differentiable: a trainable
    bias (learned relative position) gets its exact cotangent from a
    separable O(Tq·Tk) recompute that XLA dead-code-eliminates when the
    gradient is unused (constant masks — the common case).
    ``dropout_rate`` applies attention-probability dropout
    (softmax-then-dropout, the reference's stochastic-transformer mode,
    csrc/transformer/dropout_kernels.cu).  On the kernel path the
    keep-mask is generated IN-KERNEL by a counter-based Threefry-2x32
    keyed on ``dropout_rng`` and the score element's absolute position
    — no O(Tq·Tk) HBM buffer, so long-context training keeps flash
    attention's O(T) memory with dropout on.  Non-kernel fallback paths
    materialize the identical mask host-graph-side (warned above 4k²).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    # An explicitly-passed ``interpret`` signals "exercise the kernel"
    # (the parity tests) — only the default dispatch may take the
    # short-sequence dense shortcut below.
    explicit_interpret = interpret is not None
    if interpret is None:
        interpret = not _on_tpu()
    b, h, sq, d = q.shape
    sk = k.shape[2]
    keep_prob = 1.0 - float(dropout_rate)
    drop_seed = None  # (2,) uint32 — the kernels generate keep-bits in-kernel
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        drop_seed = _seed_pair(dropout_rng)

    def host_mask4():
        """Materialized keep-mask for the non-kernel paths — the SAME
        bits the kernels would generate (one dropout stream per seed,
        whatever the dispatch)."""
        if drop_seed is None:
            return None
        if sq * sk > 4096 * 4096:
            logger.warning(
                f"attention dropout at seq {sq}x{sk} fell off the Pallas "
                f"kernel path and materializes a {b*h*sq*sk/2**30:.1f}GiB "
                "keep-mask in HBM (the kernel path generates it in-kernel "
                "at O(T) memory)"
            )
        return dropout_keep_mask_host(drop_seed, b, h, sq, sk, keep_prob).reshape(b, h, sq, sk)

    if not explicit_interpret and sq * sk <= SMALL_SEQ_DENSE_SCORES:
        return mha_dense(
            q, k, v, causal=causal, sm_scale=sm_scale, bias=bias,
            dropout_mask=host_mask4(), keep_prob=keep_prob,
        )

    def reference():
        return mha_reference(
            q, k, v, causal=causal, sm_scale=sm_scale, bias=bias,
            dropout_mask=host_mask4(), keep_prob=keep_prob,
        )

    # Caller-supplied blocks are honored when they divide the sequence;
    # otherwise halve down to 128 looking for a divisor (so e.g. seq 384
    # runs the kernel at block 128 instead of silently falling back to
    # the materializing reference path).
    def pick(n, pref):
        b_ = min(pref, n)
        if n % b_ == 0:
            return b_
        while b_ > 128:
            b_ //= 2
            if n % b_ == 0:
                return b_
        return None

    bq, bk = pick(sq, block_q), pick(sk, block_k)
    if bq is not None and bk is not None and bias is not None:
        # the full-bias BlockSpecs are (1, block_q, sk) fwd and
        # (1, sq, block_k) in the dkv pass — clamp the block sizes so
        # those auxiliary buffers stay ~2MB (VMEM is ~16MB/core and the
        # pipeline double-buffers); in-kernel dropout carries only a
        # (2,) SMEM seed, no clamp needed
        aux_bytes = 4
        while bq > 128 and bq * sk * aux_bytes > 2**21:
            bq = pick(sq, bq // 2) or 128
        while bk > 128 and bk * sq * aux_bytes > 2**21:
            bk = pick(sk, bk // 2) or 128
    if bq is None or bk is None or sq < 8 or sk < 8:
        if sq >= 8 and sk >= 8 and b * h * sq * sk * 4 > 2**28 and bias is None and drop_seed is None:
            # No kernel-compatible blocking but the (b,h,sq,sk) fp32
            # score tensor would exceed ~256MB: blockwise-rematerialized
            # XLA path (handles ragged sk by pad+mask).
            return _blockwise_xla(q, k, v, causal=causal, sm_scale=sm_scale, block_k=min(block_k, sk))
        # bias/dropout on ragged shapes: materializing scores is the only
        # correct path (the pre-kernel behavior of every caller)
        return reference()
    # VMEM guard (bytes): the fwd kernel keeps full K/V per
    # (batch,head) program resident, and the dkv backward keeps full
    # Q/dO — two operands, each DOUBLE-buffered by the pallas pipeline
    # (measured: 16k×64 bf16 wants 16.5M scoped vmem), so budget 4×
    # against the ~16MB/core limit.
    itemsize = jnp.dtype(q.dtype).itemsize
    if max(sq, sk) * d * itemsize * 4 >= 2**23:
        if bias is not None or drop_seed is not None:
            # scores must materialize beyond the kernel's VMEM envelope
            return reference()
        if sq == sk and sq % 128 == 0:
            # VMEM-bound self-attention: the splash kernel with a dense
            # layout (lower-triangular when causal, all-ones otherwise)
            # IS a kv-blocked flash — K/V stream per block through the
            # grid instead of sitting fully resident, so the VMEM bound
            # disappears.  Measured at 16k causal (B1 H12 d64, v5e):
            # fwd 56.8ms vs 63.6ms blockwise-XLA, fwd+bwd 112.3ms vs
            # 208.4ms (1.86×).  An all-ones layout carries no padding
            # penalty (every row has uniform full degree), so the
            # dense-row bucket exemption in `_dense_row_mask` keeps all
            # rows on the streaming kernel.
            from deepspeed_tpu.ops.attention.sparse import splash_attention

            blk = 256 if sq % 256 == 0 else 128
            nbq = sq // blk
            full = np.ones((h, nbq, nbq), np.uint8)
            layout = np.tril(full) if causal else full
            return splash_attention(
                q, k, v, layout, blk, causal=causal, sm_scale=sm_scale, interpret=interpret
            )
        return _blockwise_xla(q, k, v, causal=causal, sm_scale=sm_scale, block_k=bk)
    bbq = pick(sq, bwd_block_q) if bwd_block_q else None
    bbk = pick(sk, bwd_block_k) if bwd_block_k else None
    return _flash_attention(
        q, k, v, bias, None, drop_seed, causal, float(sm_scale), bq, bk,
        interpret, keep_prob, bbq, bbk,
    )


@register_op("flash_attention", "pallas", "Online-softmax fused attention, Pallas fwd + FA-2 dq/dkv bwd, bias + attention dropout")
def _load_flash_attention():
    return flash_attention
